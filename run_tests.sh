#!/bin/bash
# Canonical suite invocation for this box: GROUPED pytest processes with
# a per-file fallback.
#
# Since 2026-07-30 ~21:45 this machine's XLA CPU compiler segfaults
# probabilistically in LONG-lived processes with many compiles behind
# them (observed at different tests, with and without the axon PJRT
# plugin on PYTHONPATH, with the persistent compilation cache shared,
# fresh, and disabled — traces in SURVEY.md header). Short-lived
# processes have NEVER crashed. Rounds 5-6 ran one pytest process PER
# FILE — deterministic, but ~15 s of interpreter+jax startup per file
# put the full suite near 50 min. The crash horizon is COMPILES per
# process, not files: a half-suite shard (~240 tests) crashed while
# 6-file batches of light files never have. So the suite now runs in
# BATCHES sized well under the horizon — compile-heavy files (sharded
# runners, ADI, FBA/LP stacks) isolated or paired, light files grouped
# — and any batch that exits on a signal (segfault = 139) is re-run one
# file per process, preserving the old mode's determinism and its RC
# semantics. `python -m pytest tests/ -q` remains the honest single
# invocation to try first on a healthy box.
#
#   ./run_tests.sh            # full suite (~15-20 min on this box)
#   ./run_tests.sh --per-file # the old one-process-per-file mode
#   ./run_tests.sh --quick    # quick tier (~<10 min): core contracts
set -u
cd "$(dirname "$0")"

# Quick tier: engine/state/process contracts + the numerics the rest of
# the stack leans on (integration, tau-leap + hybrid sampler, LP ops),
# chosen for coverage-per-second, not completeness. test_cluster.py's
# quick signal is the protocol/WAL units + LocalHost routing/stealing/
# failover; its multi-process SIGKILL host-failover drills are
# slow-marked (real worker spawns cost ~a minute each) and run in the
# full tier's cluster batch.
QUICK_FILES="
tests/test_state.py
tests/test_engine.py
tests/test_utils.py
tests/test_integrate.py
tests/test_gillespie.py
tests/test_sampling.py
tests/test_expression.py
tests/test_colony.py
tests/test_serve.py
tests/test_streamer.py
tests/test_snapshots.py
tests/test_tiers.py
tests/test_faults.py
tests/test_recovery.py
tests/test_results.py
tests/test_dedup.py
tests/test_frontdoor.py
tests/test_cluster.py
tests/test_sweep.py
tests/test_metrics.py
tests/test_obs.py
"

# Full-suite batches. Grouping rationale: each line stays well under
# the measured crash horizon (~240 tests / half-suite compiles); the
# compile-heavy files (shard_map programs, ADI/SPIKE plans, FBA + LP
# solvers, experiment segments) get lines of their own or in pairs.
# New test files not named here are appended per-file automatically.
BATCHES=(
  "tests/test_state.py tests/test_engine.py tests/test_utils.py tests/test_colony.py"
  "tests/test_integrate.py tests/test_gillespie.py tests/test_sampling.py tests/test_expression.py"
  "tests/test_spatial.py tests/test_diffusion.py tests/test_chemotaxis.py tests/test_chemotaxis_lattice.py"
  "tests/test_linprog.py tests/test_ode_processes.py tests/test_data_media.py tests/test_emit_analysis.py"
  "tests/test_metabolism.py tests/test_wcecoli_minimal.py tests/test_properties.py"
  "tests/test_fba.py"
  "tests/test_pdlp.py"
  "tests/test_adi.py"
  "tests/test_parallel.py tests/test_distributed.py"
  "tests/test_multispecies.py tests/test_ensemble.py"
  "tests/test_serve.py tests/test_streamer.py tests/test_snapshots.py tests/test_tiers.py tests/test_faults.py tests/test_recovery.py tests/test_results.py tests/test_dedup.py tests/test_frontdoor.py tests/test_metrics.py tests/test_obs.py"
  "tests/test_sweep.py tests/test_cli.py"
  "tests/test_cluster.py"
  "tests/test_experiment.py"
  "tests/test_bridge.py"
)

rc=0
note_rc() {
  # exit 5 = "no tests collected" — expected under -k/-m filters when a
  # file's tests are all deselected; not a failure
  if [ "$1" -ne 0 ] && [ "$1" -ne 5 ]; then rc=$1; fi
}

run_per_file() {
  for f in $1; do
    python -m pytest "$f" -q "${@:2}"
    note_rc $?
  done
}

mode=batched
if [ "${1:-}" = "--quick" ]; then
  shift
  # the quick tier is the fast signal: slow-marked soaks stay out of it
  # (a caller's own -m overrides, since pytest takes the last -m given)
  run_per_file "$QUICK_FILES" -m "not slow" "$@"
  if [ -e tests/test_mesh_serve.py ]; then
    # mesh serving batch (simulated devices; see MESH_FILES below)
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python -m pytest tests/test_mesh_serve.py -q -m "not slow" "$@"
    note_rc $?
  fi
  exit $rc
elif [ "${1:-}" = "--per-file" ]; then
  shift
  mode=perfile
fi

if [ "$mode" = "perfile" ]; then
  run_per_file "$(echo tests/test_*.py)" "$@"
  exit $rc
fi

# Mesh batch: the multi-device serving tests run in their own process
# with the device-count flag explicit. (tests/conftest.py already
# forces 8 simulated host devices for the whole suite, so this is
# belt-and-suspenders for running the file OUTSIDE pytest-with-
# conftest contexts; the subprocess drills inside set their own env.)
# Kept out of the grouped batches so the compile-heavy 4-device
# servers do not ride a shared process near the crash horizon.
MESH_FILES="tests/test_mesh_serve.py"

# files not named in any batch (newly added) run per-file at the end
assigned=" ${BATCHES[*]} $MESH_FILES "
leftovers=""
for f in tests/test_*.py; do
  case "$assigned" in
    *" $f "*) ;;
    *) leftovers="$leftovers $f" ;;
  esac
done

for batch in "${BATCHES[@]}"; do
  # skip batch members that don't exist (renamed/removed files)
  files=""
  for f in $batch; do [ -e "$f" ] && files="$files $f"; done
  [ -z "$files" ] && continue
  python -m pytest $files -q "$@"
  batch_rc=$?
  if [ "$batch_rc" -ge 128 ]; then
    # the process died on a signal (the known compiler segfault):
    # fall back to one process per file for THIS batch only
    echo "run_tests.sh: batch crashed (rc=$batch_rc); re-running per-file:$files" >&2
    run_per_file "$files" "$@"
  else
    note_rc $batch_rc
  fi
done

if [ -n "$leftovers" ]; then
  run_per_file "$leftovers" "$@"
fi

# mesh batch: 8 simulated CPU devices (the exhaustive kill-one-device
# sweep inside is slow-marked, so `-m "not slow"` callers skip it)
for f in $MESH_FILES; do
  if [ -e "$f" ]; then
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python -m pytest "$f" -q "$@"
    note_rc $?
  fi
done
exit $rc
