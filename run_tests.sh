#!/bin/bash
# Canonical suite invocation for this box: ONE pytest process PER FILE.
#
# Since 2026-07-30 ~21:45 this machine's XLA CPU compiler segfaults
# probabilistically in LONG-lived processes with many compiles behind
# them (observed at different tests, with and without the axon PJRT
# plugin on PYTHONPATH, with the persistent compilation cache shared,
# fresh, and disabled — traces in SURVEY.md header). Short-lived
# processes have NEVER crashed. Two half-suite shards were enough
# through round 4 (~370 tests); by round 5 the suite grew past the
# crash horizon even in quarter shards (crashes at ~240 tests in a
# half-shard and again inside a 6-file quarter shard, 2026-07-31), so
# each file now runs alone — interpreter startup ~15 s/file is the
# price of determinism here. `python -m pytest tests/ -q` remains the
# honest single invocation to try first on a healthy box.
#
#   ./run_tests.sh            # full suite (~50 min on this box)
#   ./run_tests.sh --quick    # quick tier (~<10 min): the core-contract
#                             # files below, still one process per file.
#                             # The verification loop between edits; the
#                             # full suite remains the merge gate.
set -u
cd "$(dirname "$0")"

# Quick tier: engine/state/process contracts + the numerics the rest of
# the stack leans on (integration, tau-leap + hybrid sampler, LP ops),
# chosen for coverage-per-second, not completeness.
QUICK_FILES="
tests/test_state.py
tests/test_engine.py
tests/test_utils.py
tests/test_integrate.py
tests/test_gillespie.py
tests/test_sampling.py
tests/test_expression.py
tests/test_colony.py
"

files="tests/test_*.py"
if [ "${1:-}" = "--quick" ]; then
  shift
  files=$QUICK_FILES
fi

rc=0
for f in $files; do
  python -m pytest "$f" -q "$@"
  rc2=$?
  # exit 5 = "no tests collected" — expected under -k/-m filters when a
  # file's tests are all deselected; not a failure
  if [ "$rc2" -ne 0 ] && [ "$rc2" -ne 5 ]; then rc=$rc2; fi
done
exit $rc
