"""Dense-IPM vs first-order-PDLP crossover sweep (VERDICT r4 task 4).

The dense Mehrotra IPM (``ops.linprog``) pays O(M^2 R + M^3/3) per
iteration per agent — the wall on the road to wcEcoli-class networks
(SURVEY.md §2 "wcEcoli bridge"). The PDLP solver (``ops.pdlp``) pays
O(M R) matvecs. This bench records where the crossover actually is, on
the packaged networks and on block-diagonal tilings of the full
e_coli_core (k disjoint copies: a controlled synthetic scale-up whose
optimum is exactly k x the single-network optimum — a built-in oracle).

Per (network, batch) it measures, at the SAME tol (1e-4, the FBA process
default):

- cold solves/s for both solvers;
- warm-started solves/s for both (re-solve after a 5% bounds drift —
  the temporal-coherence regime every simulation step actually runs in);
- mean iterations, convergence fraction, and objective agreement with
  the tiling oracle (and so transitively with HiGHS, which pins the
  single-network optimum in tests/test_fba.py).

Writes BENCH_LP_SCALE.json and prints one JSON line per row. CPU-safe;
the TPU half runs from the on-device queue. The expected picture: dense
wins at reference scale (72x180 — small matrices, ~10 Newton steps);
PDLP overtakes as k grows (its FLOPs scale k^2 vs the IPM's k^3) and on
the MXU (batched [N,R]@[R,M] matmuls vs batched small Cholesky).
"""

import json
import time

import numpy as np

from lens_tpu.utils.platform import guard_accelerator_or_exit


def tiled_problem(k: int):
    """k disjoint copies of the leak-relaxed full e_coli_core LP."""
    import jax.numpy as jnp

    from lens_tpu.processes.fba_metabolism import FBAMetabolism

    leak = 1.5e-3
    p = FBAMetabolism({"network": "ecoli_core_full"})
    base = {"glc": 10.0, "o2": 50.0, "nh4": 50.0, "ace": 2.0}
    env = jnp.asarray(
        [base.get(mol, 0.0) for mol in p.external], jnp.float32
    )
    lb1, ub1 = p.regulated_bounds(env, 1.0)
    S1 = np.asarray(p.stoichiometry)
    m1, _ = S1.shape
    S1 = np.concatenate([S1, np.eye(m1, dtype=S1.dtype)], axis=1)
    c1 = np.concatenate([-np.asarray(p.objective), np.zeros(m1, np.float32)])
    lb1 = np.concatenate([np.asarray(lb1), np.full(m1, -leak, np.float32)])
    ub1 = np.concatenate([np.asarray(ub1), np.full(m1, leak, np.float32)])

    m, r = S1.shape
    S = np.zeros((k * m, k * r), np.float32)
    for i in range(k):
        S[i * m : (i + 1) * m, i * r : (i + 1) * r] = S1
    return (
        S,
        np.tile(c1, k),
        np.tile(lb1, k),
        np.tile(ub1, k),
        k,  # oracle: objective = k * single-network optimum
    )


def measure(step, args, n_rep):
    import jax

    out = step(*args)
    jax.block_until_ready(out.x)  # warm-up: compile
    t0 = time.perf_counter()
    for _ in range(n_rep):
        out = step(*args)
    jax.block_until_ready(out.x)
    dt = (time.perf_counter() - t0) / n_rep
    return out, dt


def main():
    guard_accelerator_or_exit()
    import jax
    import jax.numpy as jnp

    from lens_tpu.ops.linprog import linprog_box
    from lens_tpu.ops.pdlp import pdlp_box

    backend = jax.default_backend()
    rows = []
    # k = 1 is the real full network; k >= 2 are the synthetic tilings.
    # Two tolerance passes: 1e-4 (the FBA process default — PDLP carries
    # a ~3.7% objective bias there, visible in oracle_rel_err) and 1e-5
    # (equal answer quality, the apples-to-apples crossover; dense PDLP
    # is dominated by sparse and skipped to bound the run).
    cases = [(1e-4, 1, 256), (1e-4, 2, 256), (1e-4, 4, 64), (1e-4, 8, 16),
             (1e-4, 16, 4),
             (1e-5, 1, 256), (1e-5, 2, 256), (1e-5, 4, 64), (1e-5, 8, 16)]
    single_opt = None
    for tol, k, batch in cases:
        S, c, lb, ub, _ = tiled_problem(k)
        m, r = S.shape
        Sj, cj, bj = jnp.asarray(S), jnp.asarray(c), jnp.zeros(m, jnp.float32)
        rng = np.random.default_rng(0)
        # per-lane box scale (the batched-agents regime)
        scale = jnp.asarray(
            rng.uniform(0.85, 1.15, size=(batch, 1)).astype(np.float32)
        )
        lbs = jnp.asarray(lb)[None, :] * scale
        ubs = jnp.asarray(ub)[None, :] * scale
        drift = 0.95  # warm-start regime: re-solve after a bounds drift

        solvers = {
            "ipm": {
                "cold": jax.jit(jax.vmap(
                    lambda l, u: linprog_box(
                        cj, Sj, bj, l, u, n_iter=45, tol=tol
                    )
                )),
                "warm": jax.jit(jax.vmap(
                    lambda l, u, w: linprog_box(
                        cj, Sj, bj, l, u, n_iter=45, tol=tol, warm=w
                    )
                )),
            },
            "pdlp_dense": {
                "cold": jax.jit(jax.vmap(
                    lambda l, u: pdlp_box(
                        cj, Sj, bj, l, u, n_iter=65536, tol=tol,
                        sparse=False,
                    )
                )),
                "warm": jax.jit(jax.vmap(
                    lambda l, u, w: pdlp_box(
                        cj, Sj, bj, l, u, n_iter=65536, tol=tol, warm=w,
                        sparse=False,
                    )
                )),
            },
            "pdlp_sparse": {
                "cold": jax.jit(jax.vmap(
                    lambda l, u: pdlp_box(
                        cj, Sj, bj, l, u, n_iter=65536, tol=tol,
                        sparse=True,
                    )
                )),
                "warm": jax.jit(jax.vmap(
                    lambda l, u, w: pdlp_box(
                        cj, Sj, bj, l, u, n_iter=65536, tol=tol, warm=w,
                        sparse=True,
                    )
                )),
            },
        }
        if tol < 1e-4 or k > 8:
            # dense PDLP is dominated by sparse everywhere measured;
            # at k=16 its O(M R) matvecs alone would run tens of minutes
            solvers.pop("pdlp_dense")
        n_rep = 3 if k <= 2 else 1
        for solver, fns in solvers.items():
            cold, dt_cold = measure(fns["cold"], (lbs, ubs), n_rep)
            warm_arg = cold.warm
            warm, dt_warm = measure(
                fns["warm"], (lbs * drift, ubs * drift, warm_arg), n_rep
            )
            # normalize by THIS case's own batch-scale mean: box scales
            # are per-lane uniform draws, so the mean objective tracks
            # mean(scale) — dividing it out keeps oracle_rel_err a
            # solver-accuracy number, not batch-sampling noise
            mean_scale = float(np.asarray(scale).mean())
            obj = float(np.asarray(cold.objective).mean())
            if k == 1 and solver == "ipm":
                single_opt = obj / mean_scale
            row = {
                "solver": solver,
                "k": k,
                "m": m,
                "r": r,
                "batch": batch,
                "tol": tol,
                "cold_solves_per_s": batch / dt_cold,
                "warm_solves_per_s": batch / dt_warm,
                "cold_iters_mean": float(
                    np.asarray(cold.iterations, np.float64).mean()
                ),
                "warm_iters_mean": float(
                    np.asarray(warm.iterations, np.float64).mean()
                ),
                "cold_converged_frac": float(
                    np.asarray(cold.converged).mean()
                ),
                "warm_converged_frac": float(
                    np.asarray(warm.converged).mean()
                ),
                "objective_mean": obj,
                # tiling oracle: scale-normalized mean objective ==
                # k * single-net optimum (exact for separable tilings)
                "oracle_rel_err": (
                    abs(obj / mean_scale / (k * single_opt) - 1.0)
                    if single_opt
                    else None
                ),
            }
            rows.append(row)
            print(json.dumps({
                kk: (round(v, 6) if isinstance(v, float) else v)
                for kk, v in row.items()
            }), flush=True)

    out = {
        "backend": backend,
        "note": (
            "k-fold block-diagonal tilings of the leak-relaxed full "
            "e_coli_core (72x180 -> k copies). oracle_rel_err compares "
            "the mean batch objective against k * the single-network "
            "optimum (exact for separable tilings; batch box scales "
            "average out). Warm rows re-solve after a 5% bounds drift "
            "seeded by the cold solution — the per-step FBA regime."
        ),
        "rows": rows,
    }
    with open("BENCH_LP_SCALE.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
