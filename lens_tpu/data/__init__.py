"""The knowledge-base/data layer: flat files + loaders.

The reference keeps its parameters in TSV/JSON flat files under
``lens/data/`` with small loader utilities ("JsonReader"-style), feeding
media recipes and kinetic parameters into processes and the environment
(reconstructed: SURVEY.md §1 L1, §2 "Data layer" — mount empty, see
SURVEY header). The rebuild keeps that split: data is plain files next to
this module, loaders return plain dicts/lists, and processes receive them
through ordinary config — nothing here touches jax.

TSV convention: first row is the header; ``#`` lines are comments; cells
parse as float when possible, else stay strings; a ``null`` cell parses
as None. JSON is loaded verbatim.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

_DATA_DIR = os.path.dirname(os.path.abspath(__file__))


def data_path(name: str) -> str:
    """Absolute path of a packaged data file."""
    return os.path.join(_DATA_DIR, name)


def load_json(name: str) -> Any:
    """Load a packaged JSON file (or an absolute path)."""
    path = name if os.path.isabs(name) else data_path(name)
    with open(path) as f:
        return json.load(f)


def _parse_cell(cell: str) -> Any:
    cell = cell.strip()
    if cell == "null" or cell == "":
        return None
    try:
        return float(cell)
    except ValueError:
        return cell


def load_tsv(name: str) -> List[Dict[str, Any]]:
    """Load a packaged TSV file as a list of row dicts keyed by header."""
    path = name if os.path.isabs(name) else data_path(name)
    rows: List[Dict[str, Any]] = []
    header: List[str] | None = None
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            cells = line.split("\t")
            if header is None:
                header = [c.strip() for c in cells]
                continue
            rows.append({h: _parse_cell(c) for h, c in zip(header, cells)})
    if header is None:
        raise ValueError(f"TSV file {path} has no header row")
    return rows


def load_table(name: str, key: str, value: str) -> Dict[Any, Any]:
    """Collapse a TSV into a {row[key]: row[value]} mapping."""
    return {row[key]: row[value] for row in load_tsv(name)}


def _parse_terms(cell: Any) -> Dict[str, float]:
    """Parse a `species:coeff species:coeff` cell into a dict."""
    if cell is None:
        return {}
    out: Dict[str, float] = {}
    for term in str(cell).split():
        name, _, coeff = term.rpartition(":")
        if not name:
            raise ValueError(f"malformed stoichiometry term {term!r}")
        out[name] = float(coeff)
    return out


def load_rfba_network(prefix: str = "ecoli_core") -> Dict[str, Any]:
    """Load a regulated-FBA network from ``{prefix}_species.tsv`` +
    ``{prefix}_reactions.tsv`` into the network-dict format
    :class:`~lens_tpu.processes.fba_metabolism.FBAMetabolism` consumes.

    This is the data-layer path for reference-scale metabolism (SURVEY.md
    §2 "Data layer": reaction stoichiometries as flat files + loaders;
    "Metabolism": Covert–Palsson 2002 lineage): the network is *content*,
    not code — editing the TSV changes the model without touching any
    Python. The species file fixes ordering (internal = steady-state LP
    rows, external = lattice-coupled fields); each reaction row carries
    stoichiometry, bounds, exchange coupling with Michaelis–Menten ``km``,
    and a boolean regulation rule over external species.
    """
    internal: list = []
    external: list = []
    for row in load_tsv(f"{prefix}_species.tsv"):
        kind = row.get("type")
        if kind == "internal":
            internal.append(row["species"])
        elif kind == "external":
            external.append(row["species"])
        else:
            raise ValueError(
                f"species {row.get('species')!r}: type must be "
                f"'internal' or 'external', got {kind!r}"
            )
    reactions: Dict[str, dict] = {}
    objective = None
    for row in load_tsv(f"{prefix}_reactions.tsv"):
        name = row["reaction"]
        stoich = _parse_terms(row.get("stoichiometry"))
        bad = [s for s in stoich if s not in internal]
        if bad:
            raise ValueError(
                f"reaction {name!r}: stoichiometry names non-internal "
                f"species {bad}"
            )
        exchanges = _parse_terms(row.get("exchanges"))
        bad = [s for s in exchanges if s not in external]
        if bad:
            raise ValueError(
                f"reaction {name!r}: exchanges names non-external "
                f"species {bad}"
            )
        reactions[name] = {
            "stoich": stoich,
            "bounds": (float(row["lb"]), float(row["ub"])),
            "exchanges": exchanges,
            # blank km cell -> the process default (0.5); an explicit 0
            # in the TSV disables MM saturation for that import
            "km": 0.5 if row.get("km") is None else float(row["km"]),
            "rule": str(row["rule"]) if row.get("rule") else "",
        }
        if row.get("objective"):
            if objective is not None:
                raise ValueError(
                    f"two objective reactions: {objective!r} and {name!r}"
                )
            objective = name
    if objective is None:
        raise ValueError(f"{prefix}: no reaction has objective=1")
    return {
        "internal": internal,
        "external": external,
        "reactions": reactions,
        "objective": objective,
    }
