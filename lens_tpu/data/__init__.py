"""The knowledge-base/data layer: flat files + loaders.

The reference keeps its parameters in TSV/JSON flat files under
``lens/data/`` with small loader utilities ("JsonReader"-style), feeding
media recipes and kinetic parameters into processes and the environment
(reconstructed: SURVEY.md §1 L1, §2 "Data layer" — mount empty, see
SURVEY header). The rebuild keeps that split: data is plain files next to
this module, loaders return plain dicts/lists, and processes receive them
through ordinary config — nothing here touches jax.

TSV convention: first row is the header; ``#`` lines are comments; cells
parse as float when possible, else stay strings; a ``null`` cell parses
as None. JSON is loaded verbatim.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

_DATA_DIR = os.path.dirname(os.path.abspath(__file__))


def data_path(name: str) -> str:
    """Absolute path of a packaged data file."""
    return os.path.join(_DATA_DIR, name)


def load_json(name: str) -> Any:
    """Load a packaged JSON file (or an absolute path)."""
    path = name if os.path.isabs(name) else data_path(name)
    with open(path) as f:
        return json.load(f)


def _parse_cell(cell: str) -> Any:
    cell = cell.strip()
    if cell == "null" or cell == "":
        return None
    try:
        return float(cell)
    except ValueError:
        return cell


def load_tsv(name: str) -> List[Dict[str, Any]]:
    """Load a packaged TSV file as a list of row dicts keyed by header."""
    path = name if os.path.isabs(name) else data_path(name)
    rows: List[Dict[str, Any]] = []
    header: List[str] | None = None
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line.strip() or line.lstrip().startswith("#"):
                continue
            cells = line.split("\t")
            if header is None:
                header = [c.strip() for c in cells]
                continue
            rows.append({h: _parse_cell(c) for h, c in zip(header, cells)})
    if header is None:
        raise ValueError(f"TSV file {path} has no header row")
    return rows


def load_table(name: str, key: str, value: str) -> Dict[Any, Any]:
    """Collapse a TSV into a {row[key]: row[value]} mapping."""
    return {row[key]: row[value] for row in load_tsv(name)}
