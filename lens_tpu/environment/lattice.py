"""The spatial environment: molecular fields on a 2D lattice.

The reference's outer agent owns a 2D diffusion lattice of molecular
fields ``[molecule, x, y]`` with per-window diffusion, exchange-flux
application, and media changes (reconstructed:
``EnvironmentSpatialLattice`` in ``lens/environment/lattice.py``,
SURVEY.md §2 — path corroborated by BASELINE.json). The rebuild keeps the
same responsibilities but as a pure function library over a ``[M, H, W]``
array co-resident with agent state in HBM; the "outer agent" as a concurrent
process disappears (SURVEY.md §2 parallelism table).

Units: fields hold concentrations (mM). A cell occupying a bin exchanges
amounts; ``counts_to_conc = 1 / (bin_volume * N_A)``-style factors are
collapsed into a single configurable ``exchange_scale``.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from lens_tpu.ops.diffusion import diffuse, stable_substeps


def masked_exchange_contrib(
    exchange: jnp.ndarray, alive: jnp.ndarray, exchange_scale: float
) -> jnp.ndarray:
    """The [M, N] exchange payload masked by liveness and scaled to
    concentration units — the ONE authoritative copy of this expression
    (same association as the reference path's
    ``exchange * alive[:, None] * scale``): the unsharded flat apply and
    both sharded fused blocks all call it, so a future scaling change
    cannot land in one copy and break the bitwise parity contracts."""
    return exchange * alive.astype(exchange.dtype)[None, :] * exchange_scale


class Lattice:
    """Static configuration + pure field-update functions.

    Parameters
    ----------
    molecules: ordered molecule names; index = channel in the field array.
    shape: (H, W) bins.
    size: physical edge lengths (h, w) in um; dx = size/shape (square bins).
    diffusion: per-molecule diffusion coefficient (um^2/s), dict or scalar.
    initial: per-molecule initial concentration (uniform), dict or scalar.
    exchange_scale: concentration change per unit of agent exchange flux
        landing in one bin (collapses bin volume/Avogadro bookkeeping).
    """

    def __init__(
        self,
        molecules: Sequence[str],
        shape: Tuple[int, int] = (256, 256),
        size: Tuple[float, float] | None = None,
        diffusion: Dict[str, float] | float = 600.0,
        initial: Dict[str, float] | float = 10.0,
        exchange_scale: float = 1.0,
        timestep: float = 1.0,
        impl: str = "auto",
    ):
        self.molecules = list(molecules)
        self.shape = tuple(shape)
        self.size = tuple(size) if size is not None else (float(shape[0]), float(shape[1]))
        self.dx = self.size[0] / self.shape[0]
        if abs(self.size[1] / self.shape[1] - self.dx) > 1e-9:
            raise ValueError("bins must be square (size/shape equal per axis)")
        if isinstance(diffusion, dict):
            self.diffusion = jnp.asarray(
                [float(diffusion[m]) for m in self.molecules], jnp.float32
            )
        else:
            self.diffusion = jnp.full((len(self.molecules),), float(diffusion), jnp.float32)
        if isinstance(initial, dict):
            self._initial = [float(initial[m]) for m in self.molecules]
        else:
            self._initial = [float(initial)] * len(self.molecules)
        self.exchange_scale = float(exchange_scale)
        self.timestep = float(timestep)
        self.impl = impl
        d_max = float(jnp.max(self.diffusion)) if self.molecules else 0.0
        self.n_substeps = stable_substeps(d_max, self.timestep, self.dx)
        self.alpha = self.diffusion * (self.timestep / self.n_substeps) / (self.dx * self.dx)
        self._adi = None  # lazily built ADIPlan (impl == "adi")

    # -- construction --------------------------------------------------------

    @property
    def alpha_window(self):
        """Whole-window ``D*dt/dx^2`` per molecule (float64 numpy) — the
        ONE derivation both the local ADI plan and the sharded SPIKE plan
        factor from (so they describe the identical matrix)."""
        import numpy as np

        return (
            np.asarray(self.diffusion, np.float64)
            * self.timestep
            / (self.dx * self.dx)
        )

    def initial_fields(self) -> jnp.ndarray:
        h, w = self.shape
        return jnp.stack(
            [jnp.full((h, w), c, jnp.float32) for c in self._initial]
        )

    def index(self, molecule: str) -> int:
        return self.molecules.index(molecule)

    # -- pure field ops ------------------------------------------------------

    def step_fields(self, fields: jnp.ndarray) -> jnp.ndarray:
        """One environment timestep of diffusion (all substeps).

        ``impl="adi"`` swaps the substepped FTCS stencil for one
        unconditionally stable backward-Euler-split step (ops.adi): two
        tridiagonal solves instead of ``n_substeps`` stencil sweeps,
        positivity-preserving under secretion spikes, at a first-order
        splitting-accuracy cost the nutrient fields don't notice (tests
        pin it against the dense-substep oracle).

        Sharded runs (parallel.runner) honor ``impl="adi"`` through the
        SPIKE distributed tridiagonal solve (parallel.adi_spike — one
        boundary exchange per window); every other ``impl`` value routes
        the sharded path to its own ppermute-halo FTCS.
        """
        if self.impl == "adi":
            if self._adi is None:
                from lens_tpu.ops.adi import adi_plan

                self._adi = adi_plan(self.alpha_window, *self.shape)
            from lens_tpu.ops.adi import diffuse_adi

            return diffuse_adi(fields, self._adi)
        return diffuse(fields, self.alpha, self.n_substeps, impl=self.impl)

    def bin_of(self, locations: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Map continuous agent locations [N, 2] (um) to bin indices."""
        ij = jnp.floor(locations / self.dx).astype(jnp.int32)
        i = jnp.clip(ij[:, 0], 0, self.shape[0] - 1)
        j = jnp.clip(ij[:, 1], 0, self.shape[1] - 1)
        return i, j

    @property
    def n_bins(self) -> int:
        return self.shape[0] * self.shape[1]

    def flat_bin_of(self, locations: jnp.ndarray) -> jnp.ndarray:
        """Row-major flat bin index [N] (int32) — ``i * W + j`` of
        :meth:`bin_of`, exactly (integer composition, so the fused
        coupling path that computes this ONCE per step sees the same
        bins the reference path derives three times over).
        """
        i, j = self.bin_of(locations)
        return i * self.shape[1] + j

    def occupancy_flat(
        self, flat: jnp.ndarray, alive: jnp.ndarray
    ) -> jnp.ndarray:
        """Live-agent count per flat bin: [H*W] (float32).

        The flat-index counterpart of :meth:`occupancy`, built on the
        coupling scatter primitive (ops.scatter) so the fused step's
        occupancy count shares both the precomputed ``flat`` index and
        the fast scatter path with the exchange application. Bitwise
        equal to ``occupancy(...).reshape(-1)``.
        """
        from lens_tpu.ops.scatter import scatter_add_2d

        base = jnp.zeros((1, self.n_bins), jnp.float32)
        return scatter_add_2d(
            base, flat, alive.astype(jnp.float32)[None, :]
        )[0]

    def apply_exchanges_flat(
        self,
        fields_flat: jnp.ndarray,
        flat: jnp.ndarray,
        exchange: jnp.ndarray,
        alive: jnp.ndarray,
    ) -> jnp.ndarray:
        """Flat-index counterpart of :meth:`apply_exchanges`.

        fields_flat: [M, H*W]; exchange: [M, N] (channel-major, unlike
        the reference path's [N, M] — the scatter consumes channel rows
        directly, so the fused path never materializes the transpose).
        Returns the updated [M, H*W] (same ``>= 0`` clamp and mask
        semantics as the reference; bitwise equal to it on CPU).
        """
        from lens_tpu.ops.scatter import scatter_add_2d

        contrib = masked_exchange_contrib(
            exchange, alive, self.exchange_scale
        )
        return jnp.maximum(scatter_add_2d(fields_flat, flat, contrib), 0.0)

    def occupancy(
        self, locations: jnp.ndarray, alive: jnp.ndarray
    ) -> jnp.ndarray:
        """Live-agent count per bin: [H, W]."""
        i, j = self.bin_of(locations)
        return (
            jnp.zeros(self.shape, jnp.float32)
            .at[i, j]
            .add(alive.astype(jnp.float32))
        )

    def local_concentrations(
        self,
        fields: jnp.ndarray,
        locations: jnp.ndarray,
        alive: jnp.ndarray | None = None,
        share_bins: bool = True,
        occupancy: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        """Gather each agent's local concentration: [N, M].

        This IS the reference's outer->inner ENVIRONMENT_UPDATE message
        (SURVEY.md §3.2), reduced to one gather.

        With ``share_bins`` (default), co-located agents see the bin
        concentration divided by the bin's live occupancy AND by
        ``exchange_scale``. Since a transport process can take up at most
        what it sees, and the scatter multiplies fluxes back by
        ``exchange_scale``, collective uptake then never exceeds the bin
        content — exact mass conservation, where the reference's
        end-of-window flux application could overdraw a shared site.
        """
        i, j = self.bin_of(locations)
        local = fields[:, i, j].T
        if share_bins:
            if occupancy is None:
                if alive is None:
                    raise ValueError("share_bins needs the alive mask")
                occupancy = self.occupancy(locations, alive)
            # ``occupancy`` may be passed precomputed so callers stepping
            # SEVERAL agent populations against one lattice (multi-species)
            # can share bins across all of them, not just within one.
            occ = occupancy[i, j]
            local = local / (
                jnp.maximum(occ, 1.0)[:, None] * self.exchange_scale
            )
        return local

    def apply_exchanges(
        self,
        fields: jnp.ndarray,
        locations: jnp.ndarray,
        exchange: jnp.ndarray,
        alive: jnp.ndarray,
    ) -> jnp.ndarray:
        """Scatter-add agent uptake(-)/secretion(+) into their bins.

        exchange: [N, M] net flux for the window (positive = secreted into
        the environment). The inner->outer CELL_UPDATE message as one
        scatter. Dead rows are masked out.

        Conservation caveat: the final ``>= 0`` clamp floors overdrawn
        bins, which CREATES mass (agents already banked their uptake).
        Overdraw is impossible when gathers use ``share_bins=True`` (each
        co-located agent sees only its share, and transport caps uptake
        at what it sees); with ``share_bins=False`` co-located agents can
        collectively overdraw, so conservation checks only hold in the
        shared-bin configuration.
        """
        i, j = self.bin_of(locations)
        contrib = exchange * alive[:, None] * self.exchange_scale
        updated = fields.at[:, i, j].add(contrib.T)
        return jnp.maximum(updated, 0.0)
