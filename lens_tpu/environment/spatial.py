"""SpatialColony: colony + lattice coupled through pure index ops.

This module is the rebuild of the reference's whole outer/inner exchange
machinery (SURVEY.md §3.2): where the reference's outer agent broadcasts
local concentrations over Kafka, waits on a barrier for every inner
agent's exchange fluxes, then applies them to the lattice, here one pure
``step`` does, in order:

1. **gather**   — each agent's ``external`` port variables are overwritten
   with its bin's concentrations (ENVIRONMENT_UPDATE as one gather);
2. **biology**  — one vmapped colony step (all Processes + division);
3. **scatter**  — each agent's ``exchange`` accumulators are added into
   its bin and zeroed (CELL_UPDATE as one scatter-add);
4. **fields**   — diffusion substeps advance the lattice.

The barrier is implicit: step 3 happens after step 2 for every agent by
construction. No broker, no messages, no waiting.
"""

from __future__ import annotations

from typing import Dict, Mapping, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from lens_tpu.colony.colony import Colony, ColonyState, _bcast
from lens_tpu.core.schedule import scan_schedule
from lens_tpu.core.topology import Path, normalize_path
from lens_tpu.environment.lattice import Lattice
from lens_tpu.utils.dicts import get_path, set_path


class SpatialState(NamedTuple):
    colony: ColonyState
    fields: jax.Array  # [M, H, W]


class FieldPort(NamedTuple):
    """Wiring of one lattice molecule into the agent state tree.

    ``exchange`` may be ``None`` for sense-only coupling (e.g. a
    chemoreceptor reading an attractant it does not consume): the gather
    still runs, the scatter is skipped.
    """

    local: Path               # agent path overwritten with the bin concentration
    exchange: Optional[Path]  # agent path accumulating net secretion (or None)


class SpatialColony:
    """A Colony embedded in a Lattice.

    field_ports: molecule name -> FieldPort (or (local, exchange) tuple).
    location_path: agent path of the [2] position leaf (um).
    """

    def __init__(
        self,
        colony: Colony,
        lattice: Lattice,
        field_ports: Mapping[str, FieldPort | Tuple],
        location_path: Path | str = ("boundary", "location"),
        share_bins: bool = True,
    ):
        self.colony = colony
        self.lattice = lattice
        self.share_bins = bool(share_bins)
        self.location_path = normalize_path(location_path)
        self.field_ports: Dict[str, FieldPort] = {}
        known = colony.compartment.updaters
        if self.location_path not in known:
            raise ValueError(f"location path {self.location_path} not in schema")
        for mol, port in field_ports.items():
            if mol not in lattice.molecules:
                raise ValueError(f"molecule {mol!r} not on the lattice")
            local, exchange = port[0], port[1]
            port = FieldPort(
                normalize_path(local),
                normalize_path(exchange) if exchange is not None else None,
            )
            for path in port:
                if path is not None and path not in known:
                    raise ValueError(f"field port path {path} not in schema")
            self.field_ports[mol] = port

    # -- construction --------------------------------------------------------

    def with_colony(self, colony: Colony) -> "SpatialColony":
        """Rewrap a (typically capacity-grown) colony with this
        SpatialColony's lattice and wiring — the ONE place the
        constructor-argument set is repeated, so expansion/resume paths
        cannot silently drop a newly added parameter."""
        return SpatialColony(
            colony,
            self.lattice,
            self.field_ports,
            location_path=self.location_path,
            share_bins=self.share_bins,
        )

    def expanded(
        self, ss: SpatialState, factor: int = 2
    ) -> Tuple["SpatialColony", SpatialState]:
        """Capacity growth for the embedded colony (host-side, segment
        boundary): see :meth:`lens_tpu.colony.colony.Colony.expanded`.
        The lattice and fields are untouched — only the agent rows grow
        (padded rows are dead, parked at location 0 like every dead
        row)."""
        grown, cs = self.colony.expanded(ss.colony, factor)
        return self.with_colony(grown), ss._replace(colony=cs)

    def initial_state(
        self,
        n_alive: int,
        key: jax.Array,
        overrides: Mapping | None = None,
        locations: jax.Array | None = None,
    ) -> SpatialState:
        """Colony rows + uniform fields. Locations default to uniform random
        placement over the domain (live rows only; dead rows parked at 0)."""
        cs = self.colony.initial_state(n_alive, overrides=overrides, key=key)
        if locations is not None:
            locations = jnp.asarray(locations)
            expected = (self.colony.capacity, 2)
            if locations.shape != expected:
                raise ValueError(
                    f"locations has shape {locations.shape}, expected "
                    f"{expected} (rows for ALL capacity slots, not just "
                    f"n_alive; dead rows' values are ignored)"
                )
        if locations is None:
            lkey = jax.random.fold_in(key, 0x10C)
            h, w = self.lattice.size
            locations = jax.random.uniform(
                lkey,
                (self.colony.capacity, 2),
                minval=jnp.zeros(2),
                maxval=jnp.asarray([h, w]),
            )
        agents = set_path(
            cs.agents,
            self.location_path,
            jnp.asarray(locations, jnp.float32),
        )
        cs = cs._replace(agents=agents)
        return SpatialState(colony=cs, fields=self.lattice.initial_fields())

    # -- stepping ------------------------------------------------------------

    def step(self, ss: SpatialState, timestep: float) -> SpatialState:
        if abs(timestep - self.lattice.timestep) > 1e-9:
            raise ValueError(
                f"timestep={timestep} != lattice.timestep="
                f"{self.lattice.timestep}: the lattice precomputes its "
                f"diffusion substeps for its own timestep — construct the "
                f"Lattice with the timestep you run at"
            )
        cs, fields = ss
        locations = get_path(cs.agents, self.location_path)

        # 1. gather: overwrite each agent's local-env variables. Consuming
        # ports see the bin-SHARED concentration (co-located agents split
        # the bin, so uptake cannot overdraw it); sense-only ports
        # (exchange=None) see the RAW bin value — they never debit the
        # bin, so sharing would just distort sensing with occupancy.
        local_shared = self.lattice.local_concentrations(
            fields, locations, cs.alive, share_bins=self.share_bins
        )  # [N, M]
        local_raw = (
            self.lattice.local_concentrations(
                fields, locations, cs.alive, share_bins=False
            )
            if any(p.exchange is None for p in self.field_ports.values())
            else local_shared
        )
        agents = cs.agents
        for mol, port in self.field_ports.items():
            local = local_raw if port.exchange is None else local_shared
            col = local[:, self.lattice.index(mol)]
            prev = get_path(agents, port.local)
            # dead rows keep their previous value (mask hygiene)
            agents = set_path(
                agents, port.local, jnp.where(cs.alive, col, prev)
            )
        cs = cs._replace(agents=agents)

        # 2. biology — processes only; division is deferred until the
        # exchange is applied (its dividers zero the accumulators)
        cs = self.colony.step_biology(cs, timestep)

        # 3. scatter: debit/credit the PRE-STEP bins — the bins whose
        # concentrations the transport processes actually saw. (Motility
        # may have moved the agent this step; debiting the new bin could
        # overdraw it, and the >=0 clamp would then create mass.)
        agents = cs.agents
        exchange = jnp.stack(
            [
                get_path(agents, self.field_ports[mol].exchange)
                if mol in self.field_ports
                and self.field_ports[mol].exchange is not None
                else jnp.zeros(self.colony.capacity)
                for mol in self.lattice.molecules
            ],
            axis=1,
        )  # [N, M]
        fields = self.lattice.apply_exchanges(
            fields, locations, exchange, cs.alive
        )
        for mol, port in self.field_ports.items():
            if port.exchange is None:
                continue
            agents = set_path(
                agents,
                port.exchange,
                jnp.zeros_like(get_path(agents, port.exchange)),
            )
        cs = cs._replace(agents=agents)

        # 4. division (row activation) now that accumulators are drained;
        # then clip every agent onto the lattice — motility processes need
        # not know the domain geometry (it lives here, once)
        cs = self.colony.step_division(cs)
        agents = cs.agents
        loc = get_path(agents, self.location_path)
        h, w = self.lattice.size
        loc = jnp.clip(
            loc,
            jnp.zeros(2, loc.dtype),
            jnp.asarray([h, w], loc.dtype) - 1e-3,
        )
        cs = cs._replace(
            agents=set_path(agents, self.location_path, loc),
            step=cs.step + 1,
        )

        # 5. diffusion
        fields = self.lattice.step_fields(fields)
        return SpatialState(colony=cs, fields=fields)

    def emit_state(self, ss: SpatialState) -> dict:
        """The emit slice for one state (colony slice + fields)."""
        emit = self.colony.emit(ss.colony)
        emit["fields"] = ss.fields
        return emit

    def run(
        self,
        ss: SpatialState,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
    ) -> Tuple[SpatialState, dict]:
        return scan_schedule(
            lambda c: self.step(c, timestep), self.emit_state, ss,
            total_time, timestep, emit_every,
        )

    def run_timeline(
        self,
        ss: SpatialState,
        timeline,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        start_time: float = 0.0,
    ) -> Tuple[SpatialState, dict]:
        """Run with media changes: the timeline splits the run into
        segments; each segment is one jitted scan; at each media EVENT
        the fields are reset from the new recipe (host-side — a few
        device stores per media switch, off the hot path).

        ``timeline`` accepts anything ``environment.media.parse_timeline``
        does, e.g. ``"0 minimal, 500 minimal_lactose"``. Segment
        boundaries snap to whole steps (each duration must be a multiple
        of ``timestep * emit_every``, same contract as ``run``).

        ``start_time`` is this call's absolute simulation time: event
        times are absolute, so a checkpointed continuation starting at
        t=250 keeps its evolved fields (no spurious reset) and still
        applies later events on schedule.
        """
        from lens_tpu.environment.media import (
            fields_from_media,
            run_media_timeline,
        )

        return run_media_timeline(
            ss,
            timeline,
            total_time,
            start_time,
            run_segment=lambda s, d: self.run(s, d, timestep, emit_every),
            reset_fields=lambda s, media: s._replace(
                fields=fields_from_media(self.lattice, media)
            ),
        )

    # -- diagnostics ---------------------------------------------------------

    def total_field_mass(self, ss: SpatialState) -> jax.Array:
        """Sum over bins per molecule (conservation checks)."""
        return jnp.sum(ss.fields, axis=(1, 2))
