"""SpatialColony: colony + lattice coupled through pure index ops.

This module is the rebuild of the reference's whole outer/inner exchange
machinery (SURVEY.md §3.2): where the reference's outer agent broadcasts
local concentrations over Kafka, waits on a barrier for every inner
agent's exchange fluxes, then applies them to the lattice, here the
whole exchange window — gather, biology, scatter, division, diffusion —
compiles into ONE program around a :class:`CouplingPlan` built once at
construction:

1. **gather**   — the flat bin index is computed exactly once per step
   and one ``[M, N]`` gather overwrites every agent's ``external`` port
   variables with its bin's concentrations (ENVIRONMENT_UPDATE as one
   gather). Consuming ports see the occupancy-SHARED view (the gather
   divided by the bin's live count); sense-only ports read the RAW bin
   value straight from the same gather — no second gather is issued,
   because the raw view is the gather's own output before the division;
2. **biology**  — one vmapped colony step (all Processes);
3. **scatter**  — every agent's ``exchange`` accumulators land in its
   PRE-step bin through one ``[M]``-channel segment-sum over the shared
   flat index, then are zeroed (CELL_UPDATE as one scatter-add). The
   occupancy count of phase 1 is the same segment-sum primitive
   (ops.scatter — native CPU kernel when available); it cannot share
   the scatter op itself because its OUTPUT feeds the gather that feeds
   the biology that produces the exchange: occupancy -> gather ->
   biology -> scatter is the step's load-bearing dependency chain;
4. **division** — row activation after the accumulators drained;
5. **fields**   — diffusion substeps advance the lattice.

The barrier is implicit: step 3 happens after step 2 for every agent by
construction. No broker, no messages, no waiting. ``run`` compiles and
caches one jitted program per (window, timestep, emit cadence) — and
donates the input state's buffers on accelerators, where the colony +
fields pytree dominates HBM.

``coupling="reference"`` keeps the original three-message step (one op
per message, per-molecule Python loops, ``bin_of`` derived per op) as an
oracle; the fused path is bitwise-equal to it on CPU (tested) and
allclose elsewhere.
"""

from __future__ import annotations

from typing import Dict, Mapping, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from lens_tpu.colony.colony import Colony, ColonyState, _bcast
from lens_tpu.core.schedule import scan_schedule
from lens_tpu.core.topology import Path, normalize_path
from lens_tpu.environment.lattice import Lattice
from lens_tpu.utils.dicts import get_path, set_path


class SpatialState(NamedTuple):
    colony: ColonyState
    fields: jax.Array  # [M, H, W]


def _lattice_trace_key(lattice: Lattice):
    """Trace-relevant lattice parameters baked into compiled run
    programs (tests mutate lattices post-construction — e.g.
    ``lattice.impl = "adi"`` — so cached programs must be keyed on what
    their traces closed over; same contract as
    ``parallel.base.ShardedRunnerBase._lattice_key``)."""
    return (
        lattice.impl,
        lattice.alpha_window.tobytes(),
        lattice.shape,
        lattice.exchange_scale,
    )


def _colony_trace_key(colony: Colony):
    """Trace-relevant COLONY parameters baked into compiled run
    programs: every process config (tests mutate process configs
    post-construction too — e.g. ``processes["transport"].config
    ["vmax"] = 0.0`` — and before round 7 ``run`` re-traced per call,
    so such mutations silently took effect; the cache must notice
    them). Configs are small static trees, so fingerprinting per run()
    call costs microseconds against a window's dispatch."""
    import numpy as np

    from lens_tpu.utils.dicts import flatten_paths

    parts = [colony.capacity, colony.division_trigger, colony.death_trigger]
    for pname, proc in colony.compartment.processes.items():
        # class identity too: swapping a process for a different CLASS
        # with an identical config dict must also miss the cache
        parts.append(
            (pname, type(proc).__module__, type(proc).__qualname__)
        )
        for path, leaf in flatten_paths(proc.config):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                leaf = (str(leaf.dtype), leaf.shape,
                        np.asarray(leaf).tobytes())
            elif not isinstance(
                leaf, (str, int, float, bool, bytes, type(None), tuple)
            ):
                leaf = repr(leaf)
            parts.append((pname, path, leaf))
    return tuple(parts)


def _cached_run(
    cache: Dict, key, step_fn, emit_fn, total_time, timestep, emit_every
):
    """Get-or-build the jitted whole-window program for ``run``.

    One compiled program per cache key — a fresh ``lax.scan`` of a fresh
    lambda per call (the pre-round-7 shape) re-traces every segment of a
    segmented run. The key's last three elements are the window
    parameters (several may legitimately coexist — segment + remainder
    durations); everything before them is the model EPOCH (lattice
    trace key, colony/process fingerprints, coupling wiring). An epoch
    change means a post-construction mutation: every cached program
    baked the stale model, so the cache drops wholesale — which also
    bounds it, a config sweep mutating one process in place does not
    accumulate one dead executable per swept value.

    Input-state donation is resolved per call, NOT per cache entry
    alone: donation only means anything at top level on an accelerator
    (CPU ignores it loudly; under an outer jit/vmap trace the inner
    donation is meaningless), so tracer arguments and CPU backends take
    the non-donating twin of the program.
    """
    epoch = key[:-3]
    if cache.get("_epoch") != epoch:
        cache.clear()
        cache["_epoch"] = epoch

    def dispatch(state):
        donate = jax.default_backend() != "cpu" and not any(
            isinstance(leaf, jax.core.Tracer)
            for leaf in jax.tree.leaves(state)
        )
        full_key = key + (donate,)
        fn = cache.get(full_key)
        if fn is None:
            fn = cache[full_key] = jax.jit(
                lambda s: scan_schedule(
                    step_fn, emit_fn, s, total_time, timestep, emit_every
                ),
                donate_argnums=(0,) if donate else (),
            )
        return fn(state)

    return dispatch


class FieldPort(NamedTuple):
    """Wiring of one lattice molecule into the agent state tree.

    ``exchange`` may be ``None`` for sense-only coupling (e.g. a
    chemoreceptor reading an attractant it does not consume): the gather
    still runs, the scatter is skipped.
    """

    local: Path               # agent path overwritten with the bin concentration
    exchange: Optional[Path]  # agent path accumulating net secretion (or None)


class PortSpec(NamedTuple):
    """One resolved port of a :class:`CouplingPlan`."""

    molecule: str
    channel: int              # lattice field channel
    local: Path
    exchange: Optional[Path]  # None = sense-only (reads the RAW view)


class CouplingPlan(NamedTuple):
    """Static port->field-channel map, precomputed once per composite.

    Everything the per-step coupling needs that does NOT depend on state:
    which lattice channel each port reads, which agent path each
    channel's exchange accumulates in, and whether any port needs the
    raw (sense-only) view or any exchange scatter at all. Building it at
    construction time is what lets ``step`` run the whole coupling as
    index ops over ``[M, N]`` blocks with the flat bin index computed
    exactly once — the reference path instead re-derives ``bin_of`` per
    lattice op and loops Python-side per molecule per phase.
    """

    ports: Tuple[PortSpec, ...]
    #: per lattice channel (len M): the exchange path feeding it, or None
    exchange_by_channel: Tuple[Optional[Path], ...]
    any_exchange: bool


def build_coupling_plan(
    lattice: Lattice, field_ports: Mapping[str, FieldPort]
) -> CouplingPlan:
    """Resolve validated ``field_ports`` against the lattice's channel
    order (ports may name any subset of the lattice's molecules)."""
    ports = tuple(
        PortSpec(mol, lattice.index(mol), port.local, port.exchange)
        for mol, port in field_ports.items()
    )
    exchange_by_channel: list = [None] * len(lattice.molecules)
    for spec in ports:
        if spec.exchange is not None:
            exchange_by_channel[spec.channel] = spec.exchange
    return CouplingPlan(
        ports=ports,
        exchange_by_channel=tuple(exchange_by_channel),
        any_exchange=any(p.exchange is not None for p in ports),
    )


# -- the fused step's shared float expressions --------------------------------
#
# ONE authoritative copy of every numeric expression the fused coupling
# uses, called by all four fused step bodies (SpatialColony,
# MultiSpeciesColony, and their shard_map block programs — which differ
# only in where the psum sits and how rows split per species). The
# bitwise fused==reference contract leans on these expressions matching
# the reference path exactly; keeping them here keeps a future numerics
# tweak from silently landing in one copy and breaking parity.


def shared_view(raw, occ, flat, exchange_scale):
    """The occupancy-SHARED concentrations: ``raw`` [M, N] divided by
    each agent's bin occupancy (and the exchange scale) — identical
    expression to ``Lattice.local_concentrations(share_bins=True)``."""
    return raw / (jnp.maximum(occ[flat], 1.0)[None, :] * exchange_scale)


def apply_gather(plan: CouplingPlan, agents, alive, raw, shared):
    """Write every port's local variable from the gather ([M, rows]
    blocks): sense-only ports read ``raw``, consuming ports ``shared``;
    dead rows keep their previous value (mask hygiene)."""
    for spec in plan.ports:
        col = (raw if spec.exchange is None else shared)[spec.channel]
        prev = get_path(agents, spec.local)
        agents = set_path(agents, spec.local, jnp.where(alive, col, prev))
    return agents


def exchange_payload(plan: CouplingPlan, agents, n_rows: int):
    """The [M, rows] channel-major exchange block (zeros for channels
    without an exchange port) — feeds the scatter directly, so the
    fused path never materializes the reference's [rows, M] transpose."""
    return jnp.stack(
        [
            get_path(agents, path) if path is not None
            else jnp.zeros(n_rows)
            for path in plan.exchange_by_channel
        ],
        axis=0,
    )


def zero_exchanges(plan: CouplingPlan, agents):
    """Drain every exchange accumulator after the scatter banked it."""
    for spec in plan.ports:
        if spec.exchange is None:
            continue
        agents = set_path(
            agents,
            spec.exchange,
            jnp.zeros_like(get_path(agents, spec.exchange)),
        )
    return agents


def clip_to_domain(lattice: Lattice, agents, location_path: Path):
    """Clip every agent's location onto the lattice domain — motility
    processes need not know the geometry; it lives here, ONCE, for both
    coupling paths, both colony forms, and their sharded blocks (the
    1e-3 um inset keeps the floor'd bin index on-lattice)."""
    loc = get_path(agents, location_path)
    h, w = lattice.size
    loc = jnp.clip(
        loc,
        jnp.zeros(2, loc.dtype),
        jnp.asarray([h, w], loc.dtype) - 1e-3,
    )
    return set_path(agents, location_path, loc)


class SpatialColony:
    """A Colony embedded in a Lattice.

    field_ports: molecule name -> FieldPort (or (local, exchange) tuple).
    location_path: agent path of the [2] position leaf (um).
    coupling: "fused" (default — one-pass gather/scatter over the
        precomputed :class:`CouplingPlan`) or "reference" (the original
        per-molecule three-message step, kept as a numerical oracle).
        The two are bitwise-equal on CPU and allclose in general
        (tests/test_spatial.py::TestFusedCoupling).
    """

    def __init__(
        self,
        colony: Colony,
        lattice: Lattice,
        field_ports: Mapping[str, FieldPort | Tuple],
        location_path: Path | str = ("boundary", "location"),
        share_bins: bool = True,
        coupling: str = "fused",
    ):
        self.colony = colony
        self.lattice = lattice
        self.share_bins = bool(share_bins)
        if coupling not in ("fused", "reference"):
            raise ValueError(
                f"coupling must be 'fused' or 'reference', got {coupling!r}"
            )
        self.coupling = coupling
        self.location_path = normalize_path(location_path)
        self.field_ports: Dict[str, FieldPort] = {}
        known = colony.compartment.updaters
        if self.location_path not in known:
            raise ValueError(f"location path {self.location_path} not in schema")
        for mol, port in field_ports.items():
            if mol not in lattice.molecules:
                raise ValueError(f"molecule {mol!r} not on the lattice")
            local, exchange = port[0], port[1]
            port = FieldPort(
                normalize_path(local),
                normalize_path(exchange) if exchange is not None else None,
            )
            for path in port:
                if path is not None and path not in known:
                    raise ValueError(f"field port path {path} not in schema")
            self.field_ports[mol] = port
        self.plan = build_coupling_plan(lattice, self.field_ports)
        #: compiled run programs, keyed per (lattice trace key, window,
        #: timestep, emit cadence, donate) — see :meth:`run`
        self._run_cache: Dict = {}

    # -- construction --------------------------------------------------------

    def with_colony(self, colony: Colony) -> "SpatialColony":
        """Rewrap a (typically capacity-grown) colony with this
        SpatialColony's lattice and wiring — the ONE place the
        constructor-argument set is repeated, so expansion/resume paths
        cannot silently drop a newly added parameter."""
        return SpatialColony(
            colony,
            self.lattice,
            self.field_ports,
            location_path=self.location_path,
            share_bins=self.share_bins,
            coupling=self.coupling,
        )

    def expanded(
        self, ss: SpatialState, factor: int = 2
    ) -> Tuple["SpatialColony", SpatialState]:
        """Capacity growth for the embedded colony (host-side, segment
        boundary): see :meth:`lens_tpu.colony.colony.Colony.expanded`.
        The lattice and fields are untouched — only the agent rows grow
        (padded rows are dead, parked at location 0 like every dead
        row)."""
        grown, cs = self.colony.expanded(ss.colony, factor)
        return self.with_colony(grown), ss._replace(colony=cs)

    def initial_state(
        self,
        n_alive: int,
        key: jax.Array,
        overrides: Mapping | None = None,
        locations: jax.Array | None = None,
    ) -> SpatialState:
        """Colony rows + uniform fields. Locations default to uniform random
        placement over the domain (live rows only; dead rows parked at 0)."""
        cs = self.colony.initial_state(n_alive, overrides=overrides, key=key)
        if locations is not None:
            locations = jnp.asarray(locations)
            expected = (self.colony.capacity, 2)
            if locations.shape != expected:
                raise ValueError(
                    f"locations has shape {locations.shape}, expected "
                    f"{expected} (rows for ALL capacity slots, not just "
                    f"n_alive; dead rows' values are ignored)"
                )
        if locations is None:
            lkey = jax.random.fold_in(key, 0x10C)
            h, w = self.lattice.size
            locations = jax.random.uniform(
                lkey,
                (self.colony.capacity, 2),
                minval=jnp.zeros(2),
                maxval=jnp.asarray([h, w]),
            )
        agents = set_path(
            cs.agents,
            self.location_path,
            jnp.asarray(locations, jnp.float32),
        )
        cs = cs._replace(agents=agents)
        return SpatialState(colony=cs, fields=self.lattice.initial_fields())

    def apply_overrides(
        self, ss: SpatialState, overrides: Mapping | None
    ) -> SpatialState:
        """Set schema variables on an existing state (the serve fork
        point; see :meth:`Colony.apply_overrides`). Agent rows only —
        the lattice fields are evolved state, not schema variables."""
        if not overrides:
            return ss
        return ss._replace(
            colony=self.colony.apply_overrides(ss.colony, overrides)
        )

    # -- stepping ------------------------------------------------------------

    def step(self, ss: SpatialState, timestep: float) -> SpatialState:
        if abs(timestep - self.lattice.timestep) > 1e-9:
            raise ValueError(
                f"timestep={timestep} != lattice.timestep="
                f"{self.lattice.timestep}: the lattice precomputes its "
                f"diffusion substeps for its own timestep — construct the "
                f"Lattice with the timestep you run at"
            )
        if self.coupling == "fused":
            return self._step_fused(ss, timestep)
        return self._step_reference(ss, timestep)

    def _finish_step(
        self, cs: ColonyState, fields: jax.Array
    ) -> SpatialState:
        """Shared tail of both coupling paths: division (row activation)
        now that accumulators are drained; then clip every agent onto
        the lattice — motility processes need not know the domain
        geometry (it lives here, once) — and advance the fields."""
        cs = self.colony.step_division(cs)
        cs = cs._replace(
            agents=clip_to_domain(
                self.lattice, cs.agents, self.location_path
            ),
            step=cs.step + 1,
        )
        fields = self.lattice.step_fields(fields)
        return SpatialState(colony=cs, fields=fields)

    def _step_fused(self, ss: SpatialState, timestep: float) -> SpatialState:
        """One-pass coupling over the precomputed CouplingPlan.

        The flat bin index is derived once and shared by the occupancy
        count, the ``[M, N]`` gather, and the exchange segment-sum; the
        raw (sense-only) view is the gather's own output before the
        occupancy division, so no second gather exists. Identical
        numerics to :meth:`_step_reference` op for op (same fold order
        in the scatters, same division expression in the gather), so the
        two paths agree bitwise on CPU.
        """
        cs, fields = ss
        lattice, plan = self.lattice, self.plan
        agents = cs.agents
        locations = get_path(agents, self.location_path)
        flat = lattice.flat_bin_of(locations)  # the step's ONE bin map
        ff = fields.reshape(len(lattice.molecules), lattice.n_bins)

        # 1. gather: raw = the bins themselves; shared = raw / the
        # bin's live occupancy (consuming ports must split the bin so
        # collective uptake cannot overdraw it — sense-only ports never
        # debit it, so they read raw)
        raw = ff[:, flat]  # [M, N]
        if self.share_bins:
            occ = lattice.occupancy_flat(flat, cs.alive)
            shared = shared_view(raw, occ, flat, lattice.exchange_scale)
        else:
            shared = raw
        cs = cs._replace(
            agents=apply_gather(plan, agents, cs.alive, raw, shared)
        )

        # 2. biology — processes only; division is deferred until the
        # exchange is applied (its dividers zero the accumulators)
        cs = self.colony.step_biology(cs, timestep)

        # 3. scatter: one [M]-channel segment-sum into the PRE-STEP bins
        # (motility may have moved agents this step; debiting the new
        # bin could overdraw it, and the >=0 clamp would create mass)
        if plan.any_exchange:
            exchange = exchange_payload(plan, cs.agents, cs.alive.shape[0])
            fields = lattice.apply_exchanges_flat(
                ff, flat, exchange, cs.alive
            ).reshape(fields.shape)
            cs = cs._replace(agents=zero_exchanges(plan, cs.agents))
        else:
            # no exchange ports: the reference path still applies its
            # all-zero exchange and therefore still CLAMPS — which is a
            # real invariant for e.g. impl="adi" fields that can
            # undershoot zero. Keep the clamp so the oracle contract
            # (and the >=0 fields guarantee) holds for sense-only
            # wirings too.
            fields = jnp.maximum(fields, 0.0)

        # 4.-5. division, clip, diffusion (shared tail)
        return self._finish_step(cs, fields)

    def _step_reference(
        self, ss: SpatialState, timestep: float
    ) -> SpatialState:
        """The original three-message step — one lattice op per message,
        ``bin_of`` re-derived per op — kept as the fused path's oracle
        (``coupling="reference"``)."""
        cs, fields = ss
        locations = get_path(cs.agents, self.location_path)

        # 1. gather: overwrite each agent's local-env variables. Consuming
        # ports see the bin-SHARED concentration (co-located agents split
        # the bin, so uptake cannot overdraw it); sense-only ports
        # (exchange=None) see the RAW bin value — they never debit the
        # bin, so sharing would just distort sensing with occupancy.
        local_shared = self.lattice.local_concentrations(
            fields, locations, cs.alive, share_bins=self.share_bins
        )  # [N, M]
        local_raw = (
            self.lattice.local_concentrations(
                fields, locations, cs.alive, share_bins=False
            )
            if any(p.exchange is None for p in self.field_ports.values())
            else local_shared
        )
        agents = cs.agents
        for mol, port in self.field_ports.items():
            local = local_raw if port.exchange is None else local_shared
            col = local[:, self.lattice.index(mol)]
            prev = get_path(agents, port.local)
            # dead rows keep their previous value (mask hygiene)
            agents = set_path(
                agents, port.local, jnp.where(cs.alive, col, prev)
            )
        cs = cs._replace(agents=agents)

        # 2. biology — processes only; division is deferred until the
        # exchange is applied (its dividers zero the accumulators)
        cs = self.colony.step_biology(cs, timestep)

        # 3. scatter: debit/credit the PRE-STEP bins — the bins whose
        # concentrations the transport processes actually saw. (Motility
        # may have moved the agent this step; debiting the new bin could
        # overdraw it, and the >=0 clamp would then create mass.)
        agents = cs.agents
        exchange = jnp.stack(
            [
                get_path(agents, self.field_ports[mol].exchange)
                if mol in self.field_ports
                and self.field_ports[mol].exchange is not None
                else jnp.zeros(self.colony.capacity)
                for mol in self.lattice.molecules
            ],
            axis=1,
        )  # [N, M]
        fields = self.lattice.apply_exchanges(
            fields, locations, exchange, cs.alive
        )
        for mol, port in self.field_ports.items():
            if port.exchange is None:
                continue
            agents = set_path(
                agents,
                port.exchange,
                jnp.zeros_like(get_path(agents, port.exchange)),
            )
        cs = cs._replace(agents=agents)

        # 4.-5. division, clip, diffusion (shared tail)
        return self._finish_step(cs, fields)

    def emit_state(self, ss: SpatialState) -> dict:
        """The emit slice for one state (colony slice + fields)."""
        emit = self.colony.emit(ss.colony)
        emit["fields"] = ss.fields
        return emit

    def run(
        self,
        ss: SpatialState,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
    ) -> Tuple[SpatialState, dict]:
        """Scan ``step`` over ``total_time`` as ONE cached jitted program.

        Programs are cached per (lattice trace key, window, timestep,
        emit cadence), so segmented runs (experiment checkpointing,
        media timelines) re-dispatch the compiled step chain instead of
        re-tracing a fresh scan per segment. On accelerators the input
        state's buffers are donated — the colony + fields pytree
        dominates device memory, and a window's input is dead the moment
        its output exists. (Donation is skipped on CPU, inside outer
        traces, and thus for every vmapped/ensemble use.)
        """
        key = (
            _lattice_trace_key(self.lattice),
            _colony_trace_key(self.colony),
            self.coupling,
            self.share_bins,
            float(total_time),
            float(timestep),
            int(emit_every),
        )
        return _cached_run(
            self._run_cache,
            key,
            lambda c: self.step(c, timestep),
            self.emit_state,
            total_time,
            timestep,
            emit_every,
        )(ss)

    def run_timeline(
        self,
        ss: SpatialState,
        timeline,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        start_time: float = 0.0,
    ) -> Tuple[SpatialState, dict]:
        """Run with media changes: the timeline splits the run into
        segments; each segment is one jitted scan; at each media EVENT
        the fields are reset from the new recipe (host-side — a few
        device stores per media switch, off the hot path).

        ``timeline`` accepts anything ``environment.media.parse_timeline``
        does, e.g. ``"0 minimal, 500 minimal_lactose"``. Segment
        boundaries snap to whole steps (each duration must be a multiple
        of ``timestep * emit_every``, same contract as ``run``).

        ``start_time`` is this call's absolute simulation time: event
        times are absolute, so a checkpointed continuation starting at
        t=250 keeps its evolved fields (no spurious reset) and still
        applies later events on schedule.
        """
        from lens_tpu.environment.media import (
            fields_from_media,
            run_media_timeline,
        )

        return run_media_timeline(
            ss,
            timeline,
            total_time,
            start_time,
            run_segment=lambda s, d: self.run(s, d, timestep, emit_every),
            reset_fields=lambda s, media: s._replace(
                fields=fields_from_media(self.lattice, media)
            ),
        )

    # -- diagnostics ---------------------------------------------------------

    def total_field_mass(self, ss: SpatialState) -> jax.Array:
        """Sum over bins per molecule (conservation checks)."""
        return jnp.sum(ss.fields, axis=(1, 2))
