from lens_tpu.environment.lattice import Lattice
from lens_tpu.environment.spatial import SpatialColony, SpatialState

__all__ = ["Lattice", "SpatialColony", "SpatialState"]
