from lens_tpu.environment.lattice import Lattice
from lens_tpu.environment.multispecies import (
    MultiSpeciesColony,
    MultiSpeciesState,
)
from lens_tpu.environment.spatial import SpatialColony, SpatialState

__all__ = [
    "Lattice",
    "MultiSpeciesColony",
    "MultiSpeciesState",
    "SpatialColony",
    "SpatialState",
]
