"""Mixed-species colonies: distinct process sets sharing one lattice.

The reference's mixed-species experiments boot DIFFERENT agent types onto
the same environment lattice — each cell type has its own process set,
and the outer agent neither knows nor cares which inner sim answers an
exchange window (reconstructed: SURVEY.md §2 "Boot registry" agent types,
§7 hard-part #1 "mixed process-sets per agent").

Under SPMD there are two ways to get heterogeneity (SURVEY.md §7):
masked unified state (every process runs on every agent, masked off) or
**per-species subcolonies** — this module implements the latter, which is
the TPU-idiomatic choice:

- each species is its own :class:`~lens_tpu.colony.colony.Colony` with
  its own compartment, so each species' biology is one clean ``vmap``
  over a densely-packed agent axis — no wasted FLOPs on masked-off
  processes, no schema union across species;
- the lattice is shared: every species' rows concatenate onto ONE agent
  axis for the lattice couplings — one occupancy, one gather, one
  scatter per step regardless of species count — with **combined
  occupancy** (all species' live cells in a bin split its content) so
  shared-bin mass conservation spans species exactly as it spans agents
  within one species;
- division stays within a species (cells breed true), so each
  subcolony's row-activation machinery is untouched.

Each species' agent axis can be sharded independently with ``shard_map``
(the same data-parallel layout ``parallel.runner`` gives one species);
the fields axis shards spatially as usual. Scale limits are per species:
capacity is preallocated per subcolony.
"""

from __future__ import annotations

from typing import Dict, Mapping, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from lens_tpu.colony.colony import Colony, ColonyState
from lens_tpu.core.topology import Path, normalize_path
from lens_tpu.environment.lattice import Lattice
from lens_tpu.environment.spatial import (
    FieldPort,
    SpatialColony,
    apply_gather,
    clip_to_domain,
    exchange_payload,
    shared_view,
    zero_exchanges,
)
from lens_tpu.utils.dicts import get_path, set_path


class MultiSpeciesState(NamedTuple):
    species: Dict[str, ColonyState]  # one stacked subcolony per species
    fields: jax.Array                # [M, H, W] shared lattice fields


class MultiSpeciesColony:
    """N species, one lattice, one jitted step.

    Parameters
    ----------
    species:
        name -> ``SpatialColony`` built against the SAME ``lattice``
        object (their per-species port wiring and validation are reused;
        their own ``step`` is not — stepping happens here so occupancy,
        scatter and diffusion are shared across species).
    lattice:
        The shared environment.
    share_bins:
        As in :class:`SpatialColony`, but occupancy counts live cells of
        ALL species in a bin.
    """

    def __init__(
        self,
        species: Mapping[str, SpatialColony],
        lattice: Lattice,
        share_bins: bool = True,
        coupling: str = "fused",
    ):
        if not species:
            raise ValueError("need at least one species")
        if "fields" in species:
            raise ValueError(
                'species name "fields" is reserved (the emit trajectory '
                "carries the lattice under that key)"
            )
        for name, sp in species.items():
            if sp.lattice is not lattice:
                raise ValueError(
                    f"species {name!r} was built against a different "
                    f"Lattice object; all species must share one"
                )
        self.species: Dict[str, SpatialColony] = dict(species)
        self.lattice = lattice
        self.share_bins = bool(share_bins)
        if coupling not in ("fused", "reference"):
            raise ValueError(
                f"coupling must be 'fused' or 'reference', got {coupling!r}"
            )
        self.coupling = coupling
        self._run_cache: Dict = {}

    # -- construction --------------------------------------------------------

    def initial_state(
        self,
        n_alive: Mapping[str, int],
        key: jax.Array,
        overrides: Mapping[str, Mapping] | None = None,
        locations: Mapping[str, jax.Array] | None = None,
    ) -> MultiSpeciesState:
        """Per-species row construction + one shared field array."""
        overrides = overrides or {}
        locations = locations or {}
        states: Dict[str, ColonyState] = {}
        for idx, name in enumerate(sorted(self.species)):
            sp = self.species[name]
            ss = sp.initial_state(
                int(n_alive.get(name, 0)),
                jax.random.fold_in(key, idx),
                overrides=overrides.get(name),
                locations=locations.get(name),
            )
            states[name] = ss.colony
        return MultiSpeciesState(
            species=states, fields=self.lattice.initial_fields()
        )

    def apply_overrides(
        self,
        ms: MultiSpeciesState,
        overrides: Mapping[str, Mapping] | None,
    ) -> MultiSpeciesState:
        """Set schema variables on an existing state (the serve fork
        point; see :meth:`Colony.apply_overrides`). Keyed per species,
        like ``initial_state``'s ``overrides=``."""
        if not overrides:
            return ms
        states = dict(ms.species)
        for name, ovr in overrides.items():
            if name not in self.species:
                raise KeyError(
                    f"override species {name!r} is not one of "
                    f"{sorted(self.species)}"
                )
            states[name] = self.species[name].colony.apply_overrides(
                states[name], ovr
            )
        return ms._replace(species=states)

    # -- stepping ------------------------------------------------------------

    def _row_slices(self, ms: MultiSpeciesState) -> Dict[str, slice]:
        """Static row slice of each species within the concatenated
        all-species agent axis (dict order = iteration order)."""
        out: Dict[str, slice] = {}
        offset = 0
        for name in self.species:
            rows = ms.species[name].alive.shape[0]
            out[name] = slice(offset, offset + rows)
            offset += rows
        return out

    def total_occupancy(self, ms: MultiSpeciesState) -> jax.Array:
        """Live-cell count per bin, summed over every species: [H, W]."""
        locs, alive = self._concat_rows(ms)
        return self.lattice.occupancy(locs, alive)

    def _concat_rows(self, ms: MultiSpeciesState):
        """All species' (locations, alive) stacked on one row axis.

        The cross-species couplings (combined occupancy, one gather, one
        scatter) run over this concatenated axis — O(1) lattice ops per
        step regardless of species count, instead of one gather/scatter
        pipeline per species.
        """
        locs = jnp.concatenate(
            [
                get_path(ms.species[name].agents, sp.location_path)
                for name, sp in self.species.items()
            ]
        )
        alive = jnp.concatenate(
            [ms.species[name].alive for name in self.species]
        )
        return locs, alive

    def step(self, ms: MultiSpeciesState, timestep: float) -> MultiSpeciesState:
        """One exchange window for every species + the shared fields.

        Same pre-step-bin semantics as :meth:`SpatialColony.step`, with
        cross-species combined occupancy in the gather and ONE clamp after
        all species' exchanges land (so inter-species accounting is a
        single mass balance, not per-species application order).
        """
        if abs(timestep - self.lattice.timestep) > 1e-9:
            raise ValueError(
                f"timestep={timestep} != lattice.timestep="
                f"{self.lattice.timestep}"
            )
        if self.coupling == "fused":
            return self._step_fused(ms, timestep)
        return self._step_reference(ms, timestep)

    def _step_fused(
        self, ms: MultiSpeciesState, timestep: float
    ) -> MultiSpeciesState:
        """One-pass coupling over the species' precomputed CouplingPlans.

        All species' rows concatenate onto one agent axis; the flat bin
        index of that axis is computed once and shared by the combined
        (ALL-species) occupancy count, the single ``[M, rows_all]``
        gather, and the single exchange segment-sum — O(1) lattice ops
        per step regardless of species count, now with O(1) index
        derivations too. Numerically identical to
        :meth:`_step_reference` (bitwise on CPU, tested).
        """
        lattice = self.lattice
        fields = ms.fields
        rows = self._row_slices(ms)
        all_locs, all_alive = self._concat_rows(ms)
        flat = lattice.flat_bin_of(all_locs)  # ONE bin map for the step
        n_mols = len(lattice.molecules)
        ff = fields.reshape(n_mols, lattice.n_bins)

        # 1. ONE gather for all species. raw = the bins themselves;
        # shared divides by the ALL-species occupancy (co-located cells
        # of every species split the bin's content). Sense-only ports
        # read raw — the same gather's output before the division.
        raw = ff[:, flat]  # [M, rows_all]
        if self.share_bins:
            occ = lattice.occupancy_flat(flat, all_alive)
            shared = shared_view(raw, occ, flat, lattice.exchange_scale)
        else:
            shared = raw
        stepped: Dict[str, ColonyState] = {}
        for name, sp in self.species.items():
            cs = ms.species[name]
            stepped[name] = cs._replace(
                agents=apply_gather(
                    sp.plan, cs.agents, cs.alive,
                    raw[:, rows[name]], shared[:, rows[name]],
                )
            )

        # 2. biology per species — one vmap per process set (necessarily
        # per species: each has its own program)
        for name, sp in self.species.items():
            stepped[name] = sp.colony.step_biology(stepped[name], timestep)

        # 3. ONE segment-sum of all species' exchanges into the PRE-STEP
        # bins, one >=0 clamp (channel-major payload assembled per
        # species from its plan, concatenated along the shared row axis)
        payloads = []
        for name, sp in self.species.items():
            cs = stepped[name]
            payloads.append(
                exchange_payload(sp.plan, cs.agents, cs.alive.shape[0])
            )  # [M, rows]
            stepped[name] = cs._replace(
                agents=zero_exchanges(sp.plan, cs.agents)
            )
        fields = lattice.apply_exchanges_flat(
            ff, flat, jnp.concatenate(payloads, axis=1), all_alive
        ).reshape(fields.shape)

        # 4. division per species, then clip onto the domain
        for name, sp in self.species.items():
            cs = sp.colony.step_division(stepped[name])
            stepped[name] = cs._replace(
                agents=clip_to_domain(lattice, cs.agents, sp.location_path),
                step=cs.step + 1,
            )

        # 5. diffusion, once
        fields = lattice.step_fields(fields)
        return MultiSpeciesState(species=stepped, fields=fields)

    def _step_reference(
        self, ms: MultiSpeciesState, timestep: float
    ) -> MultiSpeciesState:
        """The original per-molecule multi-species step (one lattice op
        per message), kept as the fused path's oracle
        (``coupling="reference"``)."""
        fields = ms.fields
        rows = self._row_slices(ms)
        all_locs, all_alive = self._concat_rows(ms)

        # 1. ONE gather for all species (shared for consuming ports —
        # divided by the ALL-species occupancy — raw for sense-only
        # ports), then split by static row slices
        local_shared_all = self.lattice.local_concentrations(
            fields, all_locs, all_alive, share_bins=self.share_bins
        )
        local_raw_all = (
            self.lattice.local_concentrations(
                fields, all_locs, all_alive, share_bins=False
            )
            if any(
                p.exchange is None
                for sp in self.species.values()
                for p in sp.field_ports.values()
            )
            else local_shared_all
        )
        stepped: Dict[str, ColonyState] = {}
        for name, sp in self.species.items():
            cs = ms.species[name]
            agents = cs.agents
            for mol, port in sp.field_ports.items():
                local = (
                    local_raw_all if port.exchange is None
                    else local_shared_all
                )
                col = local[rows[name], self.lattice.index(mol)]
                prev = get_path(agents, port.local)
                agents = set_path(
                    agents, port.local, jnp.where(cs.alive, col, prev)
                )
            stepped[name] = cs._replace(agents=agents)

        # 2. biology per species — one vmap per process set (necessarily
        # per species: each has its own program)
        for name, sp in self.species.items():
            stepped[name] = sp.colony.step_biology(stepped[name], timestep)

        # 3. ONE scatter of all species' exchanges into the PRE-STEP
        # bins, one >=0 clamp (lattice.apply_exchanges)
        exchanges = []
        for name, sp in self.species.items():
            cs = stepped[name]
            agents = cs.agents
            cap_rows = cs.alive.shape[0]
            exchanges.append(
                jnp.stack(
                    [
                        get_path(agents, sp.field_ports[mol].exchange)
                        if mol in sp.field_ports
                        and sp.field_ports[mol].exchange is not None
                        else jnp.zeros(cap_rows)
                        for mol in self.lattice.molecules
                    ],
                    axis=1,
                )
            )  # [rows, M]
            for mol, port in sp.field_ports.items():
                if port.exchange is None:
                    continue
                agents = set_path(
                    agents, port.exchange,
                    jnp.zeros_like(get_path(agents, port.exchange)),
                )
            stepped[name] = cs._replace(agents=agents)
        fields = self.lattice.apply_exchanges(
            fields, all_locs, jnp.concatenate(exchanges), all_alive
        )

        # 4. division per species, then clip onto the domain
        for name, sp in self.species.items():
            cs = sp.colony.step_division(stepped[name])
            stepped[name] = cs._replace(
                agents=clip_to_domain(
                    self.lattice, cs.agents, sp.location_path
                ),
                step=cs.step + 1,
            )

        # 5. diffusion, once
        fields = self.lattice.step_fields(fields)
        return MultiSpeciesState(species=stepped, fields=fields)

    def emit_state(self, ms: MultiSpeciesState) -> dict:
        """The emit slice for one state (per-species slices + fields)."""
        emit = {
            name: sp.colony.emit(ms.species[name])
            for name, sp in self.species.items()
        }
        emit["fields"] = ms.fields
        return emit

    def run(
        self,
        ms: MultiSpeciesState,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
    ) -> Tuple[MultiSpeciesState, dict]:
        """Scan ``step`` as ONE cached jitted program (same caching and
        accelerator-side input donation as :meth:`SpatialColony.run`)."""
        from lens_tpu.environment.spatial import (
            _cached_run,
            _colony_trace_key,
            _lattice_trace_key,
        )

        key = (
            _lattice_trace_key(self.lattice),
            tuple(
                (name, _colony_trace_key(sp.colony))
                for name, sp in self.species.items()
            ),
            self.coupling,
            self.share_bins,
            float(total_time),
            float(timestep),
            int(emit_every),
        )
        return _cached_run(
            self._run_cache,
            key,
            lambda c: self.step(c, timestep),
            self.emit_state,
            total_time,
            timestep,
            emit_every,
        )(ms)

    def run_timeline(
        self,
        ms: MultiSpeciesState,
        timeline,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        start_time: float = 0.0,
    ) -> Tuple[MultiSpeciesState, dict]:
        """Run with media changes: same semantics as
        ``SpatialColony.run_timeline`` (one shared helper —
        environment.media.run_media_timeline): the timeline splits the
        run into segments, each segment is one jitted scan, and at each
        media EVENT the shared fields are rebuilt from the new recipe.
        ``start_time`` is absolute, so checkpointed segments / resumes
        continue the timeline instead of restarting it."""
        from lens_tpu.environment.media import (
            fields_from_media,
            run_media_timeline,
        )

        def reset_fields(s, media):
            return s._replace(
                fields=fields_from_media(self.lattice, media)
            )

        return run_media_timeline(
            ms,
            timeline,
            total_time,
            start_time,
            run_segment=lambda s, d: self.run(s, d, timestep, emit_every),
            reset_fields=reset_fields,
        )

    # -- capacity growth -----------------------------------------------------

    def expanded(
        self,
        ms: MultiSpeciesState,
        factors: Mapping[str, int] | int = 2,
    ) -> Tuple["MultiSpeciesColony", MultiSpeciesState]:
        """Per-species capacity growth (host-side, segment boundary).

        ``factors``: one int for every species, or a per-species mapping
        (missing / <=1 leaves that species untouched — species fill their
        pools at different rates, so growth is naturally per-species).
        Delegates to :meth:`lens_tpu.colony.colony.Colony.expanded` per
        species (pre-expansion trajectories bitwise unchanged, lineage id
        watermarks carried), shares the untouched lattice fields, and
        rebuilds the wrapper with the same lattice/wiring.
        """
        new_species: Dict[str, SpatialColony] = {}
        new_states: Dict[str, ColonyState] = {}
        for name, sp in self.species.items():
            f = factors if isinstance(factors, int) else int(
                factors.get(name, 1)
            )
            if f <= 1:
                new_species[name] = sp
                new_states[name] = ms.species[name]
                continue
            grown, cs = sp.colony.expanded(ms.species[name], f)
            new_species[name] = sp.with_colony(grown)
            new_states[name] = cs
        multi = MultiSpeciesColony(
            new_species, self.lattice, share_bins=self.share_bins,
            coupling=self.coupling,
        )
        return multi, MultiSpeciesState(
            species=new_states, fields=ms.fields
        )

    # -- diagnostics ---------------------------------------------------------

    def total_field_mass(self, ms: MultiSpeciesState) -> jax.Array:
        return jnp.sum(ms.fields, axis=(1, 2))

    def n_alive(self, ms: MultiSpeciesState) -> Dict[str, jax.Array]:
        return {
            name: jnp.sum(ms.species[name].alive) for name in self.species
        }
