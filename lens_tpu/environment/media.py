"""Media maker + timeline parsing: environment composition as data.

The reference builds media composition dicts from named recipes and parses
timeline strings like ``"0 minimal, 500 minimal_lactose"`` that switch the
environment's composition at given simulation times (reconstructed:
``lens/environment/make_media.py`` + timeline helpers, SURVEY.md §2 "Media
maker"). The rebuild keeps media as plain data (mM dicts from
``lens_tpu/data/media_recipes.json``) and implements timeline changes the
TPU-idiomatic way: a timeline splits a run into segments; each segment is
one jitted scan; at each boundary the field array is reset host-side from
the recipe (a handful of device stores every few hundred sim-seconds —
nowhere near the hot path).
"""

from __future__ import annotations

import re
from typing import Dict, List, Mapping, Sequence, Tuple, Union

import jax.numpy as jnp

from lens_tpu.data import load_json
from lens_tpu.utils.dicts import deep_merge

MediaDict = Dict[str, float]
TimelineEvent = Tuple[float, MediaDict]

_recipes_cache: Dict[str, MediaDict] | None = None


def media_recipes() -> Dict[str, MediaDict]:
    """All packaged recipes (name -> {molecule: mM}), loaded once."""
    global _recipes_cache
    if _recipes_cache is None:
        raw = load_json("media_recipes.json")
        _recipes_cache = {
            name: dict(comp)
            for name, comp in raw.items()
            if not name.startswith("_")
        }
    return _recipes_cache


def make_media(
    recipe: Union[str, Mapping[str, float]],
    overrides: Mapping[str, float] | None = None,
) -> MediaDict:
    """Build a media composition dict from a recipe name or literal dict.

    ``overrides`` deep-merge on top (set a molecule to a new value, or add
    one) — the reference's "recipe + modifications" pattern.
    """
    if isinstance(recipe, str):
        recipes = media_recipes()
        if recipe not in recipes:
            raise KeyError(
                f"unknown media recipe {recipe!r}; known: {sorted(recipes)}"
            )
        base = dict(recipes[recipe])
    else:
        base = dict(recipe)
    if overrides:
        base = deep_merge(base, dict(overrides))
    return {mol: float(v) for mol, v in base.items()}


def parse_timeline(
    timeline: Union[str, Sequence[Tuple[float, Union[str, Mapping]]]],
) -> List[TimelineEvent]:
    """Parse a timeline into sorted ``[(time_s, media_dict), ...]``.

    String form: comma-separated ``"<time> <recipe>"`` events, e.g.
    ``"0 minimal, 500 minimal_lactose, 1000 blank"``. Times are seconds
    (floats ok). Sequence form: ``[(time, recipe_or_dict), ...]``.
    The first event must be at t=0 (the initial media).
    """
    events: List[TimelineEvent] = []
    if isinstance(timeline, str):
        for chunk in timeline.split(","):
            chunk = chunk.strip()
            if not chunk:
                continue
            m = re.match(r"^(\S+)\s+(\S+)$", chunk)
            if not m:
                raise ValueError(
                    f"timeline event {chunk!r} is not '<time> <recipe>'"
                )
            events.append((float(m.group(1)), make_media(m.group(2))))
    else:
        for time, recipe in timeline:
            events.append((float(time), make_media(recipe)))
    events.sort(key=lambda e: e[0])
    if not events:
        raise ValueError("timeline has no events")
    if events[0][0] != 0.0:
        raise ValueError(
            f"timeline must start at t=0 (first event at t={events[0][0]})"
        )
    times = [t for t, _ in events]
    if len(set(times)) != len(times):
        raise ValueError(f"timeline has duplicate event times: {times}")
    return events


def fields_from_media(lattice, media: MediaDict) -> jnp.ndarray:
    """Uniform [M, H, W] field array for a media composition.

    Molecules the lattice does not track are ignored; lattice molecules
    missing from the media get 0 (defined-blank semantics).
    """
    h, w = lattice.shape
    return jnp.stack(
        [
            jnp.full((h, w), float(media.get(mol, 0.0)), jnp.float32)
            for mol in lattice.molecules
        ]
    )


def run_media_timeline(
    state,
    timeline,
    total_time: float,
    start_time: float,
    run_segment,
    reset_fields,
):
    """The shared timeline-driven run loop (ONE copy for the unsharded
    and sharded paths): split ``[start_time, start_time+total_time)`` at
    media events, reset fields only at segment starts that ARE event
    times (a checkpoint continuation mid-epoch keeps its evolved
    fields), run each segment, concatenate trajectories.

    ``run_segment(state, duration) -> (state, trajectory)``;
    ``reset_fields(state, media) -> state``.
    """
    import jax
    import jax.numpy as _jnp

    events = parse_timeline(timeline)
    event_times = {t for t, _ in events}
    trajectories = []
    for seg_start, duration, media in timeline_segments(
        events, total_time, start_time
    ):
        if any(abs(seg_start - t) < 1e-9 for t in event_times):
            state = reset_fields(state, media)
        state, traj = run_segment(state, duration)
        trajectories.append(traj)
    trajectory = jax.tree.map(
        lambda *xs: _jnp.concatenate(xs, axis=0), *trajectories
    )
    return state, trajectory


def timeline_segments(
    events: Sequence[TimelineEvent],
    total_time: float,
    start_time: float = 0.0,
) -> List[Tuple[float, float, MediaDict]]:
    """Cut ``[start_time, start_time + total_time)`` into
    ``(abs_start, duration, media)`` segments.

    ``start_time`` matters for segmented/checkpointed runs: a
    continuation covering [250, 500) of a ``"0 minimal, 400 lactose"``
    timeline gets the minimal segment [250, 400) and the lactose shift
    at 400 — event times are ABSOLUTE simulation times, not offsets into
    each run call.
    """
    end_time = start_time + total_time
    out: List[Tuple[float, float, MediaDict]] = []
    for k, (start, media) in enumerate(events):
        nxt = events[k + 1][0] if k + 1 < len(events) else end_time
        s = max(start, start_time)
        e = min(nxt, end_time)
        if e > s:
            out.append((s, e - s, media))
    return out
