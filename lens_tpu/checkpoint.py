"""Checkpoint/resume: the whole colony as one orbax-saved pytree.

The reference has no checkpointing of its own — the only state
serialization is the division handshake's daughter-state dicts
(reconstructed: SURVEY.md §5 "Checkpoint/resume") — but the rebuild makes
it first-class, exactly because the whole-simulation-state-as-one-pytree
design gives it away for free: save the ``ColonyState``/``SpatialState``
every K steps with orbax, resume = restore + continue. Resumed runs are
bitwise-identical to uninterrupted ones (the PRNG key and step counter
are part of the state), which the tests pin.

Layout: ``<dir>/step_<n>/`` orbax PyTree checkpoints; ``latest_step()``
scans the directory. NamedTuple states are saved as plain nested
containers and rebuilt by the typed ``restore_*`` helpers.

Crash safety: ``save`` writes into ``step_<n>.tmp-save`` and
``os.rename``\\ s it into place once orbax has fully committed the tree.
``steps()`` matches only final ``step_<n>`` names, so a run killed
mid-save can never leave a half-written directory that ``restore()``
then picks as latest — the worst case is a stale ``.tmp-save`` dir,
which the next save of that step silently overwrites.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, List, Optional

import jax
import orbax.checkpoint as ocp

from lens_tpu.colony.colony import ColonyState
from lens_tpu.environment.multispecies import MultiSpeciesState
from lens_tpu.environment.spatial import SpatialState

_STEP_RE = re.compile(r"^step_(\d+)$")


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: the tmp->rename commit protocol's last step.
    ``os.rename`` makes the new name visible, but the directory entry
    itself is metadata the filesystem may still hold in memory — on
    power loss an un-synced rename can roll back, leaving the old name
    (or nothing). Syncing the parent directory fd makes the rename
    durable. Best-effort on filesystems whose directory fds refuse
    fsync (some network mounts): losing the sync there degrades to the
    pre-round-17 guarantee, never corrupts."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _to_plain(state: Any) -> Any:
    """NamedTuples -> dicts so orbax sees vanilla containers.

    The kind is encoded in the key set (no string leaves — orbax stores
    array leaves): ``{spatial_colony, fields}`` / ``{agents, alive, key,
    step}`` / ``{pytree_value}``.
    """
    if isinstance(state, SpatialState):
        return {
            "spatial_colony": _to_plain(state.colony),
            "fields": state.fields,
        }
    if isinstance(state, MultiSpeciesState):
        return {
            "species_colonies": {
                name: _to_plain(cs) for name, cs in state.species.items()
            },
            "fields": state.fields,
        }
    if isinstance(state, ColonyState):
        return {
            "agents": state.agents,
            "alive": state.alive,
            "key": state.key,
            "step": state.step,
        }
    return {"pytree_value": state}


def _from_plain(plain: Any) -> Any:
    keys = set(plain)
    if keys == {"species_colonies", "fields"}:
        return MultiSpeciesState(
            species={
                name: _from_plain(cs)
                for name, cs in plain["species_colonies"].items()
            },
            fields=plain["fields"],
        )
    if keys == {"spatial_colony", "fields"}:
        return SpatialState(
            colony=_from_plain(plain["spatial_colony"]),
            fields=plain["fields"],
        )
    if keys == {"agents", "alive", "key", "step"}:
        return ColonyState(
            agents=plain["agents"],
            alive=plain["alive"],
            key=plain["key"],
            step=plain["step"],
        )
    if keys == {"pytree_value"}:
        return plain["pytree_value"]
    raise ValueError(f"unrecognized checkpoint key set {sorted(keys)}")


def save_tree(path: str, state: Any) -> str:
    """Crash-safe orbax save of ONE state tree at an arbitrary path
    (no step indexing). The commit protocol, in order: (1) orbax-save
    the full tree into ``<path>.tmp-save`` (orbax fsyncs the array
    files), (2) ``os.rename`` it into place — readers never see a torn
    tree, (3) **fsync the parent directory**, making the rename itself
    durable: without it a power loss can roll the directory entry back
    even though the data blocks were synced, and cross-host failover
    (docs/serving.md, "Cluster serving") trusts that a spill another
    host observed on the shared tier directory STAYS there. The serve
    layer's held-snapshot spill (``lens_tpu.serve.wal``) is the
    client: a ``hold_state`` request's pinned final state lands here
    at retirement, so a killed server's ``resubmit`` chain can
    continue from the exact bits after recovery. Single-process only
    (the serve layer's scheduler is one process per host; the
    multi-host promotion barrier lives in :meth:`Checkpointer.save`)."""
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-save"
    ocp.PyTreeCheckpointer().save(tmp, _to_plain(state), force=True)
    if os.path.exists(path):
        shutil.rmtree(path)
    os.rename(tmp, path)
    _fsync_dir(parent)
    return path


def restore_tree(path: str, device: Any = None) -> Any:
    """Inverse of :func:`save_tree` (typed states rebuilt).

    ``device`` (a ``jax.Device`` or any ``jax.sharding.Sharding``)
    re-pins the restored leaves there instead of the default
    placement. The mesh-serving failover client: a spill captured on a
    device that has since been quarantined must rehydrate onto a
    SURVIVING device — the original layout no longer exists — and the
    bytes are placement-independent, so the restored state is the
    spilled state wherever it lands."""
    plain = ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
    plain = jax.tree.map(jax.numpy.asarray, plain)
    if device is not None:
        plain = jax.device_put(plain, device)
    return _from_plain(plain)


class Checkpointer:
    """Save/restore simulation states under one directory."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.PyTreeCheckpointer()

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def save(self, state: Any, step: int, force: bool = True) -> str:
        """Write ``step_<n>`` atomically: orbax-save into a ``.tmp-save``
        sibling, then rename into place. A kill at ANY point leaves
        either the old final dir (or nothing) or the complete new one —
        never a torn ``step_<n>/`` that ``restore()`` would pick as
        latest. The tmp name is deterministic (not randomized) so every
        process of a multi-host save addresses the same directory, and a
        stale tmp from a previous kill is simply overwritten."""
        path = self._path(step)
        if os.path.exists(path) and not force:
            raise FileExistsError(
                f"checkpoint {path} already exists (force=False)"
            )
        tmp = f"{path}.tmp-save"
        self._ckpt.save(tmp, _to_plain(state), force=True)
        # Only the coordinator promotes (multi-host orbax saves share one
        # filesystem path; a per-process rename would race). On one host
        # this is always true.
        from lens_tpu.parallel.distributed import is_coordinator

        if is_coordinator():
            if os.path.exists(path):
                shutil.rmtree(path)
            os.rename(tmp, path)
            # the rename is only durable once the parent directory's
            # entry is synced (same protocol as save_tree)
            _fsync_dir(self.directory)
        if jax.process_count() > 1:
            # every host must observe the promotion before its save()
            # returns — without the barrier a non-coordinator could
            # read steps()/restore() ahead of the coordinator's rename
            # and miss the step it just saved
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"checkpoint_promote_{step}"
            )
        return path

    def steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, step: int | None = None) -> Any:
        """Restore the given (default: latest) step's state."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        plain = self._ckpt.restore(self._path(step))
        return _from_plain(jax.tree.map(jax.numpy.asarray, plain))
