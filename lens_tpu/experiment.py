"""The experiment layer: named composites -> running, emitting, resumable sims.

This is the rebuild of the reference's whole L4/L5 orchestration surface —
boot registry, control CLI, shepherd, experiment commands (reconstructed:
``lens/actor/boot.py``, ``control.py``, ``shepherd.py``, SURVEY.md §1
L4-L5, §3.1). The actor machinery itself (Kafka loops, OS processes) has
no TPU analogue — the colony IS one program — so what remains is exactly
what the user actually touched:

- a **registry** of named agent types/composites (models.composites),
- an **Experiment**: config dict -> built model -> segmented run loop
  with emission and checkpointing,
- a **CLI** (`python -m lens_tpu run|list|resume ...`) replacing
  `python -m lens.actor.control experiment ...`.

The run loop is segmented: ``checkpoint_every`` sim-seconds per jitted
scan segment, then emit (one device->host transfer per segment) and
orbax-save. Interrupting between segments loses at most one segment;
``Experiment.resume`` continues bitwise-identically (PRNG key and step
counter live in the state).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import numpy as np

from lens_tpu.checkpoint import Checkpointer
from lens_tpu.colony.colony import Colony, ColonyState
from lens_tpu.core.engine import Compartment
from lens_tpu.emit import Emitter, get_emitter
from lens_tpu.environment.spatial import SpatialColony, SpatialState
from lens_tpu.models.composites import composite_registry
from lens_tpu.utils.dicts import deep_merge

DEFAULT_CONFIG: Dict[str, Any] = {
    "composite": "grow_divide",     # name in models.composites registry
    "config": {},                    # composite factory config
    "n_agents": 1,                   # initially-alive rows
    "capacity": None,                # colony rows (None: composite default
                                     # for spatial models; n_agents*64 else)
    "division": True,                # watch ('global','divide') trigger
    "total_time": 100.0,             # sim seconds
    "timestep": 1.0,
    "emit_every": 1,                 # engine steps between emits
    "seed": 0,
    "emitter": {"type": "ram"},
    "checkpoint_dir": None,          # None: no checkpointing
    "checkpoint_every": None,        # sim-seconds per segment (None: one segment)
    "timeline": None,                # media timeline (spatial models only)
    "overrides": {},                 # initial-state overrides
    # Device mesh for sharded execution (spatial models only):
    # {"agents": N, "space": M} -> shard_map over a global (N x M) mesh
    # via parallel.ShardedSpatialColony; None -> single-program jit.
    # Multi-host bring-up (parallel.initialize) happens automatically.
    # Optional "stripe" (default True) deals initially-alive rows
    # round-robin across agent shards (per-shard division pools start
    # balanced); False keeps the contiguous row layout, making sharded
    # trajectories row-for-row comparable to unsharded ones.
    "mesh": None,
}


class Experiment:
    """One configured, runnable simulation (the reference's "experiment").

    Build from a config dict (deep-merged over ``DEFAULT_CONFIG``), then
    ``run()``. The composite name selects the model; everything else is
    scale/IO policy.
    """

    def __init__(self, config: Mapping[str, Any] | None = None):
        self.config = deep_merge(DEFAULT_CONFIG, config)
        name = self.config["composite"]
        if name not in composite_registry:
            raise ValueError(
                f"unknown composite {name!r}; known: {sorted(composite_registry)}"
            )
        built = composite_registry[name](self.config["config"])
        self.spatial: Optional[SpatialColony] = None
        if isinstance(built, tuple):  # (SpatialColony, Compartment)
            self.spatial, self.compartment = built
            self.colony = self.spatial.colony
        elif isinstance(built, Compartment):
            self.compartment = built
            capacity = self.config["capacity"] or max(
                int(self.config["n_agents"]) * 64, 64
            )
            trigger = (
                ("global", "divide")
                if self.config["division"]
                and ("global", "divide") in built.updaters
                else None
            )
            self.colony = Colony(built, capacity=capacity, division_trigger=trigger)
        else:
            raise TypeError(
                f"composite factory {name!r} returned {type(built)!r}"
            )
        self.runner = None
        if self.config["mesh"]:
            if self.spatial is None:
                raise ValueError(
                    "config 'mesh' needs a spatial composite (lattice model)"
                )
            from lens_tpu.parallel import (
                ShardedSpatialColony,
                global_mesh,
                initialize,
            )

            initialize()  # multi-host no-op on one host
            m = self.config["mesh"]
            self.runner = ShardedSpatialColony(
                self.spatial,
                global_mesh(
                    n_agents=int(m["agents"]), n_space=int(m.get("space", 1))
                ),
            )
        self.emitter: Emitter = get_emitter(dict(self.config["emitter"]))
        self.checkpointer = (
            Checkpointer(self.config["checkpoint_dir"])
            if self.config["checkpoint_dir"]
            else None
        )

    # -- state construction --------------------------------------------------

    def initial_state(self):
        key = jax.random.PRNGKey(int(self.config["seed"]))
        n = int(self.config["n_agents"])
        overrides = self.config["overrides"] or None
        if self.runner is not None:
            stripe = bool(self.config["mesh"].get("stripe", True))
            return self.runner.initial_state(
                n, key, stripe=stripe, overrides=overrides
            )
        if self.spatial is not None:
            return self.spatial.initial_state(n, key, overrides=overrides)
        return self.colony.initial_state(n, overrides=overrides, key=key)

    # -- running -------------------------------------------------------------

    def _segment_plan(self) -> Tuple[float, int]:
        total = float(self.config["total_time"])
        seg = self.config["checkpoint_every"]
        seg = float(seg) if seg else total
        n_segments = max(int(round(total / seg)), 1)
        return seg, n_segments

    def _run_segment(self, state, duration: float):
        dt = float(self.config["timestep"])
        emit_every = int(self.config["emit_every"])
        # Timeline event times are ABSOLUTE: a checkpointed segment (or a
        # resume) starting at t>0 must continue the timeline from where
        # the state's step counter says it is, not restart it.
        start_time = self._state_step(state) * dt
        if self.runner is not None:
            if self.config["timeline"] is not None:
                return self.runner.run_timeline(
                    state, self.config["timeline"], duration, dt,
                    emit_every, start_time=start_time,
                )
            return self.runner.run(state, duration, dt, emit_every)
        if self.spatial is not None:
            if self.config["timeline"] is not None:
                return self.spatial.run_timeline(
                    state, self.config["timeline"], duration, dt,
                    emit_every, start_time=start_time,
                )
            return self.spatial.run(state, duration, dt, emit_every)
        return self.colony.run(state, duration, dt, emit_every)

    def _state_step(self, state) -> int:
        cs = state.colony if isinstance(state, SpatialState) else state
        return int(cs.step)

    def run(self, state=None, verbose: bool = False):
        """Run ``total_time``, emitting and checkpointing per segment.

        Returns the final state. Timeseries access depends on the emitter
        (``RamEmitter.timeseries()``, or the log file on disk).
        """
        from lens_tpu.parallel.distributed import is_coordinator

        if state is None:
            state = self.initial_state()
        seg, n_segments = self._segment_plan()
        dt = float(self.config["timestep"])
        emit_every = int(self.config["emit_every"])
        for k in range(n_segments):
            t0 = time.perf_counter()
            state, trajectory = self._run_segment(state, seg)
            start_step = self._state_step(state) - int(round(seg / dt))
            times = (
                np.arange(1, int(round(seg / dt)) // emit_every + 1)
                * emit_every
                * dt
                + start_step * dt
            )
            # Multi-host: gather shards to every host (a collective — all
            # processes must participate), THEN only the coordinator
            # writes. Single-host this is the identity.
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                trajectory = multihost_utils.process_allgather(trajectory)
            if is_coordinator():
                self.emitter.emit_trajectory(trajectory, times=times)
            if self.checkpointer is not None:
                # Unguarded on purpose: orbax multi-host saves need every
                # process to participate (each writes its own shards).
                self.checkpointer.save(state, self._state_step(state))
            if verbose:
                # The alive count is a computation over globally sharded
                # state — every process must dispatch it; only the print
                # is coordinator-local.
                alive_now = int(np.asarray(jax.device_get(self.n_alive(state))))
                wall = time.perf_counter() - t0
                if is_coordinator():
                    print(
                        f"segment {k + 1}/{n_segments}: sim t="
                        f"{self._state_step(state) * dt:g}s  wall={wall:.2f}s  "
                        f"alive={alive_now}"
                    )
        self.emitter.flush()
        return state

    def n_alive(self, state):
        cs = state.colony if isinstance(state, SpatialState) else state
        return self.colony.n_alive(cs)

    def resume(self, verbose: bool = False):
        """Continue from the latest checkpoint through ``total_time``.

        The checkpointed step counter determines the remaining time; the
        continuation is bitwise-identical to an uninterrupted run.
        """
        if self.checkpointer is None:
            raise ValueError("resume() needs checkpoint_dir in the config")
        state = self.checkpointer.restore()
        done = self._state_step(state) * float(self.config["timestep"])
        remaining = float(self.config["total_time"]) - done
        if remaining <= 0:
            return state
        original = self.config["total_time"]
        self.config["total_time"] = remaining
        try:
            return self.run(state, verbose=verbose)
        finally:
            self.config["total_time"] = original

    def close(self) -> None:
        self.emitter.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
