"""The experiment layer: named composites -> running, emitting, resumable sims.

This is the rebuild of the reference's whole L4/L5 orchestration surface —
boot registry, control CLI, shepherd, experiment commands (reconstructed:
``lens/actor/boot.py``, ``control.py``, ``shepherd.py``, SURVEY.md §1
L4-L5, §3.1). The actor machinery itself (Kafka loops, OS processes) has
no TPU analogue — the colony IS one program — so what remains is exactly
what the user actually touched:

- a **registry** of named agent types/composites (models.composites),
- an **Experiment**: config dict -> built model -> segmented run loop
  with emission and checkpointing,
- a **CLI** (`python -m lens_tpu run|list|resume ...`) replacing
  `python -m lens.actor.control experiment ...`.

The run loop is segmented: ``checkpoint_every`` sim-seconds per jitted
scan segment, then emit (one device->host transfer per segment) and
orbax-save. Interrupting between segments loses at most one segment;
``Experiment.resume`` continues bitwise-identically (PRNG key and step
counter live in the state).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Mapping, NamedTuple, Optional, Tuple

import jax
import numpy as np

from lens_tpu.checkpoint import Checkpointer
from lens_tpu.colony.colony import Colony, ColonyState
from lens_tpu.core.engine import Compartment
from lens_tpu.emit import Emitter, get_emitter
from lens_tpu.environment.multispecies import (
    MultiSpeciesColony,
    MultiSpeciesState,
)
from lens_tpu.environment.spatial import SpatialColony, SpatialState
from lens_tpu.models.composites import composite_registry
from lens_tpu.utils.dicts import deep_merge
from lens_tpu.utils.hostio import copy_tree_to_host_async

DEFAULT_CONFIG: Dict[str, Any] = {
    "composite": "grow_divide",     # name in models.composites registry
    "config": {},                    # composite factory config
    "n_agents": 1,                   # initially-alive rows
    "capacity": None,                # colony rows (None: composite default
                                     # for spatial models; n_agents*64 else)
    "division": True,                # watch ('global','divide') trigger
    "total_time": 100.0,             # sim seconds
    "timestep": 1.0,
    "emit_every": 1,                 # engine steps between emits
    "seed": 0,
    "emitter": {"type": "ram"},
    "checkpoint_dir": None,          # None: no checkpointing
    "checkpoint_every": None,        # sim-seconds per segment (None: one segment)
    "timeline": None,                # media timeline (spatial models only)
    "overrides": {},                 # initial-state overrides
    # Device mesh for sharded execution (lattice composites — spatial
    # AND multi-species): {"agents": N, "space": M} -> shard_map over a
    # global (N x M) mesh via parallel.ShardedSpatialColony /
    # ShardedMultiSpeciesColony; None -> single-program jit.
    # Multi-host bring-up (parallel.initialize) happens automatically.
    # Optional "stripe" (default True) deals initially-alive rows
    # round-robin across agent shards (per-shard division pools start
    # balanced); False keeps the contiguous row layout, making sharded
    # trajectories row-for-row comparable to unsharded ones.
    "mesh": None,
    # Segment-boundary capacity growth (the reference grows its colony
    # without limit by spawning processes, SURVEY.md §3.3; a fixed-shape
    # colony re-allocates instead): when the free-row fraction drops to
    # or below ``free_frac`` at a segment boundary, the colony is
    # rebuilt at ``factor`` x capacity (Colony.expanded — pre-expansion
    # trajectory bitwise unchanged, lineage ids collision-free).
    # None disables. Requires checkpoint_every (segments) to react
    # mid-run. Composes with agent/space meshes on single- AND
    # multi-host runs (each shard pads its own block on device —
    # ``_expand_sharded``/``_expand_sharded_multi``) and with replicate
    # meshes (device-local pad, ``ShardedEnsemble.expanded``).
    # {"free_frac": 0.2, "factor": 2, "max_capacity": None}
    "auto_expand": None,
    # Segment-boundary division-pool rebalance (sharded runs only):
    # division pools are shard-local, so an inherited-fast lineage can
    # saturate its shard's pool while other shards hold free rows —
    # measured 52% population deficit vs unsharded in the adversarial
    # regime (tests/test_experiment.py::TestHeterogeneousDivergence). When
    # True (default), each segment boundary checks two global scalars
    # (division backlog, free rows); iff BOTH are nonzero the rows are
    # re-dealt round-robin by alive-rank (parallel.mesh.
    # rebalance_colony_rows) so every shard regains an equal share of
    # free rows — per species on a multi-species mesh. A no-op in
    # balanced runs (the gate never fires) and on unsharded/ensemble
    # paths. Needs checkpoint_every (segments) to react mid-run, like
    # auto_expand.
    "rebalance": True,
    # Replicate ensembles (colony.Ensemble): N independent copies of the
    # built sim stepped as ONE device program — the reference runs
    # replicates as N separate experiment clusters (SURVEY.md §3.3).
    # ``replicate_overrides`` (nested mapping, leaves [N, ...]) turns the
    # replicate axis into a parameter scan. Emission gains a [T, R, ...]
    # layout that analysis.report renders as fan charts. Composes with
    # checkpoint/resume, (for lattice composites) media timelines,
    # replicate-parallel meshes ({"mesh": {"replicates": N}} splits the
    # replicate axis over N devices — zero collectives, perfect scaling),
    # and auto_expand (every replicate's capacity grows when the TIGHTEST
    # pool runs low; single-species forms only); NOT with agent/space
    # meshes (gated at construction).
    "replicates": None,
    "replicate_overrides": {},
    # Poisson event sampler for the stochastic-expression stack
    # (ops.sampling): None defers to the composite/process defaults
    # ("hybrid", the batched fast path); "exact" pins every expression
    # process in the composite to jax.random.poisson — bitwise-
    # compatible with checkpoints recorded before the fast path (the
    # two samplers consume the PRNG key differently, so the knob that
    # produced a checkpoint must also resume it). Threaded into the
    # composite config as its top-level "sampler" key; an explicit
    # per-process sampler in "config" still wins.
    "sampler": None,
    # Agent<->lattice coupling implementation for lattice composites
    # (environment.spatial CouplingPlan): None defers to the composite
    # default ("fused", the one-pass gather/scatter over the precomputed
    # plan); "reference" pins the original per-molecule three-message
    # step — the numerics oracle the fused path is tested against, and
    # the A/B lever for BENCH_PHASES coupling records. Bitwise-equal
    # trajectories on CPU (so no resume sidecar is needed: a checkpoint
    # written under either knob resumes under either). Threaded into
    # the composite config as its top-level "coupling" key; an explicit
    # coupling in "config" wins.
    "coupling": None,
}


class BuiltModel(NamedTuple):
    """A composite factory's output, normalized to one shape.

    Exactly one of ``multi`` / ``spatial`` is set for lattice
    composites; ``colony`` is set for every single-species form (for a
    spatial composite it is the wrapped colony). ``sim`` is the
    steppable to hand to runners — the object exposing the colony-form
    protocol (``initial_state`` / ``step`` / ``emit_state``).
    """

    compartment: Any
    colony: Optional[Colony]
    spatial: Optional[SpatialColony]
    multi: Optional[MultiSpeciesColony]

    @property
    def sim(self):
        return self.multi or self.spatial or self.colony


def build_model(
    name: str,
    config: Mapping[str, Any] | None = None,
    *,
    capacity: int | None = None,
    n_agents: Any = 1,
    division: bool = True,
) -> BuiltModel:
    """Registry name + composite config -> a steppable sim.

    The one place composite-factory outputs (bare ``Compartment``,
    ``(SpatialColony, Compartment)``, ``(MultiSpeciesColony, {...})``)
    are normalized and wrapped in a ``Colony``; both ``Experiment`` and
    the serve layer (lens_tpu.serve) build through it so model
    construction cannot drift between the one-shot and serving paths.
    ``capacity``/``n_agents``/``division`` only matter for bare
    compartments (lattice composites size their own colonies).
    """
    if name not in composite_registry:
        raise ValueError(
            f"unknown composite {name!r}; known: {sorted(composite_registry)}"
        )
    built = composite_registry[name](config or {})
    if isinstance(built, tuple) and isinstance(built[0], MultiSpeciesColony):
        multi, compartments = built
        return BuiltModel(compartments, None, None, multi)
    if isinstance(built, tuple):  # (SpatialColony, Compartment)
        spatial, compartment = built
        return BuiltModel(compartment, spatial.colony, spatial, None)
    if isinstance(built, Compartment):
        cap = capacity or max(int(n_agents) * 64, 64)
        trigger = (
            ("global", "divide")
            if division and ("global", "divide") in built.updaters
            else None
        )
        from lens_tpu.models.composites import _death_trigger_of

        colony = Colony(
            built,
            capacity=cap,
            division_trigger=trigger,
            death_trigger=_death_trigger_of(built),
        )
        return BuiltModel(built, colony, None, None)
    raise TypeError(f"composite factory {name!r} returned {type(built)!r}")


def _jsonable(node):
    """Config tree -> plain JSON-serializable types (tuples -> lists,
    arrays -> lists, anything else -> str) for the log header's
    provenance record. The str fallback matters: a Path or other object
    in the config must degrade to readable provenance, not crash
    json.dumps inside the emitter header."""
    if isinstance(node, Mapping):
        return {str(k): _jsonable(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return [_jsonable(v) for v in node]
    if hasattr(node, "tolist"):
        return node.tolist()
    if isinstance(node, (str, int, float, bool)) or node is None:
        return node
    return str(node)


#: Module-level so the jit cache is hit across segment boundaries (a
#: fresh lambda per call would retrace the reduction every segment).
_count_free = jax.jit(lambda alive: (~alive).sum())

#: The rebalance gate's two replicated scalars: STARVED backlog and
#: global free rows. A triggered alive row is only evidence of a
#: suppressed division if its shard ALSO has zero free rows — division
#: claims free rows until the pool runs dry, so a shard that suppressed
#: anything this step ends the step with an empty pool. Counting any
#: ``alive & trigger`` row instead (the pre-round-7 gate) fires spurious
#: global re-deals for composites whose trigger variable survives a
#: successful division (a copy-style divider: both daughters inherit
#: the set trigger) — ADVICE r5 #4. Rows are block-partitioned
#: contiguously across the ``n_blocks`` agent shards, so the per-shard
#: view is a static reshape.
_backlog_and_free = jax.jit(
    lambda alive, trig, n_blocks: (
        (
            (alive & (trig > 0)).reshape(n_blocks, -1).sum(axis=-1)
            * ((~alive).reshape(n_blocks, -1).sum(axis=-1) == 0)
        ).sum(),
        (~alive).sum(),
    ),
    static_argnums=2,
)

#: Free rows of the TIGHTEST replicate (alive is [R, rows]) — the
#: ensemble auto_expand gate, as a replicated scalar.
_min_free_per_replicate = jax.jit(
    lambda alive: (~alive).sum(axis=-1).min()
)


class Experiment:
    """One configured, runnable simulation (the reference's "experiment").

    Build from a config dict (deep-merged over ``DEFAULT_CONFIG``), then
    ``run()``. The composite name selects the model; everything else is
    scale/IO policy.
    """

    def __init__(self, config: Mapping[str, Any] | None = None):
        self.config = deep_merge(DEFAULT_CONFIG, config)
        name = self.config["composite"]
        if name not in composite_registry:
            raise ValueError(
                f"unknown composite {name!r}; known: {sorted(composite_registry)}"
            )
        # ONE definition of "the mesh shards the replicate axis" — three
        # code paths branch on it and must agree.
        mesh_cfg = self.config["mesh"]
        replicate_mesh = bool(mesh_cfg) and set(mesh_cfg) == {"replicates"}
        if replicate_mesh:
            if self.config["replicates"] is None:
                raise ValueError(
                    "mesh={'replicates': N} needs 'replicates' set — a "
                    "replicate mesh without replicates would silently run "
                    "unsharded"
                )
            n_dev = mesh_cfg["replicates"]
            if not isinstance(n_dev, int) or isinstance(n_dev, bool) \
                    or n_dev < 1:
                raise ValueError(
                    f"mesh replicates must be an int >= 1, got {n_dev!r}"
                )
            # Multi-host bring-up must happen BEFORE the gates below read
            # jax.process_count() (pre-handshake it reads 1 and the
            # fail-at-construction guards would be dead letters).
            from lens_tpu.parallel import initialize

            initialize()
        if self.config["sampler"] is not None:
            # experiment-level sampler knob -> composite top-level key
            # (composites _thread_sampler it into their expression
            # processes; a sampler already set in "config" wins)
            self.config["config"] = deep_merge(
                {"sampler": self.config["sampler"]}, self.config["config"]
            )
        if self.config["coupling"] is not None:
            # same threading for the coupling-implementation knob
            # (lattice composites read it via _coupling_of; others
            # ignore the key)
            self.config["config"] = deep_merge(
                {"coupling": self.config["coupling"]}, self.config["config"]
            )
        built = build_model(
            name,
            self.config["config"],
            capacity=self.config["capacity"],
            n_agents=self.config["n_agents"],
            division=self.config["division"],
        )
        self.compartment = built.compartment
        self.spatial: Optional[SpatialColony] = built.spatial
        self.multi = built.multi  # MultiSpeciesColony composites (config 4)
        self.colony = built.colony
        if self.config["timeline"] is not None and self.spatial is None \
                and self.multi is None:
            # without this the run loop would fall through to the plain
            # colony path and silently drop the media schedule
            raise ValueError(
                "'timeline' needs a lattice composite (media timelines "
                "reset fields)"
            )
        # Replicates gates fire BEFORE any runner/distributed bring-up:
        # initialize() can block on multi-host peers, and a doomed config
        # must not get that far.
        self.ensemble = None
        if self.config["replicates"] is not None:
            r = self.config["replicates"]
            if not isinstance(r, int) or isinstance(r, bool) or r < 1:
                # truthiness would let 0 degrade to an unreplicated run
                # and a float silently truncate downstream
                raise ValueError(f"replicates must be an int >= 1, got {r!r}")
            if self.config["auto_expand"] and self.multi is not None:
                raise ValueError(
                    "'replicates' with 'auto_expand' on a multi-species "
                    "composite: per-species expansion factors are not "
                    "wired through the replicate axis"
                )
            if self.config["mesh"] and not replicate_mesh:
                raise ValueError(
                    "'replicates' composes with mesh={'replicates': N} "
                    "(replicate-parallel: the replicate axis splits over N "
                    "devices) — agent/space mesh axes shard the colony "
                    "axis instead; wrap parallel runners in "
                    "colony.Ensemble directly if you need both"
                )
        elif self.config["replicate_overrides"]:
            raise ValueError(
                "replicate_overrides without replicates: set "
                "'replicates': N to enable the scan axis"
            )
        self.runner = None
        if self.config["mesh"] and not replicate_mesh:
            if self.spatial is None and self.multi is None:
                raise ValueError(
                    "config 'mesh' needs a lattice composite (spatial "
                    "or multi-species model)"
                )
            from lens_tpu.parallel import (
                ShardedMultiSpeciesColony,
                ShardedSpatialColony,
                global_mesh,
                initialize,
            )

            initialize()  # multi-host no-op on one host
            m = self.config["mesh"]
            gm = global_mesh(
                n_agents=int(m["agents"]), n_space=int(m.get("space", 1))
            )
            if self.multi is not None:
                self.runner = ShardedMultiSpeciesColony(self.multi, gm)
            else:
                self.runner = ShardedSpatialColony(self.spatial, gm)
        # auto_expand is multi-host-safe on BOTH mesh forms: the
        # agent-mesh runner expands shard-locally on device
        # (_expand_sharded) and the replicate mesh pads device-locally
        # (ShardedEnsemble.expanded) — no construction guard needed.
        self.ensemble_runner = None
        if self.config["replicates"] is not None:
            from lens_tpu.colony.ensemble import Ensemble

            sim = self.multi or self.spatial or self.colony
            self.ensemble = Ensemble(sim, int(self.config["replicates"]))
            if replicate_mesh:
                from lens_tpu.parallel import ShardedEnsemble, initialize
                from lens_tpu.parallel.mesh import make_mesh

                initialize()
                self.ensemble_runner = ShardedEnsemble(
                    self.ensemble,
                    make_mesh(
                        n_agents=int(mesh_cfg["replicates"]), n_space=1
                    ),
                )
        # Experiment provenance rides the emitter: the log header records
        # the FULL experiment config (the reference stored experiment
        # documents beside the data in Mongo — SURVEY.md §3.5), so a log
        # is self-describing: `analyze` can report what produced it and
        # auto-detect scan axes from replicate_overrides.
        emitter_cfg = dict(self.config["emitter"])
        if "config" not in emitter_cfg:
            emitter_cfg["config"] = _jsonable(self.config)
        self.emitter: Emitter = get_emitter(emitter_cfg)
        self.checkpointer = (
            Checkpointer(self.config["checkpoint_dir"])
            if self.config["checkpoint_dir"]
            else None
        )

    @property
    def _ensemble_exec(self):
        """The object that executes replicate runs: the sharded runner
        when a replicate mesh is configured, else the plain Ensemble
        (identical surfaces)."""
        return self.ensemble_runner or self.ensemble

    def _rewrap_ensemble_runner(self):
        """Rebuild the replicate-parallel runner around the CURRENT
        ``self.ensemble`` (same mesh/axis) — required after anything that
        replaces the wrapped sim (capacity growth, checkpoint adoption),
        else runs would step a stale colony."""
        from lens_tpu.parallel import ShardedEnsemble

        old = self.ensemble_runner
        self.ensemble_runner = ShardedEnsemble(
            self.ensemble, old.mesh, old.axis
        )

    # -- state construction --------------------------------------------------

    def initial_state(self):
        key = jax.random.PRNGKey(int(self.config["seed"]))
        if self.multi is not None:
            n_cfg = self.config["n_agents"]
            if not isinstance(n_cfg, Mapping):
                raise ValueError(
                    "multi-species composites need n_agents as a "
                    'per-species dict, e.g. {"ecoli": 100, "scavenger": 50}'
                    " (the CLI accepts the same as JSON: "
                    "--n-agents '{\"ecoli\": 100, ...}')"
                )
            unknown = set(n_cfg) - set(self.multi.species)
            if unknown:
                # a typo would otherwise silently boot that species empty
                raise ValueError(
                    f"n_agents names unknown species {sorted(unknown)}; "
                    f"this composite has {sorted(self.multi.species)}"
                )
            counts = {k: int(v) for k, v in n_cfg.items()}
            if self.ensemble is not None:
                return self._ensemble_exec.initial_state(
                    counts,
                    key=key,
                    overrides=self.config["overrides"] or None,
                    replicate_overrides=self.config["replicate_overrides"]
                    or None,
                )
            if self.runner is not None:
                stripe = bool(self.config["mesh"].get("stripe", True))
                return self.runner.initial_state(
                    counts,
                    key,
                    stripe=stripe,
                    overrides=self.config["overrides"] or None,
                )
            return self.multi.initial_state(
                counts,
                key,
                overrides=self.config["overrides"] or None,
            )
        n = int(self.config["n_agents"])
        overrides = self.config["overrides"] or None
        if self.runner is not None:
            stripe = bool(self.config["mesh"].get("stripe", True))
            return self.runner.initial_state(
                n, key, stripe=stripe, overrides=overrides
            )
        if self.ensemble is not None:
            return self._ensemble_exec.initial_state(
                n,
                key=key,
                overrides=overrides,
                replicate_overrides=self.config["replicate_overrides"]
                or None,
            )
        if self.spatial is not None:
            return self.spatial.initial_state(n, key, overrides=overrides)
        return self.colony.initial_state(n, overrides=overrides, key=key)

    # -- running -------------------------------------------------------------

    def _segment_plan(self) -> Tuple[float, int]:
        total = float(self.config["total_time"])
        seg = self.config["checkpoint_every"]
        seg = float(seg) if seg else total
        n_segments = max(int(round(total / seg)), 1)
        return seg, n_segments

    def _run_segment(self, state, duration: float, start_step: int):
        dt = float(self.config["timestep"])
        emit_every = int(self.config["emit_every"])
        # Timeline event times are ABSOLUTE: a checkpointed segment (or a
        # resume) starting at t>0 must continue the timeline from where
        # it is. ``start_step`` is host-side bookkeeping (initial step +
        # elapsed segments) — reading the device counter here would force
        # a sync and serialize the pipelined emission below.
        start_time = start_step * dt
        if self.ensemble is not None:
            ens = self._ensemble_exec
            if self.config["timeline"] is not None:
                return ens.run_timeline(
                    state, self.config["timeline"], duration, dt,
                    emit_every, start_time=start_time,
                )
            return ens.run(state, duration, dt, emit_every)
        if self.runner is not None:
            if self.config["timeline"] is not None:
                return self.runner.run_timeline(
                    state, self.config["timeline"], duration, dt,
                    emit_every, start_time=start_time,
                )
            return self.runner.run(state, duration, dt, emit_every)
        if self.multi is not None:
            if self.config["timeline"] is not None:
                return self.multi.run_timeline(
                    state, self.config["timeline"], duration, dt,
                    emit_every, start_time=start_time,
                )
            return self.multi.run(state, duration, dt, emit_every)
        if self.spatial is not None:
            if self.config["timeline"] is not None:
                return self.spatial.run_timeline(
                    state, self.config["timeline"], duration, dt,
                    emit_every, start_time=start_time,
                )
            return self.spatial.run(state, duration, dt, emit_every)
        return self.colony.run(state, duration, dt, emit_every)

    def _state_step(self, state) -> int:
        if isinstance(state, MultiSpeciesState):
            # all species advance in lockstep inside one jitted step
            cs = next(iter(state.species.values()))
        else:
            cs = state.colony if isinstance(state, SpatialState) else state
        # Replicates advance in lockstep, so under an ensemble the step
        # counter is [R] with equal entries — read any one. On a
        # multi-host replicate mesh the counter is globally sharded;
        # device_get rejects non-addressable shards, so read a LOCAL one.
        arr = cs.step
        if getattr(arr, "is_fully_addressable", True) is False:
            arr = arr.addressable_shards[0].data
        return int(np.asarray(jax.device_get(arr)).reshape(-1)[0])

    # -- capacity growth -----------------------------------------------------

    def _maybe_expand(self, state):
        """Segment-boundary capacity check: expand when free rows run low.

        Host-side by design — the decision reads one scalar per segment,
        and the re-allocation (pad + recompile at the new shape) is rare
        and amortized over the whole next segment.
        """
        cfg = self.config["auto_expand"]
        if not cfg:
            return state
        factor = int(cfg.get("factor", 2))
        free_frac = float(cfg.get("free_frac", 0.2))
        max_cap = cfg.get("max_capacity")

        if self.ensemble is not None:
            cs = state.colony if isinstance(state, SpatialState) else state
            cap = int(cs.alive.shape[-1])
            if max_cap is not None and cap * factor > int(max_cap):
                return state
            # expand when the TIGHTEST replicate runs low — replicates
            # share one capacity, so the fullest pool decides. Jitted
            # reduction (replicated scalar), not device_get(alive):
            # multi-host replicate meshes cannot address the full mask.
            if int(_min_free_per_replicate(cs.alive)) > free_frac * cap:
                return state
            if self.ensemble_runner is not None:
                # device-local pad, sharding preserved — no host gather
                self.ensemble, state = self.ensemble_runner.expanded(
                    state, factor
                )
            else:
                self.ensemble, state = self.ensemble.expanded(state, factor)
            grown = self.ensemble.sim
            if self.spatial is not None:
                self.spatial = grown
                self.colony = grown.colony
            else:
                self.colony = grown
            if self.ensemble_runner is not None:
                # re-wrap only: the device-local expanded() above already
                # returned a correctly sharded state, and shard() would
                # host-materialize non-addressable shards on multi-host
                self._rewrap_ensemble_runner()
            return state

        def wants_growth(cs) -> bool:
            cap = int(cs.alive.shape[0])
            if max_cap is not None and cap * factor > int(max_cap):
                return False
            # jitted global reduction, not device_get(alive): the scalar
            # result is replicated, so the read works on a multi-host
            # mesh where the full alive mask is not locally addressable
            free = int(_count_free(cs.alive))
            return free <= free_frac * cap

        if self.multi is not None:
            factors = {
                name: factor if wants_growth(state.species[name]) else 1
                for name in self.multi.species
            }
            if any(f > 1 for f in factors.values()):
                if self.runner is not None:
                    state = self._expand_sharded_multi(state, factors)
                else:
                    self.multi, state = self.multi.expanded(state, factors)
            return state
        cs = state.colony if isinstance(state, SpatialState) else state
        if not wants_growth(cs):
            return state
        if self.runner is not None:
            return self._expand_sharded(state, factor)
        if self.spatial is not None:
            self.spatial, state = self.spatial.expanded(state, factor)
            self.colony = self.spatial.colony
        else:
            self.colony, state = self.colony.expanded(state, factor)
        return state

    def _maybe_rebalance(self, state):
        """Segment-boundary division-pool rebalance (sharded runner only).

        Reads two replicated scalars (multi-host-safe, like
        ``_maybe_expand``): the STARVED division backlog — triggered
        alive rows on shards whose free pool is exhausted, the only
        rows whose division can actually have been suppressed (see
        ``_backlog_and_free``) — and the global free-row count. Iff both
        are nonzero — a shard is starved while capacity exists elsewhere
        — rows are re-dealt round-robin by alive-rank. Triggered rows on
        shards that still hold free rows do NOT fire the gate: they
        divide next step locally (and a copy-style divider's surviving
        trigger would otherwise re-deal globally every segment).
        See ``parallel.mesh.rebalance_colony_rows`` for why this is
        biology-neutral and why it cannot be shard-local.
        """
        if not self.config["rebalance"] or self.runner is None:
            return state
        from lens_tpu.parallel.mesh import AGENTS_AXIS
        from lens_tpu.utils.dicts import get_path

        mesh = self.runner.mesh
        n_blocks = mesh.shape[AGENTS_AXIS]

        def balanced(cs, trigger_path):
            if trigger_path is None:
                return cs
            trig = get_path(cs.agents, trigger_path)
            starved, free = _backlog_and_free(cs.alive, trig, n_blocks)
            if int(starved) == 0 or int(free) == 0:
                return cs
            return self._rebalance_fn()(cs, n_blocks)

        if self.multi is not None:
            # per-species pools, per-species re-deals (species have
            # independent row spaces; the shared fields are untouched)
            return state._replace(
                species={
                    name: balanced(
                        state.species[name], sp.colony.division_trigger
                    )
                    for name, sp in self.multi.species.items()
                }
            )
        if self.colony.division_trigger is None:
            return state
        return state._replace(
            colony=balanced(state.colony, self.colony.division_trigger)
        )

    def _rebalance_fn(self):
        """One jitted re-deal program per Experiment (jit's own cache
        handles shape/species changes; a fresh jit() per call would
        retrace every segment). The output carries an explicit
        agent-axis sharding constraint so the re-dealt state keeps the
        runner's layout regardless of how the partitioner lowers the
        cross-shard gather."""
        fn = getattr(self, "_rebalance_jit", None)
        if fn is None:
            from lens_tpu.parallel.mesh import (
                colony_pspecs,
                mesh_shardings,
                rebalance_colony_rows,
            )

            mesh = self.runner.mesh

            def reb(cs, n_blocks):
                out = rebalance_colony_rows(cs, n_blocks)
                return jax.lax.with_sharding_constraint(
                    out, mesh_shardings(mesh, colony_pspecs(out))
                )

            fn = self._rebalance_jit = jax.jit(reb, static_argnums=1)
        return fn

    def _expand_sharded_multi(self, state, factors):
        """Per-species capacity growth under a device mesh — the
        multi-species counterpart of ``_expand_sharded``: each growing
        species pads shard-locally on device
        (:func:`~lens_tpu.parallel.mesh.expand_colony_rows_on_mesh`),
        the shared lattice fields are untouched, and the runner is
        rebuilt around the grown MultiSpeciesColony. Multi-host-safe for
        the same reasons as the single-species path."""
        from lens_tpu.environment.multispecies import MultiSpeciesColony
        from lens_tpu.parallel import ShardedMultiSpeciesColony
        from lens_tpu.parallel.mesh import expand_colony_rows_on_mesh

        mesh = self.runner.mesh
        step_now = self._state_step(state)
        new_species = {}
        new_states = {}
        for name, sp in self.multi.species.items():
            f = int(factors.get(name, 1))
            cs = state.species[name]
            if f <= 1:
                new_species[name] = sp
                new_states[name] = cs
                continue
            grown_colony = sp.colony.expanded_meta(step_now, f)
            new_states[name] = expand_colony_rows_on_mesh(
                cs, grown_colony, sp.colony.capacity, mesh
            )
            new_species[name] = sp.with_colony(grown_colony)
        self.multi = MultiSpeciesColony(
            new_species, self.multi.lattice,
            share_bins=self.multi.share_bins,
            coupling=self.multi.coupling,
        )
        self.runner = ShardedMultiSpeciesColony(self.multi, mesh)
        return state._replace(species=new_states)

    def _expand_sharded(self, state, factor: int):
        """Capacity growth under a device mesh, entirely on device: each
        agent shard pads its own block with its share of fresh rows
        (:func:`~lens_tpu.parallel.mesh.expand_colony_rows_on_mesh` —
        bitwise-equal to the old gather + interleave + re-place sequence,
        tested, but with no host gather and no collectives), then the
        runner is rebuilt at the new capacity. Multi-host safe: the only
        host-side reads are two scalars (the step counter, locally
        addressable on every host, and the alive count already read by
        ``_maybe_expand``); the watermark/id_offset logic is global by
        construction, so every host derives the identical grown colony."""
        from lens_tpu.parallel import ShardedSpatialColony
        from lens_tpu.parallel.mesh import expand_colony_rows_on_mesh

        old_cap = self.colony.capacity
        grown_colony = self.colony.expanded_meta(self._state_step(state), factor)
        mesh = self.runner.mesh
        new_cs = expand_colony_rows_on_mesh(
            state.colony, grown_colony, old_cap, mesh
        )
        self.spatial = self.spatial.with_colony(grown_colony)
        self.colony = grown_colony
        self.runner = ShardedSpatialColony(self.spatial, mesh)
        return state._replace(colony=new_cs)

    def _colony_meta_path(self) -> str:
        import os

        return os.path.join(self.config["checkpoint_dir"], "colony_meta.json")

    def _lp_solver_map(self) -> Dict[str, str]:
        """{process path: lp_solver} for every FBAMetabolism in the built
        model (multi-species paths are "<species>/<process>"). Recorded
        in the sidecar because switching solvers changes the packed
        lp_state warm-vector LENGTH — a checkpoint taken with one solver
        cannot restore through the other, and without this record the
        failure surfaces as an opaque shape mismatch deep in restore."""
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        def solvers(compartment, prefix=""):
            return {
                prefix + pname: str(proc.config["lp_solver"])
                for pname, proc in compartment.processes.items()
                if isinstance(proc, FBAMetabolism)
            }

        if self.multi is not None:
            out: Dict[str, str] = {}
            for sname, sp in self.multi.species.items():
                out.update(solvers(sp.colony.compartment, f"{sname}/"))
            return out
        return solvers(self.compartment)

    def _sampler_map(self) -> Dict[str, str]:
        """{process path: sampler} for every STOCHASTIC process carrying
        a Poisson-sampler knob (ops.sampling). Recorded in the sidecar
        because the two samplers consume the PRNG key differently: a
        sampler-switched resume restores cleanly but silently continues
        on a DIFFERENT trajectory than the run that wrote the
        checkpoint — the same silent-mismatch class the lp_solver
        record guards against, minus even the shape error."""

        def samplers(compartment, prefix=""):
            return {
                prefix + pname: proc.config["sampler"]
                for pname, proc in compartment.processes.items()
                if getattr(proc, "stochastic", False)
                and isinstance(proc.config.get("sampler"), str)
            }

        if self.multi is not None:
            out: Dict[str, str] = {}
            for sname, sp in self.multi.species.items():
                out.update(samplers(sp.colony.compartment, f"{sname}/"))
            return out
        return samplers(self.compartment)

    def _save_colony_meta(self) -> None:
        """Sidecar for resume: expansion changes capacity and the lineage
        id offset, neither of which is derivable from the config alone;
        ``lp_solvers`` records which LP engine shaped any packed
        warm-start state (see ``_lp_solver_map``)."""
        from lens_tpu.parallel.distributed import is_coordinator

        if not is_coordinator():
            return
        if self.multi is not None:
            meta = {
                "species": {
                    name: {
                        "capacity": sp.colony.capacity,
                        "id_offset": sp.colony.id_offset,
                    }
                    for name, sp in self.multi.species.items()
                }
            }
        else:
            meta = {
                "capacity": self.colony.capacity,
                "id_offset": self.colony.id_offset,
            }
        meta["lp_solvers"] = self._lp_solver_map()
        meta["samplers"] = self._sampler_map()
        with open(self._colony_meta_path(), "w") as f:
            json.dump(meta, f)

    def run(self, state=None, verbose: bool = False):
        """Run ``total_time``, emitting and checkpointing per segment.

        Returns the final state. Timeseries access depends on the emitter
        (``RamEmitter.timeseries()``, or the log file on disk).
        """
        from lens_tpu.parallel.distributed import is_coordinator

        if state is None:
            state = self.initial_state()
        seg, n_segments = self._segment_plan()
        dt = float(self.config["timestep"])
        emit_every = int(self.config["emit_every"])
        # Single-host, checkpoint-free emission is PIPELINED one segment
        # deep: segment k's trajectory starts its device->host DMA right
        # after segment k+1 is dispatched, and the (blocking) emit
        # happens while k+1 computes — the reference keeps emission off
        # the hot path by putting Mongo in another process (SURVEY.md
        # §3.5); here the overlap is dispatch-ordering + an async host
        # copy, and ALL step bookkeeping below stays host-side so
        # nothing forces an early device sync. With a checkpointer the
        # strict order (emit k, then save k) is kept: the save blocks on
        # segment k anyway, and deferring the emit past the save would
        # let a crash drop segment k from the log while resume continues
        # after it. Multi-host also keeps the strict order (the shard
        # allgather is a collective).
        pipelined = jax.process_count() == 1 and self.checkpointer is None
        steps_per_seg = int(round(seg / dt))
        step0 = self._state_step(state)
        self._pending = None  # (trajectory, times) not yet emitted
        try:
            for k in range(n_segments):
                t0 = time.perf_counter()
                start_step = step0 + k * steps_per_seg
                state, trajectory = self._run_segment(state, seg, start_step)
                times = (
                    np.arange(1, steps_per_seg // emit_every + 1)
                    * emit_every
                    * dt
                    + start_step * dt
                )
                if pipelined:
                    copy_tree_to_host_async(trajectory)
                    self._flush_pending()
                    self._pending = (trajectory, times)
                else:
                    if jax.process_count() > 1:
                        # Gather shards to every host (a collective — all
                        # processes must participate), THEN only the
                        # coordinator writes.
                        from jax.experimental import multihost_utils

                        trajectory = multihost_utils.process_allgather(
                            trajectory
                        )
                    if is_coordinator():
                        self.emitter.emit_trajectory(trajectory, times=times)
                # Rebalance before expansion: starved shards may only
                # need the free rows other shards already hold, in which
                # case growth can wait. Both before the checkpoint: the
                # saved state already has the new layout/capacity, so
                # resume continues from it.
                state = self._maybe_rebalance(state)
                state = self._maybe_expand(state)
                if self.checkpointer is not None:
                    # Unguarded on purpose: orbax multi-host saves need
                    # every process to participate (each writes its own
                    # shards).
                    self.checkpointer.save(state, self._state_step(state))
                    self._save_colony_meta()
                if verbose:
                    # The alive count is a computation over globally
                    # sharded state — every process must dispatch it; only
                    # the print is coordinator-local.
                    alive_now = int(
                        np.asarray(jax.device_get(self.n_alive(state)))
                    )
                    wall = time.perf_counter() - t0
                    if is_coordinator():
                        print(
                            f"segment {k + 1}/{n_segments}: sim t="
                            f"{self._state_step(state) * dt:g}s  "
                            f"wall={wall:.2f}s  alive={alive_now}"
                        )
        finally:
            # The trailing pipelined segment — flushed in `finally` so an
            # exception mid-run cannot silently drop an already-computed
            # segment from the record.
            try:
                self._flush_pending()
            except Exception:
                # a poisoned pending segment (e.g. the device error that
                # aborted the loop) must not mask the original exception
                # or block the flush of already-buffered records
                self._pending = None
            self.emitter.flush()
        return state

    def _flush_pending(self) -> None:
        from lens_tpu.parallel.distributed import is_coordinator

        pending, self._pending = getattr(self, "_pending", None), None
        if pending is not None and is_coordinator():
            self.emitter.emit_trajectory(pending[0], times=pending[1])

    def n_alive(self, state):
        if self.multi is not None:
            counts = self.multi.n_alive(state)
            return sum(counts.values())
        cs = state.colony if isinstance(state, SpatialState) else state
        return self.colony.n_alive(cs)

    def resume(self, verbose: bool = False):
        """Continue from the latest checkpoint through ``total_time``.

        The checkpointed step counter determines the remaining time; the
        continuation is bitwise-identical to an uninterrupted run.
        """
        if self.checkpointer is None:
            raise ValueError("resume() needs checkpoint_dir in the config")
        self._check_resume_sidecar()
        state = self.checkpointer.restore()
        self._adopt_restored_capacity(state)
        if self.ensemble_runner is not None:
            # restore() hands back host arrays; without re-placement, jit
            # would silently run the whole program on one device
            state = self.ensemble_runner.shard(state)
        done = self._state_step(state) * float(self.config["timestep"])
        remaining = float(self.config["total_time"]) - done
        if remaining <= 0:
            return state
        original = self.config["total_time"]
        self.config["total_time"] = remaining
        try:
            return self.run(state, verbose=verbose)
        finally:
            self.config["total_time"] = original

    def _check_resume_sidecar(self) -> None:
        """Fail a mismatched resume BEFORE restore, descriptively.

        Two recorded hazards: a switched ``lp_solver`` (the packed
        lp_state warm vector is sized per solver, so restoring through
        the wrong one dies as an opaque shape mismatch deep in orbax)
        and a switched Poisson ``sampler`` (restores cleanly but the
        trajectory silently diverges from the run that wrote the
        checkpoint — see ``_sampler_map``). An absent ``lp_solvers``
        key passes through (either solver may have written it); an
        absent ``samplers`` key defaults to "exact", the only stream
        that existed before the record."""
        import os

        meta_path = self._colony_meta_path()
        if not os.path.exists(meta_path):
            return
        with open(meta_path) as f:
            meta = json.load(f)

        def mismatches(saved, current):
            return {
                path: (was, current[path])
                for path, was in (saved or {}).items()
                if path in current and current[path] != was
            }

        bad = mismatches(meta.get("lp_solvers"), self._lp_solver_map())
        if bad:
            detail = "; ".join(
                f"{path}: checkpoint={was!r}, config={now!r}"
                for path, (was, now) in sorted(bad.items())
            )
            raise ValueError(
                f"lp_solver mismatch at resume ({detail}) — the packed "
                f"lp_state warm-start layout differs between solvers, so "
                f"this checkpoint cannot restore under the configured "
                f"solver; set metabolism lp_solver back to the "
                f"checkpoint's value (or start a fresh run)"
            )
        current_samplers = self._sampler_map()
        saved_samplers = meta.get("samplers")
        if saved_samplers is None:
            # Pre-round-6 sidecar: the exact (jax.random.poisson) stream
            # was the only implementation, so an absent record MEANS
            # "exact" — without this default, every old checkpoint would
            # silently resume on the new hybrid default stream, the
            # precise hazard this check exists to fail loudly on.
            saved_samplers = {path: "exact" for path in current_samplers}
        bad = mismatches(saved_samplers, current_samplers)
        if bad:
            detail = "; ".join(
                f"{path}: checkpoint={was!r}, config={now!r}"
                for path, (was, now) in sorted(bad.items())
            )
            raise ValueError(
                f"Poisson sampler mismatch at resume ({detail}) — the "
                f"samplers consume the PRNG key differently, so the "
                f"resumed trajectory would silently diverge from the run "
                f"that wrote this checkpoint; set 'sampler' back to the "
                f"checkpoint's value (or start a fresh run to switch)"
            )

    def _adopt_restored_capacity(self, state) -> None:
        """A checkpoint written after auto-expansion has more rows than
        the config builds: rebuild the colony at the restored capacity
        (with the sidecar's lineage id offset) before continuing. The
        step programs are shape-polymorphic, but the id minting stride
        is not — resuming a 2x state through a 1x colony would mint
        colliding lineage ids."""
        import os

        if self.multi is not None:
            self._check_restored_replicates(
                next(iter(state.species.values()))
            )
            self._adopt_restored_capacity_multi(state)
            return
        cs = state.colony if isinstance(state, SpatialState) else state
        self._check_restored_replicates(cs)
        # Row axis is LAST: an ensemble checkpoint's alive is [R, rows].
        cap = int(cs.alive.shape[-1])
        if cap == self.colony.capacity:
            return
        meta_path = self._colony_meta_path()
        if not os.path.exists(meta_path):
            raise ValueError(
                f"checkpoint has {cap} rows but the config builds "
                f"{self.colony.capacity}, and no colony_meta.json sidecar "
                f"records the expansion (was the checkpoint moved?)"
            )
        with open(meta_path) as f:
            meta = json.load(f)
        if "capacity" not in meta:
            raise ValueError(
                f"colony_meta.json at {meta_path} is not a single-species "
                f"sidecar (keys {sorted(meta)}) — was the checkpoint "
                f"directory reused from a multi-species run?"
            )
        if int(meta["capacity"]) != cap:
            raise ValueError(
                f"colony_meta.json says capacity {meta['capacity']} but the "
                f"checkpoint has {cap} rows"
            )
        grown = Colony(
            self.colony.compartment,
            cap,
            division_trigger=self.colony.division_trigger,
            id_offset=int(meta["id_offset"]),
            death_trigger=self.colony.death_trigger,
        )
        if self.spatial is not None:
            self.spatial = self.spatial.with_colony(grown)
            if self.runner is not None:
                from lens_tpu.parallel import ShardedSpatialColony

                self.runner = ShardedSpatialColony(
                    self.spatial, self.runner.mesh
                )
        self.colony = grown
        if self.ensemble is not None:
            # the Ensemble closed over the pre-adoption sim; re-wrap so
            # resumed replicate runs step the grown colony (stale wrap =
            # wrong id-minting stride, the exact bug adoption prevents)
            from lens_tpu.colony.ensemble import Ensemble

            self.ensemble = Ensemble(
                self.spatial or self.colony, self.ensemble.n_replicates
            )
            if self.ensemble_runner is not None:
                self._rewrap_ensemble_runner()

    def _check_restored_replicates(self, cs) -> None:
        """A checkpoint's replicate axis must match the resume config:
        alive is [rows] unreplicated, [R, rows] under an ensemble.
        Silently stepping a mismatched state produces shape errors deep
        in jit (or wrong dynamics) — fail loudly at restore instead."""
        ndim = int(cs.alive.ndim)
        if self.ensemble is None:
            if ndim != 1:
                raise ValueError(
                    f"checkpoint state has a replicate axis (alive is "
                    f"{ndim}-d) but the config does not set 'replicates' "
                    f"— resume with the run's original replicates value"
                )
            return
        r = self.ensemble.n_replicates
        if ndim != 2 or int(cs.alive.shape[0]) != r:
            have = (
                f"{int(cs.alive.shape[0])} replicates" if ndim == 2
                else "no replicate axis"
            )
            raise ValueError(
                f"config sets replicates={r} but the checkpoint has "
                f"{have} — resume with the run's original replicates "
                f"value"
            )

    def _adopt_restored_capacity_multi(self, state) -> None:
        import os

        caps = {
            # row axis LAST: an ensemble checkpoint's alive is [R, rows]
            name: int(cs.alive.shape[-1])
            for name, cs in state.species.items()
        }
        if caps == {
            name: sp.colony.capacity
            for name, sp in self.multi.species.items()
        }:
            return
        if self.ensemble is not None:
            # same stance as the single-species path: nothing legitimate
            # expands an ensemble checkpoint (auto_expand is gated off)
            raise ValueError(
                f"checkpoint species capacities {caps} differ from the "
                f"config's; with 'replicates' set, resume with the "
                f"capacities the run was checkpointed at"
            )
        meta_path = self._colony_meta_path()
        if not os.path.exists(meta_path):
            raise ValueError(
                f"checkpoint species capacities {caps} differ from the "
                f"config's, and no colony_meta.json sidecar records the "
                f"expansion (was the checkpoint moved?)"
            )
        with open(meta_path) as f:
            loaded = json.load(f)
        meta = loaded.get("species")
        if meta is None or set(meta) != set(self.multi.species):
            raise ValueError(
                f"colony_meta.json at {meta_path} does not describe this "
                f"composite's species {sorted(self.multi.species)} (found "
                f"{sorted(meta) if meta else 'a single-species sidecar'}) "
                f"— was the checkpoint directory reused or a species "
                f"renamed?"
            )
        # rebuild EVERY species whose capacity differs from the restored
        # state's, in either direction (a user may have edited the config
        # capacity since the checkpoint — the state, not the config, is
        # authoritative), at the sidecar's id offset (expanded() would
        # recompute a wrong offset from the config-sized colony)
        species = {}
        for name, sp in self.multi.species.items():
            if int(meta[name]["capacity"]) != caps[name]:
                raise ValueError(
                    f"colony_meta.json says {name} capacity "
                    f"{meta[name]['capacity']} but the checkpoint has "
                    f"{caps[name]} rows"
                )
            if caps[name] == sp.colony.capacity:
                species[name] = sp
                continue
            grown = Colony(
                sp.colony.compartment,
                caps[name],
                division_trigger=sp.colony.division_trigger,
                id_offset=int(meta[name]["id_offset"]),
                death_trigger=sp.colony.death_trigger,
            )
            species[name] = sp.with_colony(grown)
        self.multi = MultiSpeciesColony(
            species, self.multi.lattice,
            share_bins=self.multi.share_bins,
            coupling=self.multi.coupling,
        )
        if self.runner is not None:
            # the runner closed over the pre-adoption multi; a stale wrap
            # would mint lineage ids at the pre-expansion stride (the
            # same bug the single-species adoption path guards against)
            from lens_tpu.parallel import ShardedMultiSpeciesColony

            self.runner = ShardedMultiSpeciesColony(
                self.multi, self.runner.mesh
            )

    def close(self) -> None:
        self.emitter.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
