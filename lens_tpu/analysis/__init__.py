"""Offline analysis: load emitted experiments, render standard plots.

The reference's ``lens/analysis/`` scripts query MongoDB by experiment id
and render per-compartment timeseries, lattice field snapshots, and
multi-generation traces to PNGs (reconstructed: SURVEY.md §2 "Analysis",
§3.5). The rebuild reads the record-log emitter's files instead; the
analysis split (offline, out of the hot path, matplotlib) is identical.

All plotting is optional — every loader works headless; plot functions
import matplotlib lazily with the Agg backend so they run in CI.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from lens_tpu.emit.log import read_experiment, stack_records


def load(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load one experiment log -> (header, timeseries tree).

    Timeseries leaves are ``[T, ...]`` numpy arrays; the emit records'
    ``__time__`` key becomes ``timeseries["__time__"]`` of shape [T].
    """
    header, records = read_experiment(path)
    return header, stack_records(records)


def get_path(tree: Mapping, path: Sequence[str]) -> np.ndarray:
    node: Any = tree
    for key in path:
        node = node[key]
    return np.asarray(node)


def flatten_leaves(tree: Mapping, prefix=()) -> List[Tuple[Tuple[str, ...], np.ndarray]]:
    out = []
    for key, node in tree.items():
        if isinstance(node, Mapping):
            out.extend(flatten_leaves(node, prefix + (key,)))
        else:
            out.append((prefix + (key,), np.asarray(node)))
    return out


def alive_counts(timeseries: Mapping) -> np.ndarray:
    """Live-cell count over time from the colony ``alive`` mask [T, N]."""
    return np.asarray(timeseries["alive"]).sum(axis=-1)


def masked_agent_series(
    timeseries: Mapping, path: Sequence[str]
) -> np.ma.MaskedArray:
    """A per-agent variable [T, N] with dead rows masked out."""
    values = get_path(timeseries, path)
    alive = np.asarray(timeseries["alive"]).astype(bool)
    return np.ma.masked_array(values, mask=~alive)


def _plt():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def _times(timeseries: Mapping, length: int) -> np.ndarray:
    if "__time__" in timeseries:
        return np.asarray(timeseries["__time__"])
    return np.arange(length)


def plot_timeseries(
    timeseries: Mapping,
    paths: Sequence[Sequence[str]] | None = None,
    out_path: str = "out/timeseries.png",
    max_agents: int = 32,
) -> str:
    """Per-variable panels over time (the reference's standard compartment
    timeseries plot). Per-agent variables show up to ``max_agents``
    masked traces; scalars show one line."""
    plt = _plt()
    leaves = (
        [(tuple(p), get_path(timeseries, p)) for p in paths]
        if paths is not None
        else [
            (path, arr)
            for path, arr in flatten_leaves(timeseries)
            if path[0] not in ("alive", "fields", "__time__")
        ]
    )
    if not leaves:
        raise ValueError("nothing to plot")
    alive = np.asarray(timeseries.get("alive", None))
    n = len(leaves)
    cols = min(3, n)
    rows = (n + cols - 1) // cols
    fig, axes = plt.subplots(
        rows, cols, figsize=(5 * cols, 3 * rows), squeeze=False
    )
    for k, (path, arr) in enumerate(leaves):
        ax = axes[k // cols][k % cols]
        t = _times(timeseries, arr.shape[0])
        if arr.ndim == 1:
            ax.plot(t, arr)
        else:
            flat = arr.reshape(arr.shape[0], -1)
            take = min(flat.shape[1], max_agents)
            data = flat[:, :take]
            if alive is not None and alive.shape == flat.shape:
                data = np.ma.masked_array(data, mask=~alive[:, :take].astype(bool))
            ax.plot(t, data, alpha=0.6, linewidth=0.8)
        ax.set_title(SEP_TITLE.join(path), fontsize=9)
        ax.set_xlabel("time (s)", fontsize=8)
    for k in range(n, rows * cols):
        axes[k // cols][k % cols].axis("off")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


SEP_TITLE = "."


def plot_colony_growth(
    timeseries: Mapping, out_path: str = "out/colony_growth.png"
) -> str:
    """Live-cell count over time (the multi-generation trace)."""
    plt = _plt()
    counts = alive_counts(timeseries)
    t = _times(timeseries, counts.shape[0])
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(t, counts)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("live cells")
    ax.set_title("colony growth")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def plot_field_snapshots(
    timeseries: Mapping,
    molecule_index: int = 0,
    n_snapshots: int = 4,
    out_path: str = "out/field_snapshots.png",
    locations: Optional[np.ndarray] = None,
    dx: float = 1.0,
) -> str:
    """Lattice field heatmaps at evenly spaced times (+ optional cell
    overlay) — the reference's lattice snapshot/animation plot."""
    plt = _plt()
    fields = np.asarray(timeseries["fields"])  # [T, M, H, W]
    steps = np.linspace(0, fields.shape[0] - 1, n_snapshots).astype(int)
    t = _times(timeseries, fields.shape[0])
    vmin = fields[:, molecule_index].min()
    vmax = fields[:, molecule_index].max()
    fig, axes = plt.subplots(
        1, n_snapshots, figsize=(4 * n_snapshots, 3.6), squeeze=False
    )
    for k, s in enumerate(steps):
        ax = axes[0][k]
        im = ax.imshow(
            fields[s, molecule_index],
            origin="lower",
            vmin=vmin,
            vmax=vmax,
            cmap="viridis",
        )
        if locations is not None:
            alive = np.asarray(timeseries["alive"])[s].astype(bool)
            # locations [T, N, 2] are (row, col) in um; divide by dx for
            # bin coordinates; imshow axes are (col=x, row=y)
            pts = np.asarray(locations)[s][alive] / dx
            ax.scatter(pts[:, 1], pts[:, 0], s=2, c="red", alpha=0.6)
        ax.set_title(f"t={float(t[s]):g}s")
        fig.colorbar(im, ax=ax, shrink=0.8)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


__all__ = [
    "load",
    "alive_counts",
    "masked_agent_series",
    "plot_timeseries",
    "plot_colony_growth",
    "plot_field_snapshots",
    "flatten_leaves",
    "get_path",
]
