"""Offline analysis: load emitted experiments, render standard plots.

The reference's ``lens/analysis/`` scripts query MongoDB by experiment id
and render per-compartment timeseries, lattice field snapshots, and
multi-generation traces to PNGs (reconstructed: SURVEY.md §2 "Analysis",
§3.5). The rebuild reads the record-log emitter's files instead; the
analysis split (offline, out of the hot path, matplotlib) is identical.

All plotting is optional — every loader works headless; plot functions
import matplotlib lazily with the Agg backend so they run in CI.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from lens_tpu.emit.log import read_experiment, stack_records


def load(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Load one experiment log -> (header, timeseries tree).

    Timeseries leaves are ``[T, ...]`` numpy arrays; the emit records'
    ``__time__`` key becomes ``timeseries["__time__"]`` of shape [T].
    """
    header, records = read_experiment(path)
    return header, stack_records(records)


def load_many(
    directory: str, pattern: str = "*.lens"
) -> Dict[str, Dict[str, Any]]:
    """Load a directory of per-trial emit logs into one trial-indexed
    timeseries tree: ``{log stem: timeseries}``, stems sorted — the
    layout a sweep's ``save_trajectories`` writes
    (``trials/trial_00042.lens``) and a serve out_dir's per-request
    logs share.

    A fleet directory is allowed to be ragged: a killed sweep leaves
    missing trials, a killed writer leaves a truncated or torn tail.
    Cleanly-truncated logs load their complete records (the framing's
    at-most-one-lost-record contract); logs that are corrupt beyond
    truncation, or hold no complete data records, are SKIPPED with a
    ``UserWarning`` naming the file — one bad trial must not take down
    the analysis of the other thousand.
    """
    import fnmatch
    import warnings

    from lens_tpu.emit.log import is_header, is_segment, expand_segment

    if not os.path.isdir(directory):
        raise NotADirectoryError(f"{directory!r} is not a directory")
    out: Dict[str, Dict[str, Any]] = {}
    for name in sorted(os.listdir(directory)):
        if not fnmatch.fnmatch(name, pattern):
            continue
        path = os.path.join(directory, name)
        records: List[Dict[str, Any]] = []
        try:
            # incremental consumption: a mid-log ValueError (corruption
            # past truncation) still keeps every record before it
            from lens_tpu.emit.log import read_records

            for record in read_records(path):
                if is_header(record):
                    continue
                if is_segment(record):
                    records.extend(expand_segment(record))
                else:
                    records.append(record)
        except (ValueError, OSError) as e:
            if not records:
                warnings.warn(
                    f"load_many: skipping unreadable log {path}: {e}"
                )
                continue
            warnings.warn(
                f"load_many: {path} is corrupt after "
                f"{len(records)} records ({e}); keeping the readable "
                f"prefix"
            )
        if not records:
            warnings.warn(
                f"load_many: skipping {path}: no complete data records "
                f"(trial still being written, or killed before its "
                f"first emit?)"
            )
            continue
        out[os.path.splitext(name)[0]] = stack_records(records)
    return out


def get_path(tree: Mapping, path: Sequence[str]) -> np.ndarray:
    node: Any = tree
    for key in path:
        node = node[key]
    return np.asarray(node)


def flatten_leaves(tree: Mapping, prefix=()) -> List[Tuple[Tuple[str, ...], np.ndarray]]:
    out = []
    for key, node in tree.items():
        if isinstance(node, Mapping):
            out.extend(flatten_leaves(node, prefix + (key,)))
        else:
            out.append((prefix + (key,), np.asarray(node)))
    return out


def alive_counts(timeseries: Mapping) -> np.ndarray:
    """Live-cell count over time from the colony ``alive`` mask [T, N]."""
    return np.asarray(timeseries["alive"]).sum(axis=-1)


def masked_agent_series(
    timeseries: Mapping, path: Sequence[str]
) -> np.ma.MaskedArray:
    """A per-agent variable [T, N] with dead rows masked out."""
    values = get_path(timeseries, path)
    alive = np.asarray(timeseries["alive"]).astype(bool)
    return np.ma.masked_array(values, mask=~alive)


def _plt():
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    return plt


def _times(timeseries: Mapping, length: int) -> np.ndarray:
    if "__time__" in timeseries:
        return np.asarray(timeseries["__time__"])
    return np.arange(length)


def plot_timeseries(
    timeseries: Mapping,
    paths: Sequence[Sequence[str]] | None = None,
    out_path: str = "out/timeseries.png",
    max_agents: int = 32,
) -> str:
    """Per-variable panels over time (the reference's standard compartment
    timeseries plot). Per-agent variables show up to ``max_agents``
    masked traces; scalars show one line."""
    plt = _plt()
    leaves = (
        [(tuple(p), get_path(timeseries, p)) for p in paths]
        if paths is not None
        else [
            (path, arr)
            for path, arr in flatten_leaves(timeseries)
            if path[0] not in ("alive", "fields", "lineage", "__time__")
        ]
    )
    if not leaves:
        raise ValueError("nothing to plot")
    alive = timeseries.get("alive")
    alive_flat = (
        np.asarray(alive).reshape(np.asarray(alive).shape[0], -1)
        if alive is not None
        else None
    )
    n = len(leaves)
    cols = min(3, n)
    rows = (n + cols - 1) // cols
    fig, axes = plt.subplots(
        rows, cols, figsize=(5 * cols, 3 * rows), squeeze=False
    )
    for k, (path, arr) in enumerate(leaves):
        ax = axes[k // cols][k % cols]
        t = _times(timeseries, arr.shape[0])
        if arr.ndim == 1:
            ax.plot(t, arr)
        else:
            flat = arr.reshape(arr.shape[0], -1)
            take = min(flat.shape[1], max_agents)
            data = flat[:, :take]
            # mask dead rows whenever the leaf flattens to the alive
            # layout (covers both [T, N] and ensemble [T, R, N] leaves)
            if alive_flat is not None and alive_flat.shape == flat.shape:
                data = np.ma.masked_array(
                    data, mask=~alive_flat[:, :take].astype(bool)
                )
            ax.plot(t, data, alpha=0.6, linewidth=0.8)
        ax.set_title(SEP_TITLE.join(path), fontsize=9)
        ax.set_xlabel("time (s)", fontsize=8)
    for k in range(n, rows * cols):
        axes[k // cols][k % cols].axis("off")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


SEP_TITLE = "."


def plot_colony_growth(
    timeseries: Mapping, out_path: str = "out/colony_growth.png"
) -> str:
    """Live-cell count over time (the multi-generation trace)."""
    plt = _plt()
    counts = alive_counts(timeseries)
    t = _times(timeseries, counts.shape[0])
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(t, counts)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("live cells")
    ax.set_title("colony growth")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def _snapshot_grid(
    timeseries: Mapping,
    molecule_index: int,
    n_snapshots: int,
    out_path: str,
    overlay=None,
) -> str:
    """Shared snapshot machinery: evenly spaced field heatmaps with a
    per-snapshot ``overlay(ax, step_index, k)`` hook, one colorbar each."""
    plt = _plt()
    fields = np.asarray(timeseries["fields"])  # [T, M, H, W]
    steps = np.linspace(0, fields.shape[0] - 1, n_snapshots).astype(int)
    t = _times(timeseries, fields.shape[0])
    vmin = fields[:, molecule_index].min()
    vmax = fields[:, molecule_index].max()
    fig, axes = plt.subplots(
        1, n_snapshots, figsize=(4 * n_snapshots, 3.8), squeeze=False
    )
    for k, s in enumerate(steps):
        ax = axes[0][k]
        im = ax.imshow(
            fields[s, molecule_index],
            origin="lower",
            vmin=vmin,
            vmax=vmax,
            cmap="viridis",
        )
        if overlay is not None:
            overlay(ax, int(s), k)
        ax.set_title(f"t={float(t[s]):g}s")
        fig.colorbar(im, ax=ax, shrink=0.8)
    handles, labels = axes[0][0].get_legend_handles_labels()
    if labels:
        fig.legend(handles, labels, loc="upper right", fontsize=8)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def plot_field_snapshots(
    timeseries: Mapping,
    molecule_index: int = 0,
    n_snapshots: int = 4,
    out_path: str = "out/field_snapshots.png",
    locations: Optional[np.ndarray] = None,
    dx: float = 1.0,
) -> str:
    """Lattice field heatmaps at evenly spaced times (+ optional cell
    overlay) — the reference's lattice snapshot/animation plot."""

    def overlay(ax, s, k):
        if locations is None:
            return
        alive = np.asarray(timeseries["alive"])[s].astype(bool)
        # locations [T, N, 2] are (row, col) in um; divide by dx for
        # bin coordinates; imshow axes are (col=x, row=y)
        pts = np.asarray(locations)[s][alive] / dx
        ax.scatter(pts[:, 1], pts[:, 0], s=2, c="red", alpha=0.6)

    return _snapshot_grid(
        timeseries, molecule_index, n_snapshots, out_path, overlay
    )


def plot_species_snapshots(
    timeseries: Mapping,
    species_locations: Mapping[str, Sequence[str]] | None = None,
    molecule_index: int = 0,
    n_snapshots: int = 4,
    out_path: str = "out/species_snapshots.png",
    dx: float = 1.0,
) -> str:
    """Mixed-species field snapshots: one field heatmap per time with
    EVERY species' live cells overlaid in a distinct color (the
    reference's multi-agent-type lattice snapshot).

    Expects a MultiSpeciesColony trajectory: per-species subtrees with
    their own ``alive`` masks, plus ``fields``. ``species_locations``
    maps species name -> path to its [T, N, 2] location leaf WITHIN the
    species subtree (default ``("boundary", "location")`` for all).
    """
    plt = _plt()
    names = [
        k for k in timeseries.keys() if k not in ("fields", "__time__")
    ]
    colors = plt.cm.tab10.colors

    def overlay(ax, s, k):
        for c, name in enumerate(names):
            sub = timeseries[name]
            path = (
                tuple(species_locations[name])
                if species_locations and name in species_locations
                else ("boundary", "location")
            )
            locs = get_path(sub, path)[s]
            alive = np.asarray(sub["alive"])[s].astype(bool)
            pts = locs[alive] / dx
            ax.scatter(
                pts[:, 1], pts[:, 0], s=4,
                color=colors[c % len(colors)],
                label=name if k == 0 else None, alpha=0.8,
            )

    return _snapshot_grid(
        timeseries, molecule_index, n_snapshots, out_path, overlay
    )


def plot_expression_heatmap(
    timeseries: Mapping,
    gene_names: Sequence[str],
    counts_path: Sequence[str] = ("counts", "protein"),
    agent: int = 0,
    out_path: str = "out/expression_heatmap.png",
) -> str:
    """Genes x time heatmap of one agent's expression counts — the
    regulated-genome view (which operons are on under which media)."""
    plt = _plt()
    values = get_path(timeseries, counts_path)  # [T, N, G] or [T, G]
    if values.ndim == 3:
        values = values[:, agent, :]
    t = _times(timeseries, values.shape[0])
    fig, ax = plt.subplots(
        figsize=(8, max(3.0, 0.18 * len(gene_names)))
    )
    im = ax.imshow(
        values.T, aspect="auto", origin="lower", cmap="magma",
        extent=[float(t[0]), float(t[-1]), -0.5, len(gene_names) - 0.5],
    )
    ax.set_yticks(range(len(gene_names)))
    ax.set_yticklabels(gene_names, fontsize=6)
    ax.set_xlabel("time (s)")
    ax.set_title(SEP_TITLE.join(counts_path))
    fig.colorbar(im, ax=ax, shrink=0.8, label="count")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def plot_reaction_fluxes(
    timeseries: Mapping,
    reaction_names: Sequence[str],
    fluxes_path: Sequence[str] = ("fluxes", "reaction_fluxes"),
    reactions: Sequence[str] | None = None,
    agent: int = 0,
    out_path: str = "out/reaction_fluxes.png",
) -> str:
    """Selected FBA reaction fluxes over time for one agent — the
    metabolic-mode view (respiration vs overflow vs shunt etc.)."""
    plt = _plt()
    values = get_path(timeseries, fluxes_path)  # [T, N, R] or [T, R]
    if values.ndim == 3:
        values = values[:, agent, :]
    t = _times(timeseries, values.shape[0])
    wanted = list(reactions) if reactions else list(reaction_names)
    index = {name: j for j, name in enumerate(reaction_names)}
    unknown = [n for n in wanted if n not in index]
    if unknown:
        raise KeyError(
            f"reactions {unknown} not in reaction_names "
            f"({sorted(index)})"
        )
    fig, ax = plt.subplots(figsize=(8, 4.2))
    for name in wanted:
        ax.plot(t, values[:, index[name]], linewidth=1.1, label=name)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("flux")
    ax.axhline(0.0, color="gray", linewidth=0.5)
    ax.legend(fontsize=7, ncol=2)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


# -- lineage ------------------------------------------------------------------


def lineage_table(timeseries: Mapping) -> Dict[int, Dict[str, Any]]:
    """Reconstruct the lineage tree from an emitted trajectory.

    Uses the colony layer's framework-level lineage emit
    (``timeseries["lineage"]``: cell_id/parent_id/birth_step per row per
    emit): every id that was ever live becomes one node. Returns
    ``{cell_id: {parent, birth_step, row, t_first, t_last, generation,
    children}}``. Generations walk parent chains; a parent that was never
    observed live (divided away between sparse emits) still appears as a
    node (``observed=False``) so chains never break.
    """
    lin = timeseries["lineage"]
    cell_id = np.asarray(lin["cell_id"])      # [T, N]
    parent_id = np.asarray(lin["parent_id"])  # [T, N]
    birth = np.asarray(lin["birth_step"])     # [T, N]
    alive = np.asarray(timeseries["alive"]).astype(bool)
    t = _times(timeseries, cell_id.shape[0])

    table: Dict[int, Dict[str, Any]] = {}
    for s in range(cell_id.shape[0]):
        for row in np.nonzero(alive[s])[0]:
            cid = int(cell_id[s, row])
            node = table.get(cid)
            if node is None:
                table[cid] = {
                    "parent": int(parent_id[s, row]),
                    "birth_step": int(birth[s, row]),
                    "row": int(row),
                    "t_first": float(t[s]),
                    "t_last": float(t[s]),
                    "observed": True,
                    "children": [],
                }
            else:
                node["t_last"] = float(t[s])
    # Materialize ONE placeholder node per missing parent (a cell that
    # divided away entirely between sparse emits): its own ancestry is
    # unknowable from the trajectory, so the chain is truncated there
    # (parent=-1) rather than walked further.
    for cid in list(table):
        pid = table[cid]["parent"]
        if pid != -1 and pid not in table:
            table[pid] = {
                "parent": -1,  # unknown further back
                "birth_step": 0,
                "row": -1,
                "t_first": float("nan"),
                "t_last": float("nan"),
                "observed": False,
                "children": [],
            }
    for cid, node in table.items():
        pid = node["parent"]
        if pid != -1 and pid in table:
            table[pid]["children"].append(cid)

    def generation(cid: int, seen=()) -> int:
        node = table[cid]
        if "generation" in node:
            return node["generation"]
        pid = node["parent"]
        g = 0 if (pid == -1 or pid not in table or pid in seen) else (
            generation(pid, seen + (cid,)) + 1
        )
        node["generation"] = g
        return g

    for cid in table:
        generation(cid)
    return table


def ancestry(table: Mapping[int, Mapping], cell: int) -> List[int]:
    """Root-first chain of ids from a founder down to ``cell``."""
    chain = [cell]
    while True:
        pid = table[chain[-1]]["parent"]
        if pid == -1 or pid not in table:
            break
        chain.append(pid)
    return chain[::-1]


def plot_lineage(
    timeseries: Mapping,
    out_path: str = "out/lineage.png",
    max_founders: int = 16,
    table: Optional[Dict[int, Dict[str, Any]]] = None,
) -> str:
    """The lineage tree: one horizontal life-line per cell (birth -> last
    seen), vertical connectors at divisions — the reference's
    multi-generation trace, reconstructed from ids instead of per-process
    bookkeeping. Pass a prebuilt ``lineage_table`` to skip rebuilding it.
    """
    plt = _plt()
    if table is None:
        table = lineage_table(timeseries)
    founders = sorted(
        cid for cid, n in table.items()
        if n["parent"] == -1 or n["parent"] not in table
    )[:max_founders]

    ys: Dict[int, float] = {}
    next_leaf = [0.0]

    def layout(cid: int) -> float:
        node = table[cid]
        kids = [k for k in node["children"] if k in table]
        if not kids:
            ys[cid] = next_leaf[0]
            next_leaf[0] += 1.0
        else:
            ys[cid] = float(np.mean([layout(k) for k in kids]))
        return ys[cid]

    fig, ax = plt.subplots(figsize=(8, 5))
    for f in founders:
        layout(f)
    for cid, y in ys.items():
        node = table[cid]
        if not node["observed"]:
            continue
        color = plt.cm.viridis(
            (node["generation"] % 8) / 8.0
        )
        ax.plot(
            [node["t_first"], node["t_last"]], [y, y],
            color=color, linewidth=1.2,
        )
        for k in node["children"]:
            if k in ys and table[k]["observed"]:
                ax.plot(
                    [table[k]["t_first"]] * 2, [y, ys[k]],
                    color="gray", linewidth=0.6, alpha=0.7,
                )
    ax.set_xlabel("time (s)")
    ax.set_ylabel("lineage position")
    ax.set_title(
        f"lineage tree ({len(ys)} cells, "
        f"{max(n['generation'] for n in table.values()) + 1} generations)"
    )
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def plot_generation_trace(
    timeseries: Mapping,
    path: Sequence[str],
    cell: Optional[int] = None,
    out_path: str = "out/generation_trace.png",
    table: Optional[Dict[int, Dict[str, Any]]] = None,
) -> str:
    """One variable followed through a cell's whole ancestry: each
    ancestor's segment plotted over its lifetime, division times marked.
    ``cell`` defaults to a deepest-generation cell. Pass a prebuilt
    ``lineage_table`` to skip rebuilding it."""
    plt = _plt()
    if table is None:
        table = lineage_table(timeseries)
    if cell is None:
        cell = max(table, key=lambda c: table[c]["generation"])
    chain = [c for c in ancestry(table, cell) if table[c]["observed"]]
    values = get_path(timeseries, path)  # [T, N]
    lin_id = np.asarray(timeseries["lineage"]["cell_id"])
    alive = np.asarray(timeseries["alive"]).astype(bool)
    t = _times(timeseries, values.shape[0])

    fig, ax = plt.subplots(figsize=(8, 4))
    for cid in chain:
        row = table[cid]["row"]
        sel = alive[:, row] & (lin_id[:, row] == cid)
        if not sel.any():
            continue
        ax.plot(t[sel], values[sel, row], linewidth=1.2, label=f"id {cid}")
        ax.axvline(t[sel][-1], color="gray", linewidth=0.5, alpha=0.5)
    ax.set_xlabel("time (s)")
    ax.set_ylabel(SEP_TITLE.join(path))
    ax.set_title(
        f"{SEP_TITLE.join(path)} across {len(chain)} generations"
    )
    if len(chain) <= 12:
        ax.legend(fontsize=7)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def animate_fields(
    timeseries: Mapping,
    molecule_index: int = 0,
    out_path: str = "out/fields.gif",
    locations: Optional[np.ndarray] = None,
    dx: float = 1.0,
    fps: int = 8,
) -> str:
    """Animated lattice field (+ optional live-cell overlay) — the
    reference's field animation, written as a GIF via Pillow."""
    plt = _plt()
    from matplotlib.animation import FuncAnimation, PillowWriter

    fields = np.asarray(timeseries["fields"])  # [T, M, H, W]
    t = _times(timeseries, fields.shape[0])
    vmin = float(fields[:, molecule_index].min())
    vmax = float(fields[:, molecule_index].max())
    fig, ax = plt.subplots(figsize=(5, 4.2))
    im = ax.imshow(
        fields[0, molecule_index], origin="lower",
        vmin=vmin, vmax=vmax, cmap="viridis",
    )
    fig.colorbar(im, ax=ax, shrink=0.85)
    scat = None
    if locations is not None:
        scat = ax.scatter([], [], s=3, c="red", alpha=0.7)
    title = ax.set_title("")

    def update(s):
        im.set_data(fields[s, molecule_index])
        title.set_text(f"t={float(t[s]):g}s")
        artists = [im, title]
        if scat is not None:
            alive = np.asarray(timeseries["alive"])[s].astype(bool)
            pts = np.asarray(locations)[s][alive] / dx
            scat.set_offsets(pts[:, ::-1])  # (col=x, row=y)
            artists.append(scat)
        return artists

    anim = FuncAnimation(fig, update, frames=fields.shape[0], blit=False)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    anim.save(out_path, writer=PillowWriter(fps=fps))
    plt.close(fig)
    return out_path


# -- ensembles ----------------------------------------------------------------


def ensemble_series(
    timeseries: Mapping,
    path: Sequence[str] | None = None,
) -> np.ndarray:
    """A per-replicate scalar series [T, R] from an ensemble trajectory.

    Ensemble trajectories (colony.Ensemble) carry leaves shaped
    [T, R, ...]. With ``path=None`` (default) live cells are counted per
    replicate; otherwise ``path`` selects a [T, R, N] per-agent leaf and
    the live-masked per-replicate mean is returned.
    """
    alive = np.asarray(timeseries["alive"])
    if alive.ndim != 3:
        raise ValueError(
            f"expected an ensemble trajectory ([T, R, N] alive), got "
            f"shape {alive.shape} — run via colony.Ensemble"
        )
    if path is None:
        return alive_counts(timeseries)
    return masked_agent_series(timeseries, path).mean(axis=-1).filled(np.nan)


def plot_ensemble_fan(
    timeseries: Mapping,
    path: Sequence[str] | None = None,
    out_path: str = "out/ensemble_fan.png",
    quantiles: Tuple[float, float] = (0.1, 0.9),
) -> str:
    """Fan chart across the replicate axis: median, inter-quantile band,
    and per-replicate traces — the one-compile answer to "what is the
    distribution of growth curves?"."""
    plt = _plt()
    series = ensemble_series(timeseries, path)  # [T, R]
    t = _times(timeseries, series.shape[0])
    lo = np.nanquantile(series, quantiles[0], axis=1)
    hi = np.nanquantile(series, quantiles[1], axis=1)
    med = np.nanmedian(series, axis=1)

    fig, ax = plt.subplots(figsize=(7, 4.2))
    ax.plot(t, series, color="gray", alpha=0.25, linewidth=0.7)
    ax.fill_between(t, lo, hi, alpha=0.25, label=f"q{quantiles[0]}–q{quantiles[1]}")
    ax.plot(t, med, linewidth=1.6, label="median")
    # trajectories straight from Ensemble.run carry no __time__ leaf —
    # then the x axis is the emit index, and saying otherwise would
    # compress time by emit_every*dt
    ax.set_xlabel("time (s)" if "__time__" in timeseries else "emit index")
    label = "live cells" if path is None else SEP_TITLE.join(path)
    ax.set_ylabel(label)
    ax.set_title(f"{label} across {series.shape[1]} replicates")
    ax.legend(fontsize=8)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def lifespan_table(timeseries: Mapping) -> List[Dict[str, Any]]:
    """Per-cell life episodes from the emitted alive mask.

    With a death trigger, rows RECYCLE: one physical row can host several
    cells over a run (die, then a daughter claims the slot — or divide,
    where daughter A replaces the parent in place with a fresh cell_id
    and NO alive gap). Episodes are therefore maximal alive-runs of
    ``alive[:, row]``, further split at every lineage-id change when the
    lineage emit is present. Returns one record per episode: ``{row,
    t_born, t_died, lifespan, cell_id, divided}`` — a ``divided``
    occupant left by division (no death, no lifespan); ``t_died`` /
    ``lifespan`` are None while still alive at the last emit; ``cell_id``
    is None without lineage emit. Times are emit times (``__time__``)
    when present, else emit indices — sparser emission coarsens the
    estimates accordingly.
    """
    alive = np.asarray(timeseries["alive"]).astype(bool)  # [T, N]
    t = _times(timeseries, alive.shape[0])
    lin = timeseries.get("lineage")
    cell_id = np.asarray(lin["cell_id"]) if lin is not None else None
    episodes: List[Dict[str, Any]] = []
    for row in range(alive.shape[1]):
        col = alive[:, row]
        # alive-run boundaries: prepend/append False so every run closes
        edges = np.flatnonzero(np.diff(np.r_[False, col, False]))
        for start, end in zip(edges[::2], edges[1::2]):
            # Division replaces a row's occupant WITHOUT an alive gap
            # (daughter A overwrites the parent's row, minting a fresh
            # cell_id), so with lineage present an alive-run splits at
            # every id change: the outgoing occupant's episode ends
            # there (divided, not died — no lifespan), the incomer's
            # begins.
            if cell_id is not None:
                ids = cell_id[start:end, row]
                cuts = [0, *np.flatnonzero(ids[1:] != ids[:-1]) + 1, end - start]
            else:
                cuts = [0, end - start]
            for a, b in zip(cuts[:-1], cuts[1:]):
                s, e = start + a, start + b
                # the run's LAST occupant died iff the run closed before
                # the record ended; earlier occupants left by division
                died = e == end and end < alive.shape[0]
                divided = e < end
                episodes.append(
                    {
                        "row": int(row),
                        "t_born": float(t[s]),
                        "t_died": float(t[e]) if died else None,
                        "lifespan": float(t[e] - t[s]) if died else None,
                        "cell_id": (
                            int(cell_id[s, row])
                            if cell_id is not None
                            else None
                        ),
                        "divided": bool(divided),
                    }
                )
    return episodes


def plot_lifespans(
    timeseries: Mapping, out_path: str = "out/lifespans.png"
) -> str:
    """Histogram of completed lifespans (death time - birth time)."""
    plt = _plt()
    spans = [
        e["lifespan"] for e in lifespan_table(timeseries)
        if e["lifespan"] is not None
    ]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.hist(spans, bins=min(30, max(5, len(spans) // 4 + 1)))
    ax.set_xlabel("lifespan (s)")
    ax.set_ylabel("cells")
    ax.set_title(f"completed lifespans (n={len(spans)})")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


def scan_response(
    timeseries: Mapping,
    path: Sequence[str] | None = None,
) -> np.ndarray:
    """The final per-replicate value of a series: the response column of
    a parameter scan (``Ensemble`` + ``replicate_overrides``). Returns
    ``[R]`` — live-cell count per replicate by default, or the
    live-masked per-agent mean of ``path`` at the last emit."""
    return ensemble_series(timeseries, path)[-1]


def plot_scan_response(
    timeseries: Mapping,
    values: Sequence[float],
    path: Sequence[str] | None = None,
    out_path: str = "out/scan_response.png",
    value_label: str = "scanned parameter",
    log_x: bool = True,
) -> str:
    """Dose-response curve of a parameter scan: the final value of a
    series (``scan_response``) against the scanned parameter values.

    ``values`` is the per-replicate parameter vector the scan was built
    with (the same array passed via ``replicate_overrides``). The scan
    runs as one compiled program; this draws its one-figure summary.
    """
    plt = _plt()
    values = np.asarray(values)
    resp = scan_response(timeseries, path)
    if values.shape != resp.shape:
        raise ValueError(
            f"values has shape {values.shape} but the trajectory has "
            f"{resp.shape[0]} replicates"
        )
    fig, ax = plt.subplots(figsize=(6, 4))
    # semilogx silently clips x <= 0 — a zero-dose control point would
    # vanish from the curve; fall back to a linear axis instead
    use_log = log_x and bool((values > 0).all())
    (ax.semilogx if use_log else ax.plot)(values, resp, "o-")
    ax.set_xlabel(value_label)
    label = "live cells" if path is None else SEP_TITLE.join(path)
    ax.set_ylabel(f"final {label}")
    ax.set_title(f"{label} vs {value_label} ({len(values)} points)")
    fig.tight_layout()
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=110)
    plt.close(fig)
    return out_path


# -- the standard report ------------------------------------------------------


def report(
    log_path: str,
    out_dir: str | None = None,
    molecule_index: int = 0,
    dx: float = 1.0,
    animate: bool = False,
) -> Dict[str, str]:
    """Render every standard plot a trajectory supports, auto-detected.

    The reference's analysis layer is a set of per-purpose scripts run
    against an experiment id (reconstructed SURVEY.md §3.5:
    ``python -m lens.analysis.<script> --experiment <id>``); this is the
    rebuild's one-stop equivalent behind ``python -m lens_tpu analyze``.
    Looks at the emitted tree's shape — single- vs multi-species, fields
    present, lineage present — and writes the applicable plots into
    ``out_dir`` (default: ``<log dir>/analysis``). Returns
    ``{plot name: written path}``.
    """
    header, ts = load(log_path)
    if out_dir is None:
        out_dir = os.path.join(os.path.dirname(log_path) or ".", "analysis")
    written: Dict[str, str] = {}

    # Species subtrees do not carry the top-level __time__ leaf; inject it
    # so per-species plots (growth, timeseries, lineage) share the real
    # time axis instead of falling back to emit indices.
    # Ensemble logs (colony.Ensemble: [T, R, ...] leaves) get fan charts;
    # the per-agent/field plots below assume [T, N] layouts. Detect both
    # the single-colony form (top-level alive) and the multi-species form
    # (per-species subtrees, each with its own 3-D alive).
    def _alive_ndim(tree) -> int:
        return np.asarray(tree["alive"]).ndim if "alive" in tree else 0

    def _with_time(sub):
        return dict(sub, __time__=ts["__time__"]) if "__time__" in ts else sub

    ens_species = {
        name: _with_time(sub)
        for name, sub in ts.items()
        if isinstance(sub, Mapping) and _alive_ndim(sub) == 3
    }
    if _alive_ndim(ts) == 3 or ens_species:
        # Scan axis from provenance: when the log's experiment config
        # (header) scanned exactly ONE parameter across replicates, the
        # dose-response curve is drawable without the user re-supplying
        # the values.
        scan = None
        cfg = header.get("config") if isinstance(header, Mapping) else None
        if isinstance(cfg, Mapping) and cfg.get("replicate_overrides"):
            from lens_tpu.utils.dicts import flatten_paths

            leaves = list(flatten_paths(cfg["replicate_overrides"]))
            if len(leaves) == 1:
                scan = (leaves[0][0], np.asarray(leaves[0][1]))

        targets = {"": ts} if _alive_ndim(ts) == 3 else ens_species
        for name, sub in targets.items():
            prefix = f"{name}_" if name else ""
            dot = f"{name}." if name else ""
            written[f"{dot}ensemble_fan"] = plot_ensemble_fan(
                sub, out_path=os.path.join(out_dir, f"{prefix}ensemble_fan.png")
            )
            written[f"{dot}timeseries"] = plot_timeseries(
                sub, out_path=os.path.join(out_dir, f"{prefix}timeseries.png")
            )
            if scan is not None and scan[1].ndim == 1 and scan[1].shape[
                0
            ] == np.asarray(sub["alive"]).shape[1]:
                written[f"{dot}scan_response"] = plot_scan_response(
                    sub,
                    scan[1],
                    out_path=os.path.join(
                        out_dir, f"{prefix}scan_response.png"
                    ),
                    value_label=SEP_TITLE.join(scan[0]),
                )
        return written

    species = {
        name: _with_time(sub)
        for name, sub in ts.items()
        if isinstance(sub, Mapping) and "alive" in sub
    }
    single = "alive" in ts

    def locations_of(tree: Mapping):
        try:
            return get_path(tree, ("boundary", "location"))
        except (KeyError, TypeError):
            return None

    def _saw_death(tree) -> bool:
        a = np.asarray(tree["alive"]).astype(bool)
        return bool((a[:-1] & ~a[1:]).any())

    if single:
        written["colony_growth"] = plot_colony_growth(
            ts, out_path=os.path.join(out_dir, "colony_growth.png")
        )
        written["timeseries"] = plot_timeseries(
            ts, out_path=os.path.join(out_dir, "timeseries.png")
        )
        if _saw_death(ts):
            written["lifespans"] = plot_lifespans(
                ts, out_path=os.path.join(out_dir, "lifespans.png")
            )
    for name, sub in species.items():
        written[f"{name}.colony_growth"] = plot_colony_growth(
            sub, out_path=os.path.join(out_dir, f"{name}_colony_growth.png")
        )
        written[f"{name}.timeseries"] = plot_timeseries(
            sub, out_path=os.path.join(out_dir, f"{name}_timeseries.png")
        )
        if _saw_death(sub):
            written[f"{name}.lifespans"] = plot_lifespans(
                sub, out_path=os.path.join(out_dir, f"{name}_lifespans.png")
            )

    if "fields" in ts:
        if single:
            written["field_snapshots"] = plot_field_snapshots(
                ts,
                molecule_index=molecule_index,
                locations=locations_of(ts),
                dx=dx,
                out_path=os.path.join(out_dir, "field_snapshots.png"),
            )
        if species:
            written["species_snapshots"] = plot_species_snapshots(
                ts,
                molecule_index=molecule_index,
                dx=dx,
                out_path=os.path.join(out_dir, "species_snapshots.png"),
            )
        if animate and single:
            written["fields_animation"] = animate_fields(
                ts,
                molecule_index=molecule_index,
                locations=locations_of(ts),
                dx=dx,
                out_path=os.path.join(out_dir, "fields.gif"),
            )

    for name, sub in species.items():
        if "lineage" not in sub:
            continue
        sp_table = lineage_table(sub)
        if any(n["parent"] != -1 for n in sp_table.values()):
            written[f"{name}.lineage"] = plot_lineage(
                sub,
                out_path=os.path.join(out_dir, f"{name}_lineage.png"),
                table=sp_table,
            )

    if single and "lineage" in ts:
        table = lineage_table(ts)
        if any(n["parent"] != -1 for n in table.values()):
            written["lineage"] = plot_lineage(
                ts, out_path=os.path.join(out_dir, "lineage.png"),
                table=table,
            )
            trace_path: Optional[Tuple[str, ...]] = next(
                (
                    p
                    for p, arr in flatten_leaves(ts)
                    if p[0] not in ("alive", "fields", "lineage", "__time__")
                    and arr.ndim == 2
                    and np.issubdtype(arr.dtype, np.floating)
                ),
                None,
            )
            try:  # prefer the canonical growth variable when emitted
                get_path(ts, ("global", "mass"))
                trace_path = ("global", "mass")
            except (KeyError, TypeError):
                pass
            if trace_path is not None:
                written["generation_trace"] = plot_generation_trace(
                    ts,
                    trace_path,
                    out_path=os.path.join(out_dir, "generation_trace.png"),
                    table=table,
                )
    return written


__all__ = [
    "load",
    "load_many",
    "report",
    "ensemble_series",
    "plot_ensemble_fan",
    "scan_response",
    "plot_scan_response",
    "lifespan_table",
    "plot_lifespans",
    "alive_counts",
    "masked_agent_series",
    "plot_timeseries",
    "plot_colony_growth",
    "plot_field_snapshots",
    "plot_species_snapshots",
    "plot_expression_heatmap",
    "plot_reaction_fluxes",
    "lineage_table",
    "ancestry",
    "plot_lineage",
    "plot_generation_trace",
    "animate_fields",
    "flatten_leaves",
    "get_path",
]
