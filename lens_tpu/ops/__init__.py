from lens_tpu.ops.integrate import odeint_window, rk4_step, heun_step, euler_step
from lens_tpu.ops.sampling import (
    poisson_from_uniform,
    poisson_hybrid,
    sample_poisson,
    uniform_block,
)

__all__ = [
    "odeint_window",
    "rk4_step",
    "heun_step",
    "euler_step",
    "poisson_from_uniform",
    "poisson_hybrid",
    "sample_poisson",
    "uniform_block",
]
