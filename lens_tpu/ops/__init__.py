from lens_tpu.ops.integrate import odeint_window, rk4_step, heun_step, euler_step

__all__ = ["odeint_window", "rk4_step", "heun_step", "euler_step"]
