"""Framework-native ODE integrators (the ``scipy.integrate.odeint`` replacement).

The reference integrates per-process kinetics with ``scipy.odeint`` inside
``next_update`` (corroborated by BASELINE.json; reconstructed site:
``lens/processes/*transport*.py``, SURVEY.md §2). On TPU that call is
replaced by fixed-step explicit integrators built on ``lax.scan``:

- fixed step count => static shapes, one compiled trace, vmappable across
  100k agents with zero divergence (every agent runs the same schedule);
- pytree state: ``y`` may be any pytree of arrays — the RHS works on
  whatever structure the process finds natural;
- no external dependency (diffrax is not in this environment).

Adaptive stepping is deliberately NOT the default: under ``vmap`` a
per-agent adaptive controller would serialize to the worst agent anyway.
Stiff regimes get the ``"implicit"`` stepper instead — implicit Euler
with a fixed Newton iteration (L-stable, so dt is set by accuracy, not
stability), the fixed-shape counterpart of the reference's LSODA
automatic stiff switching: the Jacobian comes from ``jax.jacfwd`` and
each Newton step is one small dense solve, which ``vmap`` batches across
the colony.

RHS signature: ``rhs(t, y, args) -> dy/dt`` (same pytree structure as y).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

RHS = Callable[[Any, Any, Any], Any]


def _axpy(a, xs, y):
    """y + a * xs (pytree), with xs possibly a list of (coeff, tree) pairs."""
    if not isinstance(xs, list):
        xs = [(1.0, xs)]

    def combine(y_leaf, *x_leaves):
        acc = y_leaf
        for (c, _), x in zip(xs, x_leaves):
            acc = acc + a * c * x
        return acc

    return jax.tree.map(combine, y, *[t for _, t in xs])


def euler_step(rhs: RHS, t, y, dt, args=None):
    return _axpy(dt, rhs(t, y, args), y)


def heun_step(rhs: RHS, t, y, dt, args=None):
    k1 = rhs(t, y, args)
    k2 = rhs(t + dt, _axpy(dt, k1, y), args)
    return _axpy(dt / 2.0, [(1.0, k1), (1.0, k2)], y)


def rk4_step(rhs: RHS, t, y, dt, args=None):
    k1 = rhs(t, y, args)
    k2 = rhs(t + dt / 2.0, _axpy(dt / 2.0, k1, y), args)
    k3 = rhs(t + dt / 2.0, _axpy(dt / 2.0, k2, y), args)
    k4 = rhs(t + dt, _axpy(dt, k3, y), args)
    return _axpy(
        dt / 6.0, [(1.0, k1), (2.0, k2), (2.0, k3), (1.0, k4)], y
    )


def implicit_euler_step(rhs: RHS, t, y, dt, args=None, newton_iters: int = 4):
    """One L-stable implicit-Euler step via fixed-iteration Newton.

    Solves ``y1 = y + dt * rhs(t + dt, y1)``. The state pytree is
    raveled to a vector; each Newton iteration forms the dense Jacobian
    with ``jax.jacfwd`` and solves ``(I - dt J) delta = -residual``.
    Fixed iteration count keeps shapes/trace static (SURVEY.md §4's
    vmap-across-agents requirement); for the few-species kinetic systems
    processes integrate, 3–4 iterations reach Newton's quadratic basin.
    Stability: A- and L-stable, so stiff relaxation rates (|lambda| dt
    >> 1) damp instead of exploding — the regime where rk4 diverges.
    """
    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree(y)
    n = flat0.size
    dt = jnp.asarray(dt, flat0.dtype)

    def f(v):
        return ravel_pytree(rhs(t + dt, unravel(v), args))[0]

    def newton(v, _):
        residual = v - flat0 - dt * f(v)
        A = jnp.eye(n, dtype=flat0.dtype) - dt * jax.jacfwd(f)(v)
        return v - jnp.linalg.solve(A, residual), None

    v, _ = jax.lax.scan(newton, flat0, None, length=newton_iters)
    return unravel(v)


def tr_bdf2_step(rhs: RHS, t, y, dt, args=None, newton_iters: int = 16):
    """One TR-BDF2 step: trapezoidal to ``t + gamma*dt``, then a BDF2
    closure to ``t + dt`` (gamma = 2 - sqrt(2), the L-stable choice).

    The reference's ``scipy.odeint`` is LSODA — automatic stiff
    switching with ACCURACY adaptivity, not just stability. Implicit
    Euler (the ``"implicit"`` stepper) matches the stability half only:
    it is first order, so at dt = 1 s its error is set by accuracy, not
    stiffness. TR-BDF2 is the fixed-shape second-order counterpart —
    one-step (vmappable, no history rows), L-stable, and composed of
    two Newton solves with the machinery implicit Euler uses (dense
    ``jacfwd`` Jacobian, one small solve per iteration).

    ``newton_iters`` is a CAP, not a fixed count: each stage's Newton
    runs until its residual drops below float roundoff scale (measured
    on Robertson at dt = 1: the trapezoidal half-kick throws the fast
    species three decades above equilibrium, and 4 fixed iterations
    leave a visibly wrong trajectory while ~10 reach the floor — under
    ``vmap`` the batch runs as long as its slowest lane, the adaptive-LP
    pattern of ops.linprog). Oracle-pinned on Robertson in
    tests/test_integrate.py: second-order convergence and >10x less
    error than implicit Euler at the same dt.
    """
    import math

    from jax.flatten_util import ravel_pytree

    flat0, unravel = ravel_pytree(y)
    n = flat0.size
    dt = jnp.asarray(dt, flat0.dtype)
    g = 2.0 - math.sqrt(2.0)
    eps = jnp.asarray(
        1e-7 if flat0.dtype == jnp.float32 else 1e-13, flat0.dtype
    )

    def f(v, tt):
        return ravel_pytree(rhs(tt, unravel(v), args))[0]

    def solve_implicit(const, coeff, tt, v0):
        # Early-exit Newton on  v = const + coeff * f(v, tt)
        tol = eps * (1.0 + jnp.max(jnp.abs(const)))

        def residual(v):
            return v - const - coeff * f(v, tt)

        def cond(carry):
            i, _, res = carry
            return (i < newton_iters) & (jnp.max(jnp.abs(res)) > tol)

        def body(carry):
            i, v, res = carry
            A = jnp.eye(n, dtype=flat0.dtype) - coeff * jax.jacfwd(
                lambda u: f(u, tt)
            )(v)
            v = v - jnp.linalg.solve(A, res)
            return i + 1, v, residual(v)

        _, v, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), v0, residual(v0))
        )
        return v

    # TR half: y_g = y0 + (g dt / 2) (f(y0) + f(y_g))
    a = g * dt / 2.0
    f0 = f(flat0, t)
    yg = solve_implicit(flat0 + a * f0, a, t + g * dt, flat0)
    # BDF2 closure: y1 = [y_g - (1-g)^2 y0] / (g (2-g)) + d dt f(y1)
    d = (1.0 - g) / (2.0 - g)
    c0 = (yg - (1.0 - g) ** 2 * flat0) / (g * (2.0 - g))
    y1 = solve_implicit(c0, d * dt, t + dt, yg)
    return unravel(y1)


_STEPPERS = {
    "euler": euler_step,
    "heun": heun_step,
    "rk4": rk4_step,
    "implicit": implicit_euler_step,
    "tr_bdf2": tr_bdf2_step,
}


def odeint_window(
    rhs: RHS,
    y0: Any,
    t0,
    dt: float,
    n_steps: int,
    args: Any = None,
    method: str = "rk4",
) -> Any:
    """Integrate ``y' = rhs(t, y, args)`` over ``n_steps`` substeps of ``dt``.

    Returns the final state only — this is the shape a ``Process.next_update``
    wants: integrate the process timestep as one window, report the end
    state. ``n_steps`` must be a static int (it sets the scan length).
    """
    stepper = _STEPPERS[method]
    t0 = jnp.asarray(t0, jnp.float32)

    def body(carry, _):
        t, y = carry
        return (t + dt, stepper(rhs, t, y, dt, args)), None

    (_, y_final), _ = jax.lax.scan(body, (t0, y0), None, length=n_steps)
    return y_final


def odeint_trajectory(
    rhs: RHS,
    y0: Any,
    t0,
    dt: float,
    n_steps: int,
    args: Any = None,
    method: str = "rk4",
) -> Tuple[Any, Any]:
    """Like ``odeint_window`` but also stacks the state after every substep
    (leading time axis) — the dev/test harness shape (SURVEY.md §3.4)."""
    stepper = _STEPPERS[method]
    t0 = jnp.asarray(t0, jnp.float32)

    def body(carry, _):
        t, y = carry
        y_next = stepper(rhs, t, y, dt, args)
        return (t + dt, y_next), y_next

    (_, y_final), ys = jax.lax.scan(body, (t0, y0), None, length=n_steps)
    return y_final, ys
