"""Batched dense LP solver — exact FBA on the MXU.

SURVEY.md §7 ranks "FBA metabolism" as the hardest gap: the reference's
metabolism lineage (Covert–Palsson 2002) is flux-balance analysis — a
linear program per cell per step — and a classic simplex is data-dependent
control flow XLA cannot tile. This module closes that gap the TPU way: a
**fixed-iteration Mehrotra predictor–corrector interior-point method**
written in pure ``jnp``. Every iteration is the same dense linear algebra
(two small solves against one factorized normal-equations matrix), so the
whole solve jits to a static graph and ``vmap`` turns a colony of cells
into batched [N, M, M] Cholesky solves — exactly the shape the MXU wants.

Problem form (the FBA form)::

    minimize    c @ x
    subject to  A @ x = b,   lb <= x <= ub

with finite bounds (FBA fluxes are always box-bounded). Internally the
box is shifted to ``0 <= x' <= u`` and the standard primal-dual system
with upper-bound slacks is solved:

    A x' = b',  x' + s = u,  A^T y + z - w = c,  x'z = 0,  s w = 0

Each Newton step reduces to the M×M normal equations
``(A D A^T) dy = r`` with ``D = diag(1 / (z/x + w/s))`` — one
``cho_factor`` + two ``cho_solve`` per iteration (predictor + corrector).

Fixed shapes, **capped** iteration count: a ``lax.while_loop`` runs until
every problem in the (vmapped) batch is accepted (same tolerance tests
the result reports), frozen at the polish floor, or at the ``n_iter``
cap. The exit fires at a state-determined point, so raising the cap
cannot change the answer (tested); on typical FBA environments the batch
exits after ~10 iterations against a worst-case cap of 45 (the cap is
sized for regulation-degenerate anaerobic corners — measured ~5x
wall-clock over always running the cap). No Python control flow on data
anywhere.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import cho_factor, cho_solve


class WarmStart(NamedTuple):
    """Carryable IPM state for warm-starting a SEQUENCE of related LPs.

    FBA solves one LP per agent per step, and environments change slowly,
    so step k's optimum is an excellent guess for step k+1 (temporal
    coherence). The warm start re-enters the barrier from an interiorized
    copy of the previous iterate instead of the scale-based cold point,
    cutting the decades of complementarity the IPM must burn down.

    - ``x``: [R] primal in ORIGINAL coordinates (including any slack
      columns the caller appended — thread the FULL vector back).
    - ``y``: [M] equality duals of the row-equilibrated system (the
      scaling is deterministic in ``A``, so it matches across calls as
      long as ``A`` is static — the FBA case).
    - ``z``/``w``: [R] lower/upper bound multipliers.
    - ``flag``: scalar; ``<= 0`` means "ignore me" (cold start). The
      returned warm state carries ``flag = converged`` so a failed solve
      never seeds the next one.

    The warm start is a HINT: the solve's acceptance tests are identical
    either way, so it can change iteration counts but not what "converged"
    means. Pack/unpack helpers flatten to one vector for state threading.
    """

    x: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    w: jnp.ndarray
    flag: jnp.ndarray


def warm_size(n_constraints: int, n_variables: int) -> int:
    """Length of the packed warm-start vector."""
    return 3 * n_variables + n_constraints + 1


def pack_warm(ws: WarmStart) -> jnp.ndarray:
    return jnp.concatenate(
        [ws.x, ws.y, ws.z, ws.w, jnp.reshape(ws.flag, (1,))]
    )


def unpack_warm(vec: jnp.ndarray, n_constraints: int, n_variables: int) -> WarmStart:
    r, m = n_variables, n_constraints
    return WarmStart(
        x=vec[:r],
        y=vec[r : r + m],
        z=vec[r + m : 2 * r + m],
        w=vec[2 * r + m : 3 * r + m],
        flag=vec[3 * r + m],
    )


class LPResult(NamedTuple):
    """Solution of one LP (or a batch, under vmap)."""

    x: jnp.ndarray          # [R] primal solution in the ORIGINAL coordinates
    objective: jnp.ndarray  # scalar c @ x
    primal_residual: jnp.ndarray  # ||A x - b||_inf
    dual_gap: jnp.ndarray   # complementarity gap mu = (x'z + s w) / 2R
    converged: jnp.ndarray  # bool: gap, primal AND dual residuals below tol
    dual_residual: jnp.ndarray  # ||c - A^T y - z + w||_inf (scaled system)
    iterations: jnp.ndarray  # int32: IPM iterations this problem ran before freezing
    warm: WarmStart         # final iterate, re-usable to seed the next solve


class _IPState(NamedTuple):
    x: jnp.ndarray
    s: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    w: jnp.ndarray


def _max_step(v: jnp.ndarray, dv: jnp.ndarray) -> jnp.ndarray:
    """Largest alpha in [0, 1] with v + alpha dv >= 0 (elementwise)."""
    ratio = jnp.where(dv < 0, -v / jnp.where(dv < 0, dv, -1.0), jnp.inf)
    return jnp.clip(jnp.min(ratio), 0.0, 1.0)


def _jacobi_solver(mat: jnp.ndarray, tiny):
    """Float32-safe SPD solve: Jacobi (symmetric diagonal) scaling, a
    unit-relative ridge, Cholesky, and one iterative-refinement pass.

    Shared by the IPM iteration and the exit polish so their numerics
    cannot drift apart. The scaling bounds the scaled diagonal at 1; the
    ridge AFTER scaling bounds the scaled condition number at ~1/ridge —
    the bound the float32 factorization actually needs (a pre-scaling
    ridge gives none: the min scaled eigenvalue was measured at -6e-9 on
    the e_coli_core normal matrix and the factorization went NaN). The
    refinement pass absorbs the ridge bias. Returns ``solve(rhs)``
    (reusable: one factorization, many right-hand sides).
    """
    dtype = mat.dtype
    ridge = 1e-6 if dtype == jnp.float32 else 1e-12
    dn = jnp.sqrt(jnp.maximum(jnp.diagonal(mat), tiny))
    scaled = mat / dn[:, None] / dn[None, :] + ridge * jnp.eye(
        mat.shape[0], dtype=dtype
    )
    chol = cho_factor(scaled)

    def solve(rhs):
        rhs_s = rhs / dn
        dy = cho_solve(chol, rhs_s)
        dy = dy + cho_solve(chol, rhs_s - scaled @ dy)
        return dy / dn

    return solve


def linprog_box(
    c: jnp.ndarray,
    A: jnp.ndarray,
    b: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    n_iter: int = 35,
    tol: float = 1e-5,
    regularization: float = 1e-8,
    warm: WarmStart | None = None,
) -> LPResult:
    """Solve ``min c@x  s.t. A@x = b, lb <= x <= ub`` (dense, batched-friendly).

    All arguments are single-problem arrays (``A`` is [M, R]); batch with
    ``jax.vmap``. Bounds must be finite with ``lb <= ub``; degenerate
    (``lb == ub``) entries are handled by a tiny interior widening. Solves
    in float64 when jax's x64 mode is on, float32 otherwise (float32 is
    accurate to ~1e-5 on well-scaled FBA problems; keep networks scaled to
    O(1) fluxes).

    Infeasible problems do not raise (no data-dependent Python flow):
    ``converged`` comes back False and ``primal_residual`` large — callers
    (e.g. the FBA process) treat that as "no feasible flux" and clamp.
    """
    # Full f32 matmul precision for the whole solve: TPU matmuls default
    # to bfloat16, whose 8-bit mantissa collapses the normal-equations
    # conditioning — measured on-device: every LP of the ecoli_core
    # network reports unconverged under the default precision, all
    # converge under float32, at identical wall-clock (these matrices are
    # far too small for the MXU's bf16 advantage to matter).
    with jax.default_matmul_precision("float32"):
        return _linprog_box_impl(
            c, A, b, lb, ub, n_iter, tol, regularization, warm
        )


def _linprog_box_impl(c, A, b, lb, ub, n_iter, tol, regularization, warm=None):
    dtype = jnp.result_type(c.dtype, jnp.float32)
    c = jnp.asarray(c, dtype)
    A = jnp.asarray(A, dtype)
    b = jnp.asarray(b, dtype)
    lb = jnp.asarray(lb, dtype)
    ub = jnp.asarray(ub, dtype)
    m, r = A.shape

    # Ruiz equilibration (two-sided): alternately scale rows and columns
    # toward unit inf-norm. Row-only scaling is not enough once columns
    # span decades — a realistic biomass reaction carries coefficients
    # from 0.07 to 59.81 (growth-associated ATP), and in float32 that
    # column makes the normal equations unsolvable (measured on the full
    # e_coli_core: the row-scaled solve stalls at primal residual ~3.5
    # while float64 converges in 12 iterations; three Ruiz passes fix
    # float32). Column scaling substitutes x = D_c x~, so bounds and
    # objective rescale and the solution is mapped back exactly below.
    col_scale = jnp.ones((r,), dtype)
    if m:
        row_scale = jnp.ones((m,), dtype)
        absA = jnp.abs(A)
        for _ in range(3):
            scaled = absA * row_scale[:, None] * col_scale[None, :]
            row_scale = row_scale / jnp.sqrt(
                jnp.maximum(jnp.max(scaled, axis=1), 1e-12)
            )
            scaled = absA * row_scale[:, None] * col_scale[None, :]
            col_scale = col_scale / jnp.sqrt(
                jnp.maximum(jnp.max(scaled, axis=0), 1e-12)
            )
        A = A * row_scale[:, None] * col_scale[None, :]
        b = b * row_scale
        c = c * col_scale
        lb = lb / col_scale
        ub = ub / col_scale

    # Masked presolve for PINNED variables (lb == ub, e.g. every reaction a
    # regulation rule gated off): a zero-width box has no interior, and
    # keeping such columns in the barrier collapses the scaling matrix
    # ``d`` (measured on the regulated e_coli_core: ~25 gated columns
    # drive d to a 1e-18..1e2 range and the float32 Cholesky goes
    # singular at iteration 1). Shapes must stay static, so instead of
    # removing the columns they are masked out of the barrier entirely:
    # x is fixed at the bound (shifted coordinate 0), their d / direction
    # components are zeroed each iteration, their complementarity
    # products vanish (z = w = 0), and they are exempt from the dual
    # residual test — correct, because a fixed variable's bound
    # multipliers can absorb ANY reduced cost (z - w = c_j - A_j^T y
    # always has a nonnegative solution).
    width = ub - lb
    pinned = width <= 1e-7
    free = 1.0 - pinned.astype(dtype)
    u = jnp.maximum(width, 1e-8)
    b_shift = b - A @ lb

    # Scale-aware starting point strictly inside the box (pinned columns
    # sit at their bound with zeroed multipliers).
    x0 = free * 0.5 * u
    s0 = u - x0
    z0 = free * (1.0 + jnp.max(jnp.abs(c)))
    state = _IPState(x=x0, s=s0, y=jnp.zeros((m,), dtype), z=z0, w=z0)

    eye = jnp.eye(m, dtype=dtype)

    # Freezing floor: below this complementarity the iterate is as good as
    # float32 gets; further steps are skipped via `where` so late-iteration
    # blow-ups (z/x -> inf near active bounds) can never poison the result.
    floor = jnp.asarray(0.05 * tol, dtype)
    tiny = jnp.asarray(1e-12, dtype)
    # Acceptance thresholds, shared by the loop's stopping rule and the
    # final `converged` report (defined once so they cannot drift apart).
    sqrt_tol = jnp.sqrt(jnp.asarray(tol, dtype))
    scale = 1.0 + jnp.max(jnp.abs(b)) if m else jnp.asarray(1.0, dtype)
    dual_scale = 1.0 + jnp.max(jnp.abs(c))

    if warm is not None:
        # Interiorized restart from the previous solve's iterate: pull x
        # off the bounds by a fixed fraction of the (new) box and floor
        # the multipliers at a small multiple of the dual scale. The
        # resulting complementarity is ~delta * zfloor * u — decades
        # below the cold start's 0.5 * u * dual_scale — while staying far
        # enough interior that a moved optimum (a regulation flip) costs
        # a few extra iterations, not a stall. flag <= 0 (no history yet,
        # or the previous solve failed) selects the cold point per lane.
        delta = jnp.asarray(0.005, dtype)
        # warm.x is in ORIGINAL coordinates; map into the equilibrated,
        # shifted system before interiorizing
        xw = free * jnp.clip(
            jnp.asarray(warm.x, dtype) / col_scale - lb,
            delta * u,
            (1 - delta) * u,
        )
        zfloor = jnp.asarray(2e-3, dtype) * dual_scale
        use = jnp.asarray(warm.flag, dtype) > 0
        pick = lambda wv, cv: jnp.where(use, wv, cv)
        state = _IPState(
            x=pick(xw, x0),
            s=pick(u - xw, s0),
            y=pick(jnp.asarray(warm.y, dtype), state.y),
            z=pick(free * jnp.maximum(jnp.asarray(warm.z, dtype), zfloor), z0),
            w=pick(free * jnp.maximum(jnp.asarray(warm.w, dtype), zfloor), z0),
        )

    def iteration(_, st: _IPState) -> _IPState:
        x, s, y, z, w = st
        r_p = b_shift - A @ x                    # primal (equality) residual
        r_u = u - x - s                          # box residual
        r_d = c - A.T @ y - z + w                # dual residual
        mu = (x @ z + s @ w) / (2 * r)
        xc = jnp.maximum(x, tiny)
        sc = jnp.maximum(s, tiny)

        # free-masked scaling: pinned columns have z = w = 0 (denominator
        # 0 -> guarded), and d = 0 removes them from the normal equations
        d = free / jnp.maximum(z / xc + w / sc, tiny)  # [R]
        # FREE-VARIABLE cap: a variable far from both bounds has z, w ->
        # mu/x, so its d grows like x*s/mu without bound (measured 5.6e7
        # on e_coli_core's zero-flux reversible reactions in +-20 boxes
        # while slack pivots sit at 1e-3). Seven decades of pivot spread
        # erase every other column of those rows from the float32 normal
        # matrix, and the d-amplified direction noise makes the primal
        # residual GROW in the endgame. Capping d at max(1e3, u_max^2)
        # (equilibrated units; allows a full-box step at unit dual scale)
        # bounds the spread — a mild proximal damping on interior columns
        # that Mehrotra's corrector absorbs. With the cap the anaerobic
        # regulated solve accepts at iteration 10 with residual 1e-3;
        # without it the solve freezes at residual 7e-2 and never
        # converges.
        d = jnp.minimum(d, jnp.maximum(1e3, jnp.max(free * u) ** 2))
        AD = A * d                               # [M, R]
        normal = AD @ A.T + regularization * eye  # [M, M] SPD
        # diag(d) spans many decades as bounds go active, so rows of the
        # normal matrix do too, and a raw float32 Cholesky goes NaN on
        # reference-scale networks (measured on the 72x188 e_coli_core:
        # every direction non-finite from mid-solve, freezing the
        # iterate; float64 converges in 13) — hence the scaled solver.
        refine_solve = _jacobi_solver(normal, tiny)

        def solve_direction(r_xz, r_sw):
            # Reduced RHS derivation: eliminate dz, dw, ds in favor of dx,
            # then dx in favor of dy through the normal equations. Pinned
            # columns get identically-zero directions (they are not in
            # the barrier; their state never moves).
            rhat = r_d - r_xz / xc + r_sw / sc - (w / sc) * r_u
            dy = refine_solve(r_p + AD @ rhat)
            dx = d * (A.T @ dy - rhat)
            ds = free * (r_u - dx)
            dz = free * (r_xz - z * dx) / xc
            dw = free * (r_sw - w * ds) / sc
            return dx, ds, dy, dz, dw

        # Predictor (affine scaling: drive complementarity to zero).
        aff = solve_direction(-x * z, -s * w)
        dx_a, ds_a, _, dz_a, dw_a = aff
        alpha_p = jnp.minimum(_max_step(x, dx_a), _max_step(s, ds_a))
        alpha_d = jnp.minimum(_max_step(z, dz_a), _max_step(w, dw_a))
        mu_aff = (
            (x + alpha_p * dx_a) @ (z + alpha_d * dz_a)
            + (s + alpha_p * ds_a) @ (w + alpha_d * dw_a)
        ) / (2 * r)
        sigma = jnp.clip((mu_aff / jnp.maximum(mu, tiny)) ** 3, 0.0, 1.0)

        # Corrector (recenter + second-order complementarity correction).
        r_xz = sigma * mu - x * z - dx_a * dz_a
        r_sw = sigma * mu - s * w - ds_a * dw_a
        dx, ds, dy, dz, dw = solve_direction(r_xz, r_sw)

        # eta = fraction of the distance to the boundary taken per step.
        # 0.9 (not the textbook 0.995) is a float32 safeguard: at 0.995
        # the iterate crashes into its bounds faster than the f32 normal
        # equations can track, and the primal residual DRIFTS UP in the
        # endgame (measured on e_coli_core: residual grows 2e-3 -> 1e-1
        # while mu -> 0, never re-entering tolerance; at 0.9 the same
        # solve accepts at iteration 17 with residual 6e-3). Costs ~1-2
        # iterations on easy problems.
        eta = 0.9
        alpha_p = eta * jnp.minimum(_max_step(x, dx), _max_step(s, ds))
        alpha_d = eta * jnp.minimum(_max_step(z, dz), _max_step(w, dw))
        # One shared finiteness flag across ALL direction components:
        # stepping primal while freezing dual (or vice versa) would leave
        # an inconsistent iterate, so the whole step is all-or-nothing.
        finite = (
            jnp.isfinite(dx).all()
            & jnp.isfinite(ds).all()
            & jnp.isfinite(dy).all()
            & jnp.isfinite(dz).all()
            & jnp.isfinite(dw).all()
        )
        go = (mu > floor) & finite
        step = lambda v, dv, a: jnp.where(go, v + a * dv, v)
        return _IPState(
            x=step(x, dx, alpha_p),
            s=step(s, ds, alpha_p),
            y=step(y, dy, alpha_d),
            z=step(z, dz, alpha_d),
            w=step(w, dw, alpha_d),
        )

    # Capped adaptive loop: iterate until the point satisfies the SAME
    # acceptance tests the result reports (gap + primal + dual residual at
    # tol level), until it freezes at the polish floor, or until the cap.
    # Under `vmap` the batching rule turns the predicate into "any lane
    # still active" with per-lane select-freezing — the batch runs exactly
    # as long as its slowest member needs (typically ~10 iterations on FBA
    # environments; the cap covers infeasible/degenerate lanes). Because
    # the exit fires at a state-determined point, raising the cap cannot
    # change the answer (tested). (`finite` is deliberately NOT in the
    # predicate: a lane with a non-finite direction skips the step but may
    # recover next iteration, so it stays active until accepted or
    # capped.) `n_its` stops advancing when a lane exits, giving
    # per-problem iteration telemetry for free.
    def active(carry):
        n_its, st = carry
        mu = (st.x @ st.z + st.s @ st.w) / (2 * r)
        # `mu < tol` is strictly tighter than the reported gap test
        # (tol * (1+|obj|), original coordinates), so an accepted lane
        # can never report converged=False for lack of polish.
        accepted = mu < tol
        if m:
            accepted &= jnp.max(jnp.abs(A @ st.x - b_shift)) < sqrt_tol * scale
        # pinned columns are exempt from dual feasibility (their bound
        # multipliers can absorb any reduced cost)
        accepted &= (
            jnp.max(jnp.abs(free * (c - A.T @ st.y - st.z + st.w)))
            < sqrt_tol * dual_scale
        )
        return (n_its < n_iter) & (mu > floor) & ~accepted

    n_its, state = lax.while_loop(
        active,
        lambda carry: (carry[0] + 1, iteration(carry[0], carry[1])),
        (jnp.int32(0), state),
    )

    x = state.x + lb
    if m:
        # Weighted active-set polish (two passes): the float32 endgame
        # leaves a primal residual the iterate cannot shrink (direction
        # noise accumulates as bounds go active — measured ~8e-3 on
        # e_coli_core vs 1e-8 in float64, which costs ~12% of the
        # objective through the |y|*residual suboptimality term). An
        # UNWEIGHTED least-norm correction cannot fix it: it moves
        # active variables out of their bounds and the clip re-breaks
        # feasibility. Weighting the correction by each variable's
        # distance to its nearest bound confines it to the (nearly-)free
        # subspace — crossover-style — so the clip barely bites and the
        # equality residual drops to float32 solve accuracy.
        for _ in range(2):
            wgt = jnp.maximum(jnp.minimum(x - lb, ub - x), 0.0)
            AW = A * wgt
            gram = AW @ A.T + regularization * eye
            dy = _jacobi_solver(gram, tiny)(b - A @ x)
            x = jnp.clip(x + wgt * (A.T @ dy), lb, ub)
    x = jnp.clip(x, lb, ub)
    # NOTE: residuals/gap/objective below are computed in the equilibrated
    # system (c @ x is scaling-invariant); only the returned points map
    # back through the column scaling.
    # Residual and convergence are judged on the RETURNED (clipped) point,
    # so an infeasible problem can never report a small residual just
    # because the pre-clip refinement satisfied Ax = b outside the box.
    primal_residual = jnp.max(jnp.abs(A @ x - b)) if m else jnp.asarray(0.0, dtype)
    gap = (state.x @ state.z + state.s @ state.w) / (2 * r)
    # Dual residual at the final iterate (scaled/shifted system, free
    # columns only — see the pinned presolve note): without this, an
    # iteration-starved primal-feasible point could report
    # converged=True with suboptimal fluxes.
    dual_residual = jnp.max(
        jnp.abs(free * (c - A.T @ state.y - state.z + state.w))
    )
    converged = (
        (gap < tol * (1.0 + jnp.abs(c @ x)))
        & (primal_residual < sqrt_tol * scale)
        & (dual_residual < sqrt_tol * dual_scale)
    )
    return LPResult(
        x=x * col_scale,
        objective=c @ x,
        primal_residual=primal_residual,
        dual_gap=gap,
        converged=converged,
        dual_residual=dual_residual,
        iterations=n_its,
        # Final INTERIOR iterate (pre-clip x, original coordinates;
        # y/z/w stay in the equilibrated system — the scaling is
        # deterministic in A, so it matches across calls), re-usable as
        # the next solve's warm start; flag = converged so failed solves
        # never seed.
        warm=WarmStart(
            x=(state.x + lb) * col_scale,
            y=state.y,
            z=state.z,
            w=state.w,
            flag=converged.astype(dtype),
        ),
    )


def flux_balance(
    stoichiometry: jnp.ndarray,
    objective: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    n_iter: int = 35,
    tol: float = 1e-5,
    leak: float = 0.0,
    warm: WarmStart | None = None,
) -> LPResult:
    """FBA: ``max objective @ v  s.t.  S @ v = 0, lb <= v <= ub``.

    ``stoichiometry`` is [metabolites, reactions] (steady-state internal
    metabolites only — exchange species appear via bounded exchange
    reactions, the standard FBA convention). Returns fluxes ``v`` with the
    MAXIMIZED objective value. Batch over cells with ``jax.vmap`` over
    ``(lb, ub)`` (the network is static)::

        sol = jax.vmap(lambda l, u: flux_balance(S, obj, l, u))(lbs, ubs)

    ``leak > 0`` relaxes each steady-state row to ``|S v| <= leak`` by
    appending a zero-cost identity slack column per metabolite. This is a
    float32-conditioning requirement for realistically sized regulated
    networks, not a tuning knob: when regulation gates every reaction
    touching some metabolite (e.g. the FADH2 row of a core-carbon network
    under anaerobiosis), that row of the normal-equations matrix
    ``A D A^T`` goes to zero as the barrier weights collapse, the float32
    Cholesky breaks down, and the solve freezes unconverged. The slack
    column guarantees each row a healthy pivot exactly when it is needed
    — a metabolite whose reactions are all gated has a *valueless* slack,
    which the barrier keeps interior (healthy d); valuable metabolites'
    slacks saturate, but their rows have active reaction columns anyway.
    The modeling cost is an O(leak) bias in fluxes/objective (a cell may
    "find" up to ``leak`` of any metabolite per unit time). At the
    default scale used by the FBA process (1.5e-3 vs O(1) fluxes) this is
    far below biological parameter uncertainty; tests pin the bias
    against a HiGHS oracle on the SAME relaxed problem.
    """
    S = jnp.asarray(stoichiometry)
    m, r = S.shape
    c = -jnp.asarray(objective)
    if leak > 0.0 and m:
        S = jnp.concatenate([S, jnp.eye(m, dtype=S.dtype)], axis=1)
        c = jnp.concatenate([c, jnp.zeros(m, c.dtype)])
        lb = jnp.concatenate([jnp.asarray(lb), jnp.full(m, -leak, S.dtype)])
        ub = jnp.concatenate([jnp.asarray(ub), jnp.full(m, leak, S.dtype)])
    res = linprog_box(
        c,
        S,
        jnp.zeros(m, S.dtype),
        lb,
        ub,
        n_iter=n_iter,
        tol=tol,
        warm=warm,
    )
    # x is truncated to the caller's reactions; res.warm keeps the FULL
    # column space (slacks included) — thread it back verbatim.
    return res._replace(objective=-res.objective, x=res.x[:r])
