"""Batched dense LP solver — exact FBA on the MXU.

SURVEY.md §7 ranks "FBA metabolism" as the hardest gap: the reference's
metabolism lineage (Covert–Palsson 2002) is flux-balance analysis — a
linear program per cell per step — and a classic simplex is data-dependent
control flow XLA cannot tile. This module closes that gap the TPU way: a
**fixed-iteration Mehrotra predictor–corrector interior-point method**
written in pure ``jnp``. Every iteration is the same dense linear algebra
(two small solves against one factorized normal-equations matrix), so the
whole solve jits to a static graph and ``vmap`` turns a colony of cells
into batched [N, M, M] Cholesky solves — exactly the shape the MXU wants.

Problem form (the FBA form)::

    minimize    c @ x
    subject to  A @ x = b,   lb <= x <= ub

with finite bounds (FBA fluxes are always box-bounded). Internally the
box is shifted to ``0 <= x' <= u`` and the standard primal-dual system
with upper-bound slacks is solved:

    A x' = b',  x' + s = u,  A^T y + z - w = c,  x'z = 0,  s w = 0

Each Newton step reduces to the M×M normal equations
``(A D A^T) dy = r`` with ``D = diag(1 / (z/x + w/s))`` — one
``cho_factor`` + two ``cho_solve`` per iteration (predictor + corrector).

Fixed shapes, **capped** iteration count: a ``lax.while_loop`` runs until
every problem in the (vmapped) batch is accepted (same tolerance tests
the result reports), frozen at the polish floor, or at the ``n_iter``
cap. The exit fires at a state-determined point, so raising the cap
cannot change the answer (tested); on typical FBA environments the batch
exits after ~10 iterations against a worst-case cap of 45 (the cap is
sized for regulation-degenerate anaerobic corners — measured ~5x
wall-clock over always running the cap). No Python control flow on data
anywhere.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import cho_factor, cho_solve


class LPResult(NamedTuple):
    """Solution of one LP (or a batch, under vmap)."""

    x: jnp.ndarray          # [R] primal solution in the ORIGINAL coordinates
    objective: jnp.ndarray  # scalar c @ x
    primal_residual: jnp.ndarray  # ||A x - b||_inf
    dual_gap: jnp.ndarray   # complementarity gap mu = (x'z + s w) / 2R
    converged: jnp.ndarray  # bool: gap, primal AND dual residuals below tol
    dual_residual: jnp.ndarray  # ||c - A^T y - z + w||_inf (scaled system)
    iterations: jnp.ndarray  # int32: IPM iterations this problem ran before freezing


class _IPState(NamedTuple):
    x: jnp.ndarray
    s: jnp.ndarray
    y: jnp.ndarray
    z: jnp.ndarray
    w: jnp.ndarray


def _max_step(v: jnp.ndarray, dv: jnp.ndarray) -> jnp.ndarray:
    """Largest alpha in [0, 1] with v + alpha dv >= 0 (elementwise)."""
    ratio = jnp.where(dv < 0, -v / jnp.where(dv < 0, dv, -1.0), jnp.inf)
    return jnp.clip(jnp.min(ratio), 0.0, 1.0)


def linprog_box(
    c: jnp.ndarray,
    A: jnp.ndarray,
    b: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    n_iter: int = 35,
    tol: float = 1e-5,
    regularization: float = 1e-8,
) -> LPResult:
    """Solve ``min c@x  s.t. A@x = b, lb <= x <= ub`` (dense, batched-friendly).

    All arguments are single-problem arrays (``A`` is [M, R]); batch with
    ``jax.vmap``. Bounds must be finite with ``lb <= ub``; degenerate
    (``lb == ub``) entries are handled by a tiny interior widening. Solves
    in float64 when jax's x64 mode is on, float32 otherwise (float32 is
    accurate to ~1e-5 on well-scaled FBA problems; keep networks scaled to
    O(1) fluxes).

    Infeasible problems do not raise (no data-dependent Python flow):
    ``converged`` comes back False and ``primal_residual`` large — callers
    (e.g. the FBA process) treat that as "no feasible flux" and clamp.
    """
    # Full f32 matmul precision for the whole solve: TPU matmuls default
    # to bfloat16, whose 8-bit mantissa collapses the normal-equations
    # conditioning — measured on-device: every LP of the ecoli_core
    # network reports unconverged under the default precision, all
    # converge under float32, at identical wall-clock (these matrices are
    # far too small for the MXU's bf16 advantage to matter).
    with jax.default_matmul_precision("float32"):
        return _linprog_box_impl(
            c, A, b, lb, ub, n_iter, tol, regularization
        )


def _linprog_box_impl(c, A, b, lb, ub, n_iter, tol, regularization):
    dtype = jnp.result_type(c.dtype, jnp.float32)
    c = jnp.asarray(c, dtype)
    A = jnp.asarray(A, dtype)
    b = jnp.asarray(b, dtype)
    lb = jnp.asarray(lb, dtype)
    ub = jnp.asarray(ub, dtype)
    m, r = A.shape

    # Row equilibration: unit inf-norm rows keep the normal equations
    # well-conditioned in float32 (pure row scaling — the feasible set and
    # the bounds are untouched).
    if m:
        row_scale = jnp.maximum(jnp.max(jnp.abs(A), axis=1), 1e-12)
        A = A / row_scale[:, None]
        b = b / row_scale

    # Shift the box to [0, u]; keep a strictly positive width everywhere so
    # the interior is non-empty even for pinned (lb == ub) variables.
    u = jnp.maximum(ub - lb, 1e-8)
    b_shift = b - A @ lb

    # Scale-aware starting point strictly inside the box.
    x0 = 0.5 * u
    s0 = u - x0
    z0 = jnp.full((r,), 1.0 + jnp.max(jnp.abs(c)), dtype)
    state = _IPState(x=x0, s=s0, y=jnp.zeros((m,), dtype), z=z0, w=z0)

    eye = jnp.eye(m, dtype=dtype)

    # Freezing floor: below this complementarity the iterate is as good as
    # float32 gets; further steps are skipped via `where` so late-iteration
    # blow-ups (z/x -> inf near active bounds) can never poison the result.
    floor = jnp.asarray(0.05 * tol, dtype)
    tiny = jnp.asarray(1e-12, dtype)
    # Acceptance thresholds, shared by the loop's stopping rule and the
    # final `converged` report (defined once so they cannot drift apart).
    sqrt_tol = jnp.sqrt(jnp.asarray(tol, dtype))
    scale = 1.0 + jnp.max(jnp.abs(b)) if m else jnp.asarray(1.0, dtype)
    dual_scale = 1.0 + jnp.max(jnp.abs(c))

    def iteration(_, st: _IPState) -> _IPState:
        x, s, y, z, w = st
        r_p = b_shift - A @ x                    # primal (equality) residual
        r_u = u - x - s                          # box residual
        r_d = c - A.T @ y - z + w                # dual residual
        mu = (x @ z + s @ w) / (2 * r)
        xc = jnp.maximum(x, tiny)
        sc = jnp.maximum(s, tiny)

        d = 1.0 / (z / xc + w / sc)              # [R] scaling
        AD = A * d                               # [M, R]
        normal = AD @ A.T + regularization * eye  # [M, M] SPD
        chol = cho_factor(normal)

        def refine_solve(rhs):
            # Cholesky solve + one iterative-refinement pass: recovers the
            # accuracy float32 loses when diag(d) spans many decades.
            dy = cho_solve(chol, rhs)
            return dy + cho_solve(chol, rhs - normal @ dy)

        def solve_direction(r_xz, r_sw):
            # Reduced RHS derivation: eliminate dz, dw, ds in favor of dx,
            # then dx in favor of dy through the normal equations.
            rhat = r_d - r_xz / xc + r_sw / sc - (w / sc) * r_u
            dy = refine_solve(r_p + AD @ rhat)
            dx = d * (A.T @ dy - rhat)
            ds = r_u - dx
            dz = (r_xz - z * dx) / xc
            dw = (r_sw - w * ds) / sc
            return dx, ds, dy, dz, dw

        # Predictor (affine scaling: drive complementarity to zero).
        aff = solve_direction(-x * z, -s * w)
        dx_a, ds_a, _, dz_a, dw_a = aff
        alpha_p = jnp.minimum(_max_step(x, dx_a), _max_step(s, ds_a))
        alpha_d = jnp.minimum(_max_step(z, dz_a), _max_step(w, dw_a))
        mu_aff = (
            (x + alpha_p * dx_a) @ (z + alpha_d * dz_a)
            + (s + alpha_p * ds_a) @ (w + alpha_d * dw_a)
        ) / (2 * r)
        sigma = jnp.clip((mu_aff / jnp.maximum(mu, tiny)) ** 3, 0.0, 1.0)

        # Corrector (recenter + second-order complementarity correction).
        r_xz = sigma * mu - x * z - dx_a * dz_a
        r_sw = sigma * mu - s * w - ds_a * dw_a
        dx, ds, dy, dz, dw = solve_direction(r_xz, r_sw)

        eta = 0.995
        alpha_p = eta * jnp.minimum(_max_step(x, dx), _max_step(s, ds))
        alpha_d = eta * jnp.minimum(_max_step(z, dz), _max_step(w, dw))
        # One shared finiteness flag across ALL direction components:
        # stepping primal while freezing dual (or vice versa) would leave
        # an inconsistent iterate, so the whole step is all-or-nothing.
        finite = (
            jnp.isfinite(dx).all()
            & jnp.isfinite(ds).all()
            & jnp.isfinite(dy).all()
            & jnp.isfinite(dz).all()
            & jnp.isfinite(dw).all()
        )
        go = (mu > floor) & finite
        step = lambda v, dv, a: jnp.where(go, v + a * dv, v)
        return _IPState(
            x=step(x, dx, alpha_p),
            s=step(s, ds, alpha_p),
            y=step(y, dy, alpha_d),
            z=step(z, dz, alpha_d),
            w=step(w, dw, alpha_d),
        )

    # Capped adaptive loop: iterate until the point satisfies the SAME
    # acceptance tests the result reports (gap + primal + dual residual at
    # tol level), until it freezes at the polish floor, or until the cap.
    # Under `vmap` the batching rule turns the predicate into "any lane
    # still active" with per-lane select-freezing — the batch runs exactly
    # as long as its slowest member needs (typically ~10 iterations on FBA
    # environments; the cap covers infeasible/degenerate lanes). Because
    # the exit fires at a state-determined point, raising the cap cannot
    # change the answer (tested). (`finite` is deliberately NOT in the
    # predicate: a lane with a non-finite direction skips the step but may
    # recover next iteration, so it stays active until accepted or
    # capped.) `n_its` stops advancing when a lane exits, giving
    # per-problem iteration telemetry for free.
    def active(carry):
        n_its, st = carry
        mu = (st.x @ st.z + st.s @ st.w) / (2 * r)
        # `mu < tol` is strictly tighter than the reported gap test
        # (tol * (1+|obj|), original coordinates), so an accepted lane
        # can never report converged=False for lack of polish.
        accepted = mu < tol
        if m:
            accepted &= jnp.max(jnp.abs(A @ st.x - b_shift)) < sqrt_tol * scale
        accepted &= (
            jnp.max(jnp.abs(c - A.T @ st.y - st.z + st.w))
            < sqrt_tol * dual_scale
        )
        return (n_its < n_iter) & (mu > floor) & ~accepted

    n_its, state = lax.while_loop(
        active,
        lambda carry: (carry[0] + 1, iteration(carry[0], carry[1])),
        (jnp.int32(0), state),
    )

    x = state.x + lb
    if m:
        # One primal refinement: least-norm correction onto Ax = b sharpens
        # the float32 equality residual by ~an order of magnitude; the
        # subsequent clip can only move x by that same (tiny) amount.
        gram = A @ A.T + regularization * eye
        x = x + A.T @ cho_solve(cho_factor(gram), b - A @ x)
    x = jnp.clip(x, lb, ub)
    # Residual and convergence are judged on the RETURNED (clipped) point,
    # so an infeasible problem can never report a small residual just
    # because the pre-clip refinement satisfied Ax = b outside the box.
    primal_residual = jnp.max(jnp.abs(A @ x - b)) if m else jnp.asarray(0.0, dtype)
    gap = (state.x @ state.z + state.s @ state.w) / (2 * r)
    # Dual residual at the final iterate (scaled/shifted system): without
    # this, an iteration-starved primal-feasible point could report
    # converged=True with suboptimal fluxes.
    dual_residual = jnp.max(
        jnp.abs(c - A.T @ state.y - state.z + state.w)
    )
    converged = (
        (gap < tol * (1.0 + jnp.abs(c @ x)))
        & (primal_residual < sqrt_tol * scale)
        & (dual_residual < sqrt_tol * dual_scale)
    )
    return LPResult(
        x=x,
        objective=c @ x,
        primal_residual=primal_residual,
        dual_gap=gap,
        converged=converged,
        dual_residual=dual_residual,
        iterations=n_its,
    )


def flux_balance(
    stoichiometry: jnp.ndarray,
    objective: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    n_iter: int = 35,
    tol: float = 1e-5,
    leak: float = 0.0,
) -> LPResult:
    """FBA: ``max objective @ v  s.t.  S @ v = 0, lb <= v <= ub``.

    ``stoichiometry`` is [metabolites, reactions] (steady-state internal
    metabolites only — exchange species appear via bounded exchange
    reactions, the standard FBA convention). Returns fluxes ``v`` with the
    MAXIMIZED objective value. Batch over cells with ``jax.vmap`` over
    ``(lb, ub)`` (the network is static)::

        sol = jax.vmap(lambda l, u: flux_balance(S, obj, l, u))(lbs, ubs)

    ``leak > 0`` relaxes each steady-state row to ``|S v| <= leak`` by
    appending a zero-cost identity slack column per metabolite. This is a
    float32-conditioning requirement for realistically sized regulated
    networks, not a tuning knob: when regulation gates every reaction
    touching some metabolite (e.g. the FADH2 row of a core-carbon network
    under anaerobiosis), that row of the normal-equations matrix
    ``A D A^T`` goes to zero as the barrier weights collapse, the float32
    Cholesky breaks down, and the solve freezes unconverged. The slack
    column guarantees each row a healthy pivot exactly when it is needed
    — a metabolite whose reactions are all gated has a *valueless* slack,
    which the barrier keeps interior (healthy d); valuable metabolites'
    slacks saturate, but their rows have active reaction columns anyway.
    The modeling cost is an O(leak) bias in fluxes/objective (a cell may
    "find" up to ``leak`` of any metabolite per unit time). At the
    default scale used by the FBA process (1.5e-3 vs O(1) fluxes) this is
    far below biological parameter uncertainty; tests pin the bias
    against a HiGHS oracle on the SAME relaxed problem.
    """
    S = jnp.asarray(stoichiometry)
    m, r = S.shape
    c = -jnp.asarray(objective)
    if leak > 0.0 and m:
        S = jnp.concatenate([S, jnp.eye(m, dtype=S.dtype)], axis=1)
        c = jnp.concatenate([c, jnp.zeros(m, c.dtype)])
        lb = jnp.concatenate([jnp.asarray(lb), jnp.full(m, -leak, S.dtype)])
        ub = jnp.concatenate([jnp.asarray(ub), jnp.full(m, leak, S.dtype)])
    res = linprog_box(
        c,
        S,
        jnp.zeros(m, S.dtype),
        lb,
        ub,
        n_iter=n_iter,
        tol=tol,
    )
    return res._replace(objective=-res.objective, x=res.x[:r])
