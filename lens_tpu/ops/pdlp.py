"""Batched first-order LP solver (PDLP-style PDHG) — FBA beyond the
dense-Cholesky wall.

``ops.linprog`` solves each cell's FBA exactly with a dense interior-point
method whose per-iteration cost is O(M^2 R + M^3/3): forming and Cholesky-
factoring the normal equations ``A D A^T``. At the reference-lineage scale
(e_coli_core, 72x180) that is the right tool — ~10 iterations, tiny
matrices, batched factorizations. But the reference's raison d'etre is
wcEcoli-class networks (thousands of reactions — SURVEY.md §2 "wcEcoli
bridge"), where M^3 per agent per step is the wall *no* factorization
layout fixes on a TPU: sparse Cholesky is sequential scatter/gather (the
opposite of the MXU), and the normal matrix fills in anyway.

This module is the scaling step (VERDICT r4 "missing" #3 / task 4,
option c): a **restarted, primal-weighted PDHG** ("PDLP": Applegate et
al. 2021, arXiv:2106.04756 — public algorithm) whose per-iteration work
is TWO matvecs with the static constraint matrix. Batched over a colony,
those are ``[N, R] @ [R, M]`` dense matmuls — exactly the MXU's shape,
with none of the batched-small-Cholesky awkwardness. Cost per iteration
is O(M R) dense (O(nnz) sparse), so the crossover vs the IPM arrives as
soon as the extra first-order iterations are cheaper than the cubic
factorization — measured in ``bench_lp_scale.py``, which is the evidence
for when to prefer which solver.

Same problem form as ``linprog_box`` (the FBA form)::

    minimize    c @ x
    subject to  A @ x = b,   lb <= x <= ub

Same contract too: fixed shapes, capped iterations, a ``lax.while_loop``
that exits when every (vmapped) problem is accepted at the SAME relative
KKT tolerances the result reports, warm-startable from the previous
step's solution (temporal coherence: environments change slowly). No
data-dependent Python control flow anywhere.

Algorithm (per problem; ``vmap`` batches it):

- Ruiz equilibration of ``A`` (10 passes — deterministic in ``A``, so
  warm starts stay coordinate-consistent across calls), then
  **Pock-Chambolle diagonal preconditioning** (alpha = 1): per-variable
  primal steps ``tau_j = w / sum_i |A_ij|`` and per-constraint dual
  steps ``sigma_i = w^-1 / sum_j |A_ij|``, which satisfy the PDHG step
  condition by construction. Measured on the regulated e_coli_core
  (24x59): scalar spectral-norm steps stall above gap ~1e-1 at 65k
  iterations; the diagonal steps converge to 1e-5 in ~4k.
- PDHG with reflection: ``x+ = clip(x - tau (c - A^T y), lb, ub)``;
  ``y+ = y + sigma (b - A(2 x+ - x))`` with primal weight ``w``.
- Every ``restart_every`` iterations the KKT score (max of relative
  primal residual and relative duality gap) is evaluated at BOTH the
  current iterate and the in-window average; the better one becomes the
  restart point (adaptive restart-to-average), and the primal weight is
  re-balanced from the window's primal/dual movement ratio
  ``w <- sqrt(w * ||dy|| / ||dx||)`` (PDLP's theta = 1/2 rule). The
  window length matters: too-frequent restarts (64) destabilize the
  weight adaptation and stall; 512 converged every packaged network
  (measured sweep in the round-5 records).
- The duality gap uses the exact box-LP dual: for reduced costs
  ``r = c - A^T y``, the dual objective is
  ``b @ y + sum(min(r * lb, r * ub))`` — the bound multipliers are the
  positive/negative parts of ``r``, so dual feasibility is exact by
  construction and the gap + primal residual alone certify optimality.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class PDLPWarm(NamedTuple):
    """Carryable warm-start state: previous solution in ORIGINAL primal
    coordinates, equilibrated-system duals, the adapted primal weight,
    and a flag (``<= 0`` means "ignore me" — cold start)."""

    x: jnp.ndarray      # [R] primal, original coordinates
    y: jnp.ndarray      # [M] duals of the equilibrated system
    omega: jnp.ndarray  # scalar primal weight carried across solves
    flag: jnp.ndarray   # scalar; > 0 iff the previous solve converged


def warm_size_pdlp(n_constraints: int, n_variables: int) -> int:
    """Length of the packed warm-start vector."""
    return n_variables + n_constraints + 2


def pack_warm_pdlp(ws: PDLPWarm) -> jnp.ndarray:
    return jnp.concatenate(
        [ws.x, ws.y, jnp.reshape(ws.omega, (1,)), jnp.reshape(ws.flag, (1,))]
    )


def unpack_warm_pdlp(
    vec: jnp.ndarray, n_constraints: int, n_variables: int
) -> PDLPWarm:
    r, m = n_variables, n_constraints
    return PDLPWarm(
        x=vec[:r], y=vec[r : r + m], omega=vec[r + m], flag=vec[r + m + 1]
    )


class PDLPResult(NamedTuple):
    """Solution of one LP (or a batch, under vmap)."""

    x: jnp.ndarray           # [R] primal solution, ORIGINAL coordinates
    objective: jnp.ndarray   # scalar c @ x
    primal_residual: jnp.ndarray  # ||A x - b||_inf (equilibrated, relative)
    dual_gap: jnp.ndarray    # relative primal-dual objective gap
    converged: jnp.ndarray   # bool: primal residual AND gap below tol
    iterations: jnp.ndarray  # int32 PDHG iterations actually run
    warm: PDLPWarm           # final iterate for seeding the next solve


def _ruiz_scales(absA, xp, passes: int = 10):
    """Two-sided Ruiz equilibration scales toward unit row/col inf-norms
    (same scheme as ``linprog._linprog_box_impl``). ``xp`` is the array
    module — ``jnp`` for the in-trace dense path, ``numpy`` for the
    host-side sparse precompute — so there is ONE definition of the
    scaling both solver forms (and their warm-start layouts) depend on
    being deterministic in ``A``.

    Returns ``(row_scale, col_scale)``.
    """
    m, r = absA.shape
    row_scale = xp.ones((m,), absA.dtype)
    col_scale = xp.ones((r,), absA.dtype)
    for _ in range(passes):
        scaled = absA * row_scale[:, None] * col_scale[None, :]
        row_scale = row_scale / xp.sqrt(
            xp.maximum(xp.max(scaled, axis=1), 1e-12)
        )
        scaled = absA * row_scale[:, None] * col_scale[None, :]
        col_scale = col_scale / xp.sqrt(
            xp.maximum(xp.max(scaled, axis=0), 1e-12)
        )
    return row_scale, col_scale


def _ruiz(A, b, c, lb, ub, passes: int = 10):
    """Apply Ruiz equilibration in-trace (dense path)."""
    row_scale, col_scale = _ruiz_scales(jnp.abs(A), jnp, passes)
    A = A * row_scale[:, None] * col_scale[None, :]
    return (
        A,
        b * row_scale,
        c * col_scale,
        lb / col_scale,
        ub / col_scale,
        row_scale,
        col_scale,
    )


class _PDState(NamedTuple):
    x: jnp.ndarray
    y: jnp.ndarray
    x_anchor: jnp.ndarray   # restart point (movement reference)
    y_anchor: jnp.ndarray
    omega: jnp.ndarray
    k: jnp.ndarray          # iterations run
    done: jnp.ndarray       # accepted at tol
    res_p: jnp.ndarray      # last KKT numbers (for the report)
    gap: jnp.ndarray


def pdlp_box(
    c: jnp.ndarray,
    A: jnp.ndarray,
    b: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    n_iter: int = 32768,
    tol: float = 1e-4,
    restart_every: int = 512,
    warm: PDLPWarm | None = None,
    sparse: bool | str = "auto",
) -> PDLPResult:
    """Solve ``min c@x  s.t. A@x = b, lb <= x <= ub`` by restarted PDHG.

    Single-problem arguments (``A`` is [M, R]); batch with ``jax.vmap``
    over ``(c, b, lb, ub)`` as needed — ``A`` static turns the per-
    iteration matvecs into one ``[N, R] @ [R, M]`` batch matmul.

    ``sparse``: exploit ``A``'s sparsity pattern with fixed-shape
    segment-sum matvecs — O(nnz) per iteration instead of O(M R).
    Stoichiometric matrices are extremely sparse (~3% at 72x180,
    ~99% zero for block/tiled networks), and PDHG touches ``A`` ONLY
    through matvecs, so this is where the first-order solver actually
    earns its keep at scale (bench_lp_scale.py records dense-IPM vs
    dense-PDLP vs sparse-PDLP). ``"auto"`` uses it when ``A`` is a
    concrete (non-traced) matrix with density <= 0.25; the pattern,
    equilibration, and step sizes are then precomputed host-side in
    numpy, shrinking the XLA program too. ``True`` forces it (errors on
    a traced ``A``); ``False`` keeps dense matmuls (the MXU-friendly
    form for small dense networks).

    ``n_iter`` caps TOTAL PDHG iterations (rounded up to whole restart
    windows); the loop exits early once accepted at ``tol`` (relative
    primal residual AND relative duality gap — acceptance is evaluated at
    restart boundaries, so reported iterations quantize to
    ``restart_every``). Infeasible problems come back ``converged=False``
    with a large residual; no exceptions inside jit.

    The default cap sits ABOVE the measured cold-start envelope
    (13k–25k iterations on the tiled-network sweep,
    ``BENCH_LP_SCALE_CPU_r05.json``) because an undersized cap is
    STICKY: a failed solve returns ``warm.flag = 0``, so a warm-start
    caller discards the iterate and repeats the same doomed cold solve
    every step — the problem never converges and the caller silently
    stalls. Size any override against the cold start, not the (far
    cheaper) warm-started steady state.
    """
    import numpy as np

    m = A.shape[0]
    concrete = not isinstance(A, jax.core.Tracer)
    if sparse is True and not concrete:
        raise ValueError(
            "pdlp_box(sparse=True) needs a concrete (non-traced) A: the "
            "sparsity pattern is a static shape"
        )
    use_sparse = bool(m) and concrete and (
        sparse is True
        or (
            sparse == "auto"
            and np.count_nonzero(np.asarray(A)) <= 0.25 * A.shape[0] * A.shape[1]
        )
    )
    with jax.default_matmul_precision("float32"):
        if use_sparse:
            return _pdlp_sparse_impl(
                c, A, b, lb, ub, n_iter, tol, restart_every, warm
            )
        return _pdlp_box_impl(
            c, A, b, lb, ub, n_iter, tol, restart_every, warm
        )


def _pdlp_sparse_impl(c, A, b, lb, ub, n_iter, tol, restart_every, warm):
    """Host-side (numpy) equilibration + COO pattern extraction, then the
    shared PDHG core with segment-sum matvecs. ``A`` must be concrete;
    ``b``/``c``/``lb``/``ub`` may be traced (they are scaled in-trace).

    Same ``result_type`` dtype promotion as ``_pdlp_box_impl``: under
    ``sparse="auto"`` the solve's precision must not silently depend on
    A's density — a float64 problem stays float64 on either path (the
    host-side pattern precompute is float64 regardless and only cast
    at the end)."""
    import numpy as np

    dtype = jnp.result_type(c.dtype, jnp.float32)
    An = np.asarray(A, np.float64)
    m, r = An.shape
    # Ruiz on host, float64 — the SAME _ruiz_scales the dense path runs
    # in-trace, so scaling stays deterministic in A and warm starts stay
    # coordinate-consistent across calls and across solver forms
    rs, cs = _ruiz_scales(np.abs(An), np)
    As = An * rs[:, None] * cs[None, :]
    rows, cols = np.nonzero(As)
    # two orderings so both matvecs run with sorted segment ids
    by_row = np.lexsort((cols, rows))
    by_col = np.lexsort((rows, cols))
    vals_r = jnp.asarray(As[rows, cols][by_row], dtype)
    rows_r = jnp.asarray(rows[by_row])
    cols_r = jnp.asarray(cols[by_row])
    vals_c = jnp.asarray(As[rows, cols][by_col], dtype)
    rows_c = jnp.asarray(rows[by_col])
    cols_c = jnp.asarray(cols[by_col])

    def Ax(x):
        return jax.ops.segment_sum(
            vals_r * x[cols_r], rows_r, num_segments=m,
            indices_are_sorted=True,
        )

    def ATy(y):
        return jax.ops.segment_sum(
            vals_c * y[rows_c], cols_c, num_segments=r,
            indices_are_sorted=True,
        )

    abs_sum0 = np.abs(As).sum(axis=0)  # per column
    abs_sum1 = np.abs(As).sum(axis=1)  # per row
    tau_d = jnp.asarray(1.0 / np.maximum(abs_sum0, 1e-12), dtype)
    sig_d = jnp.asarray(1.0 / np.maximum(abs_sum1, 1e-12), dtype)
    row_scale = jnp.asarray(rs, dtype)
    col_scale = jnp.asarray(cs, dtype)
    b = jnp.asarray(b, dtype) * row_scale
    c = jnp.asarray(c, dtype) * col_scale
    lb = jnp.asarray(lb, dtype) / col_scale
    ub = jnp.asarray(ub, dtype) / col_scale
    # an inverted box is an INFEASIBLE problem, not a clampable one:
    # solve the pinned version for shape-stability but report failure
    box_ok = jnp.all(ub >= lb)
    lb = jnp.minimum(lb, ub)
    return _pdlp_core(
        c, b, lb, ub, col_scale, tau_d, sig_d, Ax, ATy, m, r,
        n_iter, tol, restart_every, warm, dtype, box_ok,
    )


def _pdlp_box_impl(c, A, b, lb, ub, n_iter, tol, restart_every, warm):
    dtype = jnp.result_type(c.dtype, jnp.float32)
    c = jnp.asarray(c, dtype)
    A = jnp.asarray(A, dtype)
    b = jnp.asarray(b, dtype)
    lb = jnp.asarray(lb, dtype)
    ub = jnp.asarray(ub, dtype)
    m, r = A.shape

    box_ok = jnp.all(ub >= lb)
    if m:
        A, b, c, lb, ub, _row_scale, col_scale = _ruiz(A, b, c, lb, ub)
        # an inverted box is an INFEASIBLE problem: solve the pinned
        # version for shape-stability, report converged=False (box_ok)
        lb = jnp.minimum(lb, ub)
        # Pock-Chambolle (alpha = 1) diagonal step sizes; the primal
        # weight multiplies/divides these per restart round.
        tau_d = 1.0 / jnp.maximum(jnp.sum(jnp.abs(A), axis=0), 1e-12)
        sig_d = 1.0 / jnp.maximum(jnp.sum(jnp.abs(A), axis=1), 1e-12)
    else:
        # pure box LP: no equalities to scale against; one gradient step
        # on the (linear) objective followed by the clip is exact, so any
        # finite step works — normalize by the objective scale.
        col_scale = jnp.ones((r,), dtype)
        tau_d = jnp.full((r,), 0.9, dtype) / (1.0 + jnp.max(jnp.abs(c)))
        sig_d = jnp.zeros((0,), dtype)

    Ax = (lambda x: A @ x) if m else (lambda x: jnp.zeros((0,), dtype))
    ATy = (lambda y: A.T @ y) if m else (lambda y: jnp.zeros((r,), dtype))
    return _pdlp_core(
        c, b, lb, ub, col_scale, tau_d, sig_d, Ax, ATy, m, r,
        n_iter, tol, restart_every, warm, dtype, box_ok,
    )


def _pdlp_core(c, b, lb, ub, col_scale, tau_d, sig_d, Ax, ATy, m, r,
               n_iter, tol, restart_every, warm, dtype, box_ok):
    tol = jnp.asarray(tol, dtype)
    b_scale = 1.0 + jnp.max(jnp.abs(b)) if m else jnp.asarray(1.0, dtype)

    def kkt(x, y):
        """(relative primal residual, relative gap) at (x, y)."""
        rp = (jnp.max(jnp.abs(Ax(x) - b)) if m else jnp.asarray(0.0, dtype))
        red = c - (ATy(y) if m else 0.0)
        pobj = c @ x
        dobj = (b @ y if m else 0.0) + jnp.sum(
            jnp.minimum(red * lb, red * ub)
        )
        gap = jnp.abs(pobj - dobj) / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))
        return rp / b_scale, gap

    x0 = jnp.clip(jnp.zeros((r,), dtype), lb, ub)
    y0 = jnp.zeros((m,), dtype)
    omega0 = jnp.asarray(1.0, dtype)
    if warm is not None:
        use = jnp.asarray(warm.flag, dtype) > 0
        x0 = jnp.where(
            use, jnp.clip(jnp.asarray(warm.x, dtype) / col_scale, lb, ub), x0
        )
        y0 = jnp.where(use, jnp.asarray(warm.y, dtype), y0)
        omega0 = jnp.where(
            use, jnp.clip(jnp.asarray(warm.omega, dtype), 1e-3, 1e3), omega0
        )

    n_rounds = -(-int(n_iter) // int(restart_every))

    def round_body(st: _PDState) -> _PDState:
        tau = tau_d / st.omega
        sigma = sig_d * st.omega

        def pdhg(_, carry):
            x, y, xs, ys = carry
            x_new = jnp.clip(x - tau * (c - (ATy(y) if m else 0.0)), lb, ub)
            y_new = y + sigma * (b - Ax(2.0 * x_new - x)) if m else y
            return x_new, y_new, xs + x_new, ys + y_new

        zx = jnp.zeros_like(st.x)
        zy = jnp.zeros_like(st.y)
        x_end, y_end, xs, ys = lax.fori_loop(
            0, restart_every, pdhg, (st.x, st.y, zx, zy)
        )
        inv = 1.0 / jnp.asarray(restart_every, dtype)
        x_avg, y_avg = xs * inv, ys * inv

        # adaptive restart-to-average: continue from whichever candidate
        # scores better on the SAME acceptance metric
        rp_end, gap_end = kkt(x_end, y_end)
        rp_avg, gap_avg = kkt(x_avg, y_avg)
        score_end = jnp.maximum(rp_end, gap_end)
        score_avg = jnp.maximum(rp_avg, gap_avg)
        take_avg = score_avg < score_end
        x_next = jnp.where(take_avg, x_avg, x_end)
        y_next = jnp.where(take_avg, y_avg, y_end)
        rp = jnp.where(take_avg, rp_avg, rp_end)
        gap = jnp.where(take_avg, gap_avg, gap_end)

        # primal-weight rebalance from the window's movement ratio
        # (theta = 1/2: w <- sqrt(w * ||dy|| / ||dx||), clipped)
        dx = jnp.linalg.norm(x_next - st.x_anchor)
        dy = jnp.linalg.norm(y_next - st.y_anchor)
        ratio = jnp.clip(dy / jnp.maximum(dx, 1e-12), 1e-6, 1e6)
        omega = jnp.where(
            (dx > 1e-12) & (dy > 1e-12),
            jnp.clip(jnp.sqrt(st.omega * ratio), 1e-3, 1e3),
            st.omega,
        )

        accepted = (rp <= tol) & (gap <= tol)
        keep = lambda new, old: jnp.where(st.done, old, new)
        return _PDState(
            x=keep(x_next, st.x),
            y=keep(y_next, st.y),
            x_anchor=keep(x_next, st.x_anchor),
            y_anchor=keep(y_next, st.y_anchor),
            omega=keep(omega, st.omega),
            k=st.k + jnp.where(st.done, 0, restart_every).astype(jnp.int32),
            done=st.done | accepted,
            res_p=keep(rp, st.res_p),
            gap=keep(gap, st.gap),
        )

    rp0, gap0 = kkt(x0, y0)
    init = _PDState(
        x=x0,
        y=y0,
        x_anchor=x0,
        y_anchor=y0,
        omega=omega0,
        k=jnp.int32(0),
        done=(rp0 <= tol) & (gap0 <= tol),
        res_p=rp0,
        gap=gap0,
    )
    final = lax.while_loop(
        lambda st: (~st.done) & (st.k < n_rounds * restart_every),
        round_body,
        init,
    )

    x_orig = final.x * col_scale
    converged = final.done & box_ok
    return PDLPResult(
        x=x_orig,
        objective=jnp.asarray(c / col_scale, dtype) @ x_orig,
        primal_residual=final.res_p,
        dual_gap=final.gap,
        converged=converged,
        iterations=final.k,
        warm=PDLPWarm(
            x=x_orig, y=final.y, omega=final.omega,
            flag=converged.astype(dtype),
        ),
    )


def flux_balance_pdlp(
    stoichiometry: jnp.ndarray,
    objective: jnp.ndarray,
    lb: jnp.ndarray,
    ub: jnp.ndarray,
    n_iter: int = 32768,
    tol: float = 1e-4,
    leak: float = 0.0,
    warm: PDLPWarm | None = None,
    sparse: bool | str = "auto",
) -> PDLPResult:
    """FBA via PDLP: ``max objective @ v  s.t. S @ v = 0, lb <= v <= ub``.

    ``n_iter`` matches the ``pdlp_box`` default (32768, above the
    measured cold-start envelope — see its docstring for why an
    undersized cap is a sticky warm-start hazard) and the
    ``fba_metabolism`` process config's ``pdlp_iterations``.

    Drop-in analogue of :func:`lens_tpu.ops.linprog.flux_balance` (same
    leak-slack relaxation, same batching contract) built on the
    first-order solver — the path for networks past the dense-IPM
    crossover (see ``bench_lp_scale.py`` for where that is). Under
    ``sparse="auto"`` a concrete stoichiometry (the normal case — it is
    a static network constant even inside a jitted process step) gets
    O(nnz) segment-sum matvecs.
    """
    S = jnp.asarray(stoichiometry)
    m, r = S.shape
    c = -jnp.asarray(objective)
    if leak > 0.0 and m:
        S = jnp.concatenate([S, jnp.eye(m, dtype=S.dtype)], axis=1)
        c = jnp.concatenate([c, jnp.zeros(m, c.dtype)])
        lb = jnp.concatenate([jnp.asarray(lb), jnp.full(m, -leak, S.dtype)])
        ub = jnp.concatenate([jnp.asarray(ub), jnp.full(m, leak, S.dtype)])
    res = pdlp_box(
        c, S, jnp.zeros(m, S.dtype), lb, ub,
        n_iter=n_iter, tol=tol, warm=warm, sparse=sparse,
    )
    return res._replace(objective=-res.objective, x=res.x[:r])
