"""ADI diffusion (operator-split backward Euler) — unconditionally
stable AND positivity-preserving.

The FTCS stencil (:mod:`lens_tpu.ops.diffusion`) mirrors the reference's
explicit finite-difference step (reconstructed ``lens/environment/
lattice.py`` ``run_diffusion``; SURVEY.md §3.2) and needs
``ceil(alpha / 0.225)`` substeps per window for stability — 27 full-slab
passes for glucose-like diffusivities on 10 um bins. The ADI step here
removes the stability limit entirely: one window advances as two
axis-split IMPLICIT solves,

    (I - r L_x) u*      = u_n        L_a = clamped 1D second difference
    (I - r L_y) u_{n+1} = u*         r   = alpha = D*dt/dx^2

so the cost is two tridiagonal solves instead of ~27 stencil sweeps.

The scheme is deliberately the backward-Euler split, NOT the classical
Peaceman–Rachford half-steps: PR's explicit half ``(I + r L)`` has
negative stencil weights once ``r > 0.5``, so an agent's secretion spike
(this framework's normal input — ``apply_exchanges`` deposits point
masses) would diffuse into NEGATIVE concentrations at the default
``r = 3``. Each backward-Euler factor ``(I - r L)`` is an M-matrix whose
inverse is elementwise nonnegative, so nonnegative fields stay
nonnegative for ANY ``r``; and because ``L``'s columns sum to zero
(edge-clamped no-flux), each solve conserves mass exactly. The price is
first-order (vs PR's second-order) splitting accuracy — for environment
nutrient fields the substeps exist for stability, not accuracy, and
tests pin the error against a dense-substep FTCS oracle.

TPU mapping: every row (or column) solves the SAME constant-coefficient
tridiagonal system, so the Thomas factorization is precomputed once
(host numpy, float64) and each solve reduces to two FIRST-ORDER LINEAR
recurrences — forward substitution and back-substitution — evaluated as
``lax.associative_scan`` over affine maps ``x_i = m_i * x_{i-1} + t_i``.
That gives O(log H) depth with full lane parallelism across the other
axis and molecules: no sequential Thomas sweep, no scan-over-rows.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class ThomasFactors(NamedTuple):
    """Precomputed Thomas factors of ``(I - r * second_diff)`` per molecule.

    For the tridiagonal system with constant interior row
    ``[-r, 1 + 2r, -r]`` and Neumann ends ``[1 + r, -r]``, forward
    elimination's multipliers depend only on the matrix — so they are
    computed once in float64 and the per-solve work is two affine scans.

    Shapes are [M, N] (molecule, axis length). ``fwd_m``/``fwd_t_scale``
    define the forward recurrence ``d'_i = fwd_t_scale_i * d_i +
    fwd_m_i * d'_{i-1}``; ``back_c`` the back-substitution
    ``x_i = d'_i - back_c_i * x_{i+1}``.
    """

    fwd_m: jnp.ndarray
    fwd_t_scale: jnp.ndarray
    back_c: jnp.ndarray


def _tridiag_diagonal(
    r: np.ndarray, n: int, clamp_top: bool, clamp_bottom: bool
) -> np.ndarray:
    """The ONE definition of ``(I - r L)``'s diagonal ([m, n], float64).

    Shared by the banded Thomas factorization below and the dense
    assembler (:func:`dense_tridiag`) the SPIKE plan solves against —
    keeping the two descriptions of the same matrix bit-identical.
    """
    r = np.asarray(r, np.float64).reshape(-1)
    diag = np.full((r.shape[0], n), 1.0, np.float64) + 2.0 * r[:, None]
    if clamp_top:
        diag[:, 0] = 1.0 + r
    if clamp_bottom:
        diag[:, -1] = 1.0 + r
    if n == 1 and clamp_top and clamp_bottom:
        # clamped Laplacian of a length-1 axis is the zero operator (a
        # length-1 SLICE of a distributed axis keeps 1+r / 1+2r from the
        # writes above — its neighbors exist, they're just remote)
        diag[:, 0] = 1.0
    return diag


def dense_tridiag(
    r: float, n: int, clamp_top: bool = True, clamp_bottom: bool = True
) -> np.ndarray:
    """Dense ``I - r L`` for ONE molecule (float64, host) — the oracle
    form of the matrix :func:`thomas_factors` factorizes."""
    diag = _tridiag_diagonal(np.asarray([r]), n, clamp_top, clamp_bottom)[0]
    a = np.diag(diag)
    for i in range(1, n):
        a[i, i - 1] = -r
        a[i - 1, i] = -r
    return a


def thomas_factors(
    r: np.ndarray,
    n: int,
    clamp_top: bool = True,
    clamp_bottom: bool = True,
) -> ThomasFactors:
    """Factor ``(I - r L)`` for each molecule's ``r`` (L = clamped 1D
    Laplacian of length ``n``). Host-side, float64.

    ``clamp_top``/``clamp_bottom`` mark which ends carry the Neumann
    clamp (diag ``1 + r``). A shard that owns an INTERIOR slice of a
    distributed axis has ordinary ``1 + 2r`` end rows instead — its
    neighbors' coupling is handled by the SPIKE interface correction
    (parallel.adi_spike), not by the local matrix.
    """
    r = np.asarray(r, np.float64).reshape(-1)
    m = r.shape[0]
    diag = _tridiag_diagonal(r, n, clamp_top, clamp_bottom)
    lower = -r[:, None] * np.ones((m, n), np.float64)  # a_i (i>0)
    upper = -r[:, None] * np.ones((m, n), np.float64)  # c_i (i<n-1)

    cp = np.zeros((m, n), np.float64)     # c'_i
    inv = np.zeros((m, n), np.float64)    # 1 / (b_i - a_i c'_{i-1})
    inv[:, 0] = 1.0 / diag[:, 0]
    cp[:, 0] = upper[:, 0] * inv[:, 0]
    for i in range(1, n):
        inv[:, i] = 1.0 / (diag[:, i] - lower[:, i] * cp[:, i - 1])
        cp[:, i] = upper[:, i] * inv[:, i]

    # forward recurrence d'_i = inv_i * d_i - inv_i * a_i * d'_{i-1}
    fwd_m = -lower * inv
    fwd_m[:, 0] = 0.0
    return ThomasFactors(
        fwd_m=jnp.asarray(fwd_m, jnp.float32),
        fwd_t_scale=jnp.asarray(inv, jnp.float32),
        back_c=jnp.asarray(cp, jnp.float32),
    )


def _affine_scan(m: jnp.ndarray, t: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Evaluate ``x_i = m_i * x_{i-1} + t_i`` (x_{-1} = 0) along ``axis``
    via associative composition of affine maps."""

    def compose(f, g):  # g AFTER f, both (m, t)
        return (g[0] * f[0], g[0] * f[1] + g[1])

    _, x = lax.associative_scan(compose, (m, t), axis=axis)
    return x


def solve_tridiag(factors: ThomasFactors, d: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Solve ``(I - r L) x = d`` along ``axis`` of ``d`` [M, H, W].

    ``factors`` must have been built for that axis' length; broadcasting
    aligns the factor vectors along ``axis`` with the molecule dim 0.
    """
    n = d.shape[axis]
    shape = [1, 1, 1]
    shape[0] = factors.fwd_m.shape[0]
    shape[axis] = n
    fwd_m = factors.fwd_m.reshape(shape)
    fwd_t = factors.fwd_t_scale.reshape(shape)
    back_c = factors.back_c.reshape(shape)

    dp = _affine_scan(fwd_m, fwd_t * d, axis=axis)

    # back-substitution x_i = d'_i - c'_i x_{i+1}: reverse, then the same
    # affine form with m_i = -c'_i. (The first element of an affine scan's
    # m is never read — x_0 = t_0 — so the flipped array needing "no
    # coefficient" at its head is already satisfied.)
    x_r = _affine_scan(jnp.flip(-back_c, axis), jnp.flip(dp, axis), axis=axis)
    return jnp.flip(x_r, axis)


class ADIPlan(NamedTuple):
    """Precomputed per-lattice ADI step: factors for both axes."""

    row_factors: ThomasFactors   # for solves along H (axis 1)
    col_factors: ThomasFactors   # for solves along W (axis 2)


def adi_plan(alpha: np.ndarray, h: int, w: int) -> ADIPlan:
    """Build the ADI step plan for fields [M, h, w] with per-molecule
    ``alpha`` = D*dt/dx^2 for the WHOLE window (not per substep)."""
    r = np.asarray(alpha, np.float64).reshape(-1)
    return ADIPlan(
        row_factors=thomas_factors(r, h),
        col_factors=thomas_factors(r, w),
    )


def diffuse_adi(fields: jnp.ndarray, plan: ADIPlan) -> jnp.ndarray:
    """One backward-Euler-split window step of ``fields`` [M, H, W].

    Both factors commute (Kronecker structure), so the solve order does
    not bias the result; nonnegative input stays nonnegative (M-matrix
    inverses) and per-molecule mass is conserved exactly.
    """
    u_half = solve_tridiag(plan.row_factors, fields, axis=1)
    return solve_tridiag(plan.col_factors, u_half, axis=2)
