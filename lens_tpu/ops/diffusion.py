"""2D diffusion stencils — the environment lattice's hot kernel.

The reference advances its molecular fields with a finite-difference
diffusion step in numpy/scipy (reconstructed:
``lens/environment/lattice.py`` ``run_diffusion``, SURVEY.md §3.2 — one of
the two hot loops BASELINE.json targets). Here the 5-point FTCS stencil

    F' = F + (D * dt / dx^2) * (F_up + F_down + F_left + F_right - 4 F)

with no-flux (Neumann) boundaries is provided in two implementations:

- ``diffuse_xla``: pad+slice shifts, fused by XLA — the portable baseline;
- ``diffuse_pallas``: a Pallas TPU kernel holding the whole field slab in
  VMEM and scanning substeps on-core, so one HBM round-trip covers all
  substeps of an exchange window (the XLA path reads/writes HBM per
  substep unless XLA manages to fuse the scan — it usually doesn't).

``diffuse`` dispatches by backend; both paths are numerically identical
(same order of adds), which the tests assert.

Stability: FTCS needs alpha = D*dt/dx^2 <= 0.25 in 2D. Callers pick the
substep count; ``stable_substeps`` computes the minimum.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def stable_substeps(d_max: float, dt: float, dx: float, safety: float = 0.9) -> int:
    """Minimum FTCS substeps for stability: alpha <= 0.25 * safety."""
    if d_max <= 0.0:
        return 1
    alpha = d_max * dt / (dx * dx)
    return max(1, math.ceil(alpha / (0.25 * safety)))


def _neumann_laplacian(f: jnp.ndarray) -> jnp.ndarray:
    """5-point Laplacian with edge-clamped (no-flux) boundaries.

    f: [..., H, W]. Edge clamping makes the boundary-normal gradient zero,
    so total mass is conserved exactly (up to float addition order).
    """
    up = jnp.concatenate([f[..., :1, :], f[..., :-1, :]], axis=-2)
    down = jnp.concatenate([f[..., 1:, :], f[..., -1:, :]], axis=-2)
    left = jnp.concatenate([f[..., :, :1], f[..., :, :-1]], axis=-1)
    right = jnp.concatenate([f[..., :, 1:], f[..., :, -1:]], axis=-1)
    return up + down + left + right - 4.0 * f


def diffuse_xla(
    fields: jnp.ndarray,
    alpha: jnp.ndarray,
    n_substeps: int,
) -> jnp.ndarray:
    """FTCS diffusion, XLA implementation.

    fields: [M, H, W]; alpha: [M] = D*dt_sub/dx^2 per molecule (already
    divided by n_substeps).
    """
    a = alpha.reshape(-1, 1, 1)

    def body(f, _):
        return f + a * _neumann_laplacian(f), None

    out, _ = jax.lax.scan(body, fields, None, length=n_substeps)
    return out


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


#: The kernel's VMEM working set is ~6 slabs of one [H, W] field: input
#: block, output block, and the four shifted stencil copies the
#: concatenates materialize (measured on v5e: a 4 MiB slab allocates
#: 23.8 MiB of scoped VMEM). Budget against 14 MiB of the core's 16 MiB
#: so tiling padding and scalar buffers always fit.
_VMEM_KERNEL_SLABS = 6
_VMEM_BUDGET_BYTES = 14 * 1024 * 1024


def _fits_vmem(fields: jnp.ndarray) -> bool:
    _, h, w = fields.shape
    # account for tiling padding: VMEM allocations round up to (8, 128)
    h_pad = -(-h // 8) * 8
    w_pad = -(-w // 128) * 128
    slab = h_pad * w_pad * fields.dtype.itemsize
    return _VMEM_KERNEL_SLABS * slab <= _VMEM_BUDGET_BYTES


def diffuse_pallas(
    fields: jnp.ndarray,
    alpha: jnp.ndarray,
    n_substeps: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """FTCS diffusion as a Pallas TPU kernel, gridded over molecules.

    Each grid step pulls one [H, W] slab into VMEM, runs every substep
    there, and writes back once — substeps cost zero extra HBM traffic.
    A 256x256 f32 slab is 256 KiB, comfortably inside ~16 MiB VMEM.
    """
    m, h, w = fields.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[pl.BlockSpec((1, h, w), lambda i, *_: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, h, w), lambda i, *_: (i, 0, 0)),
    )

    def kernel(alpha_sref, f_ref, out_ref):
        i = pl.program_id(0)
        f = f_ref[0]
        a = alpha_sref[i]

        def body(_, f):
            return f + a * _neumann_laplacian(f)

        out_ref[0] = jax.lax.fori_loop(0, n_substeps, body, f)

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(fields.shape, fields.dtype),
        interpret=interpret,
    )(alpha, fields)


def _tile_rows(h: int, w: int, halo: int, itemsize: int) -> Optional[int]:
    """Largest row-tile height (multiple of 8) whose padded halo tile fits
    the VMEM budget, or None if even the minimum does not fit."""
    w_pad = -(-w // 128) * 128
    max_t = _VMEM_BUDGET_BYTES // (_VMEM_KERNEL_SLABS * w_pad * itemsize)
    tile_h = (max_t - 2 * halo) // 8 * 8
    if tile_h < 8:
        return None
    return min(tile_h, -(-h // 8) * 8)


def diffuse_pallas_tiled(
    fields: jnp.ndarray,
    alpha: jnp.ndarray,
    n_substeps: int,
    tile_h: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """FTCS diffusion for slabs BEYOND the whole-field VMEM budget:
    halo-overlap row tiling.

    The whole-slab kernel (:func:`diffuse_pallas`) wins by keeping every
    substep in VMEM; past ~14 MiB it cannot. This variant grids over
    (molecule, row-tile) with each tile carrying ``halo = n_substeps``
    extra rows on each side: one ghost row per substep is exactly the
    staleness frontier of the 5-point stencil, so after all substeps the
    tile's center rows are bit-correct while only its (discarded) halo
    is stale. Substeps still cost zero extra HBM traffic; the price is
    one overlapped gather (~``1 + 2*halo/tile_h`` x field size) and
    ``2*halo`` redundant rows of compute per tile — for a 1024x1024
    field at 27 substeps that is ~10% overhead against the XLA path's
    27 full-slab HBM round-trips.

    Halo rows beyond the field edge use **mirror (symmetric) extension**,
    which is exactly the even reflection the edge-clamped Neumann stencil
    conserves: the mirrored rows evolve identically to their real
    counterparts, so edge tiles need no special casing.

    Falls back assumptions: ``halo < h`` (else the mirror indexing would
    wrap twice) and a tile must fit VMEM — ``diffuse``'s auto dispatch
    checks both via :func:`_tile_rows`.
    """
    m, h, w = fields.shape
    halo = n_substeps
    if tile_h is None:
        tile_h = _tile_rows(h, w, halo, fields.dtype.itemsize)
        if tile_h is None:
            raise ValueError(
                f"no row tile of [{h}, {w}] fields fits the VMEM budget "
                f"with halo={halo}"
            )
    if halo + 8 > h:
        # Mirror-index safety: a gathered index is clipped (instead of
        # double-reflected) only when it lies >= 2h before reflection.
        # Retained output rows have index <= h-1, and the gather is
        # contiguous in original index space, so every clipped row sits
        # >= h+1 rows from every retained row. Staleness from a wrong
        # halo row travels one row per substep, so it can never reach a
        # retained row while halo <= h - 8 < h + 1. (The last tile's
        # round-up overhang — up to tile_h-1 rows — is discarded at
        # scatter and already absorbed by the distance bound.)
        raise ValueError(
            f"halo {halo} too large for field height {h}: use diffuse_pallas"
        )
    n_t = -(-h // tile_h)
    t_rows = tile_h + 2 * halo

    # Overlapped, mirror-extended gather: tile k holds rows
    # [k*tile_h - halo, (k+1)*tile_h + halo) with out-of-range indices
    # reflected about the field edges (symmetric/no-flux extension).
    idx = (
        jnp.arange(n_t)[:, None] * tile_h
        + jnp.arange(t_rows)[None, :]
        - halo
    )
    idx = jnp.where(idx < 0, -1 - idx, idx)
    idx = jnp.where(idx >= h, 2 * h - 1 - idx, idx)
    idx = jnp.clip(idx, 0, h - 1)  # guard round-up slack; clipped rows
    # can only sit in a discarded halo region (see the halo+8 check)
    tiles = fields[:, idx, :]  # [m, n_t, t_rows, w]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m, n_t),
        in_specs=[
            pl.BlockSpec((1, 1, t_rows, w), lambda i, j, *_: (i, j, 0, 0))
        ],
        out_specs=pl.BlockSpec(
            (1, 1, tile_h, w), lambda i, j, *_: (i, j, 0, 0)
        ),
    )

    def kernel(alpha_sref, f_ref, out_ref):
        i = pl.program_id(0)
        f = f_ref[0, 0]
        a = alpha_sref[i]

        def body(_, f):
            return f + a * _neumann_laplacian(f)

        out = jax.lax.fori_loop(0, n_substeps, body, f)
        out_ref[0, 0] = out[halo : halo + tile_h]

    tiled_out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n_t, tile_h, w), fields.dtype),
        interpret=interpret,
    )(alpha, tiles)
    return tiled_out.reshape(m, n_t * tile_h, w)[:, :h, :]


@functools.partial(jax.jit, static_argnames=("n_substeps", "impl"))
def diffuse(
    fields: jnp.ndarray,
    alpha: jnp.ndarray,
    n_substeps: int,
    impl: str = "auto",
) -> jnp.ndarray:
    """Dispatching entry point. ``alpha`` = D*dt_sub/dx^2, shape [M].

    impl: 'auto' (pallas on TPU, xla elsewhere), 'xla', 'pallas',
    'pallas_tiled' (halo-overlap row tiling for slabs beyond VMEM — kept
    out of 'auto' until an on-device A/B records it beating XLA at
    >=1024^2, the same evidence bar the whole-slab kernel cleared),
    'pallas_interpret' / 'pallas_tiled_interpret' (CPU tests of the
    kernel logic).
    """
    if impl == "auto":
        # Recorded A/B on TPU v5e (bench_diffusion_ab.py ->
        # BENCH_DIFFUSION_AB.json, round 3; SURVEY.md §7 step 5 "keep
        # whichever wins"). The decisive number is IN CONTEXT: the
        # config-2 colony window runs 8.46M agent-steps/s with the Pallas
        # kernel vs 5.24M with the XLA path (1.6x) — inside the big step
        # program XLA spills the substep scan to HBM, while the kernel
        # pins the slab in VMEM. (A stencil chain benchmarked ALONE flips
        # the result — XLA fuses it perfectly when it's the whole program
        # — which is why this decision is recorded from the in-context
        # run; see the AB json for both.) Over the VMEM budget, XLA's
        # tiling is the only option.
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
        if impl == "pallas" and not _fits_vmem(fields):
            impl = "xla"
    if impl == "xla":
        return diffuse_xla(fields, alpha, n_substeps)
    if impl == "pallas":
        return diffuse_pallas(fields, alpha, n_substeps)
    if impl == "pallas_interpret":
        return diffuse_pallas(fields, alpha, n_substeps, interpret=True)
    if impl == "pallas_tiled":
        return diffuse_pallas_tiled(fields, alpha, n_substeps)
    if impl == "pallas_tiled_interpret":
        return diffuse_pallas_tiled(fields, alpha, n_substeps, interpret=True)
    raise ValueError(f"unknown impl {impl!r}")
