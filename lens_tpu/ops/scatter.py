"""Multi-channel scatter-add — the coupling layer's one hot primitive.

Both halves of the agent<->lattice coupling reduce to the same op
(environment.spatial: occupancy counting and exchange application are
the two segment-sums of one step):

    scatter_add_2d(base [C, B], idx [N], upd [C, N]) -> [C, B]
    out = base;  out[c, idx[n]] += upd[c, n]   (OOB indices dropped)

Two implementations, bitwise-identical by construction (both left-fold
the updates in row order; asserted in tests/test_spatial.py):

- **XLA** ``base.at[:, idx].add(upd)`` — the portable baseline, and the
  only path on accelerators (TPU scatters are handled by the backend).
- **Native CPU kernel** (``native/coupling_scatter.cpp`` via XLA FFI) —
  XLA's CPU scatter lowers to a generic serial update loop measured at
  ~35-45 ns/update, which at colony scale IS the coupling phase
  (BENCH_PHASES_CPU_r07.json); the native loop is the same fold at
  ~1-2 ns/update. Built on first use with the repo Makefile (g++ is part
  of the baked toolchain); any build/load failure falls back to the XLA
  path — functionality never blocks on the native path, mirroring
  ``lens_tpu.native``'s emit-writer contract.

The native path is used only when every operand matches the kernel
contract (CPU backend, f32 data, i32 indices); everything else takes the
XLA path. The dispatch happens at trace time, so a jitted program bakes
in whichever path its backend gets.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import jax
import jax.numpy as jnp

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libcoupling_scatter.so")
_FFI_TARGET = "lens_coupling_scatter_add_f32"

_lock = threading.Lock()
_ready: bool | None = None  # None = not yet attempted


def _ffi_module():
    """jax's FFI surface across versions: ``jax.ffi`` (jax >= 0.5/0.6,
    where ``jax.extend.ffi`` was deprecated and then removed) with the
    ``jax.extend.ffi`` original as fallback — same API subset used here
    (include_dir, register_ffi_target, pycapsule, ffi_call). Returns
    None when neither exists."""
    try:
        import jax.ffi as ffi

        return ffi
    except ImportError:
        pass
    try:
        import jax.extend.ffi as ffi

        return ffi
    except ImportError:
        return None


def _build_and_register() -> bool:
    """Build (if needed), load, and FFI-register the kernel. False on any
    failure — callers fall back to XLA."""
    ffi = _ffi_module()
    if ffi is None:
        return False
    if not os.path.exists(_SO_PATH):
        try:
            subprocess.run(
                [
                    "make", "-C", _NATIVE_DIR, "scatter",
                    f"JAXLIB_INCLUDE={ffi.include_dir()}",
                ],
                check=True,
                capture_output=True,
                timeout=180,
            )
        except (subprocess.SubprocessError, OSError, AttributeError):
            # AttributeError: an ffi surface without include_dir —
            # same verdict as a failed build, fall back to XLA
            return False
        if not os.path.exists(_SO_PATH):
            return False
    try:
        lib = ctypes.CDLL(_SO_PATH)
        ffi.register_ffi_target(
            _FFI_TARGET,
            ffi.pycapsule(lib.LensCouplingScatterAdd),
            platform="cpu",
        )
    except (OSError, AttributeError):
        return False
    return True


def native_scatter_ready() -> bool:
    """True iff the native kernel is built, loaded, and registered
    (attempted at most once per process)."""
    global _ready
    if _ready is None:
        with _lock:
            if _ready is None:
                _ready = _build_and_register()
    return _ready


def _native_eligible(base, idx, upd) -> bool:
    return (
        jax.default_backend() == "cpu"
        and base.dtype == jnp.float32
        and upd.dtype == jnp.float32
        and idx.dtype == jnp.int32
        and base.ndim == 2
        and idx.ndim == 1
        and upd.ndim == 2
        and native_scatter_ready()
    )


def scatter_add_2d(base, idx, upd):
    """``base[c, idx[n]] += upd[c, n]`` for all (c, n); returns the new
    [C, B] array. Out-of-bounds indices are dropped (XLA scatter
    semantics — callers clip anyway). Duplicate indices accumulate in
    row order on CPU, so the two implementations agree bitwise.

    ``base`` is input-output aliased on the native path: when XLA can
    prove the operand dead it updates in place (the common case — a
    fresh zeros canvas or a donated fields buffer), otherwise it
    inserts the copy itself.
    """
    if _native_eligible(base, idx, upd):
        try:
            return _ffi_module().ffi_call(
                _FFI_TARGET,
                jax.ShapeDtypeStruct(base.shape, base.dtype),
                vmap_method="sequential",
                input_output_aliases={0: 0},
            )(base, idx, upd)
        except (TypeError, AttributeError):
            # an ffi_call surface without the callable-returning
            # signature / vmap_method kwarg (older jax.extend.ffi):
            # honor the never-block contract — disable the native path
            # for the process and take the XLA scatter
            global _ready
            _ready = False
    return base.at[:, idx].add(upd)
