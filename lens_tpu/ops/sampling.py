"""Batched hybrid Poisson sampling — the stochastic-expression RNG fast path.

``jax.random.poisson`` is correct but expensive inside the tau-leap hot
loop: every draw runs Knuth/transformed-rejection loops that each burn
fresh threefry invocations, measured at ~750 FLOPs per draw on the
expression step (``bench_mfu.py`` round 5 — the Poisson RNG, not the
propensity arithmetic, dominated ``GENE_FLOPS``). The TPU Monte-Carlo
literature keeps the chip fed with batched, counter-based sampling and
cheap large-mean approximations instead (Ising on TPU clusters, arXiv
1903.11714); this module is that technique for the expression stack.

The sampler is a **quantile transform**: one uniform per draw, pushed
through the Poisson inverse CDF,

- **small means** (``lam <= threshold``): exact sequential CDF inversion
  with a FIXED trip count — ``k = #{i : u > CDF(i)}`` with the pmf
  recurrence ``p_{i+1} = p_i * lam / (i+1)``. ~4 FLOPs per unrolled term
  (the trip count is static in ``threshold``), and distributionally
  EXACT to float32 CDF resolution.
- **large means** (``lam > threshold``): normal quantile with
  Cornish–Fisher skewness correction and continuity rounding,
  ``floor(lam + sqrt(lam) z + (z^2-1)/6 + (z^3-7z)/(36 sqrt(lam)) + 1/2)``
  with ``z = ndtri(u)``. Approximate by construction: the pmf
  discrepancy (chi-square divergence per sample) peaks at ~7e-4 right
  above the default threshold and decays like ~1/lam^2 (calibrated in
  ``tests/test_sampling.py``, which pins a 2e-3 bound); means/variances
  match to sampling noise. This sits well below the tau-leap
  discretization bias the expression processes already accept
  (``ops.gillespie`` docstring) — shrink ``tau`` before worrying about
  this term.

Both branches are elementwise and fused under ``jnp.where`` (no
data-dependent control flow), so the sampler stays jit/vmap/shard_map
compatible and costs ~200 FLOPs per draw regardless of regime — the
~3.5x per-draw win ``BENCH_PHASES_CPU_r06.json`` records.

The second half of the win is RNG **batching**: callers that need many
draws per step (tau-leap windows draw ``[n_substeps, R]`` events) should
draw ONE fused uniform block with :func:`uniform_block` and feed slices
to :func:`poisson_from_uniform` — a single threefry batch per expression
window instead of per-channel per-draw key folding
(``ops.gillespie.tau_leap_window`` does exactly this).

The ``sampler="exact"`` escape hatch routes to ``jax.random.poisson``
unchanged — bitwise-identical to the pre-fast-path code, kept for oracle
tests and resume flows of checkpoints recorded under the exact sampler
(the two samplers consume the PRNG key differently, so switching mid-run
changes the trajectory — correctness-neutral, but not bitwise).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

Array = jax.Array

#: Regime split. Below: exact fixed-trip CDF inversion; above: normal +
#: Cornish–Fisher quantile. 10 balances the inversion trip count (44
#: terms) against where the CF approximation is already good (~7e-4 pmf
#: divergence at the boundary, decaying fast).
DEFAULT_THRESHOLD = 10.0

#: Hard ceiling on the threshold knob: the inversion branch starts from
#: ``exp(-lam)``, which UNDERFLOWS float32 near lam ~ 87 — past that the
#: pmf recurrence is identically zero and every draw returns the trip
#: count, deterministically (silently wrong, zero variance). 80 keeps
#: an order-of-magnitude margin above the float32 normal minimum.
MAX_THRESHOLD = 80.0

SAMPLERS = ("hybrid", "exact")


def check_threshold(threshold: float) -> float:
    """Validate the regime-split knob at trace/config time."""
    t = float(threshold)
    if not 0.0 <= t <= MAX_THRESHOLD:
        raise ValueError(
            f"sampler threshold must be in [0, {MAX_THRESHOLD}] (float32 "
            f"exp(-lam) underflows past ~87, making the inversion branch "
            f"deterministically wrong), got {threshold!r}"
        )
    return t


def check_sampler(sampler: str) -> str:
    """Validate a sampler name at trace/config time (not mid-jit)."""
    if sampler not in SAMPLERS:
        raise ValueError(
            f"sampler must be one of {SAMPLERS}, got {sampler!r}"
        )
    return sampler


def inversion_trip_count(threshold: float) -> int:
    """Static trip count of the small-mean inversion: covers the
    Poisson(threshold) tail to ~1e-14 (8.5 sigma + 7), so the fixed
    loop's truncation is invisible at float32 CDF resolution."""
    t = max(float(threshold), 0.0)
    return int(math.ceil(t + 8.5 * math.sqrt(t) + 7.0))


def uniform_block(key: Array, shape) -> Array:
    """One fused threefry batch of uniforms in [0, 1) — THE bulk-RNG
    block callers slice per substep/channel (one device RNG op per
    expression window, however many draws it feeds)."""
    return jax.random.uniform(key, shape, jnp.float32)


def poisson_from_uniform(
    u: Array,
    lam: Array,
    threshold: float = DEFAULT_THRESHOLD,
) -> Array:
    """Poisson(lam) counts from uniforms by hybrid inverse-CDF transform.

    ``u`` and ``lam`` broadcast elementwise; returns float32 counts (the
    expression stack keeps molecule counts as exact-integer floats, see
    ``ops.gillespie``). Monotone in ``u`` (a true quantile transform),
    so common-random-number comparisons across parameters stay coupled.
    """
    threshold = check_threshold(threshold)
    dtype = jnp.float32
    lam = jnp.asarray(lam, dtype)
    u = jnp.asarray(u, dtype)

    # -- small regime: exact sequential inversion, fixed trip count.
    # min() keeps exp(-lam) from underflowing when the element actually
    # belongs to the large branch (the where() below discards this lane).
    small_lam = jnp.minimum(lam, threshold)
    p = jnp.exp(-small_lam)
    c = p
    k = jnp.zeros(jnp.broadcast_shapes(u.shape, lam.shape), dtype)
    for i in range(1, inversion_trip_count(threshold) + 1):
        k = k + (u > c).astype(dtype)
        p = p * (small_lam * (1.0 / i))
        c = c + p

    # -- large regime: normal + Cornish–Fisher skew term + continuity
    # rounding. max() keeps sqrt/1/sqrt finite when the element belongs
    # to the small branch (0 * inf would poison the where()).
    big_lam = jnp.maximum(lam, threshold)
    z = jax.scipy.special.ndtri(
        jnp.clip(u, jnp.finfo(dtype).tiny, 1.0 - jnp.finfo(dtype).epsneg)
    )
    s = jnp.sqrt(big_lam)
    w = (
        big_lam
        + s * z
        + (z * z - 1.0) / 6.0
        + (z * z * z - 7.0 * z) / (36.0 * s)
    )
    big = jnp.maximum(jnp.floor(w + 0.5), 0.0)

    return jnp.where(lam <= threshold, k, big)


def poisson_hybrid(
    key: Array,
    lam: Array,
    threshold: float = DEFAULT_THRESHOLD,
) -> Array:
    """Hybrid Poisson(lam) draw: ONE uniform batch for the whole ``lam``
    array (a single threefry invocation), then the quantile transform."""
    return poisson_from_uniform(
        uniform_block(key, jnp.shape(lam)), lam, threshold
    )


def sample_poisson(
    key: Array,
    lam: Array,
    sampler: str = "hybrid",
    threshold: float = DEFAULT_THRESHOLD,
) -> Array:
    """Poisson(lam) as float32 counts under the named sampler.

    ``sampler="hybrid"``: :func:`poisson_hybrid` (the fast path).
    ``sampler="exact"``: ``jax.random.poisson`` verbatim — bitwise
    identical RNG consumption to the pre-fast-path code, for oracle
    tests and resuming checkpoints recorded under the exact sampler.
    """
    check_sampler(sampler)
    if sampler == "exact":
        return jax.random.poisson(key, lam).astype(jnp.float32)
    return poisson_hybrid(key, lam, threshold)
