"""Stochastic chemical kinetics: fixed-shape tau-leaping (+ exact SSA oracle).

The reference's stochastic expression processes draw discrete reaction
events per timestep (reconstructed: ``lens/processes/`` stochastic
transcription/translation modules, SURVEY.md §2 "Gene expression
processes"). Exact Gillespie SSA is shape-hostile on TPU — each step
fires ONE reaction at a data-dependent time — so the device path is
**tau-leaping** (Gillespie 2001): within a leap ``tau``, each reaction
channel fires ``Poisson(a_r(x) * tau)`` times, all channels at once,
fixed shapes throughout (SURVEY.md §7 "Gillespie on TPU").

Negativity control: candidate event counts are capped per reaction by the
firings its consumed species can support from the pre-leap state
(``floor(x_s / |nu_rs|)`` min over consumed species). Concurrent
reactions draining the same species can still jointly overshoot, so a
final clamp floors counts at zero; shrink ``tau`` (more substeps) until
the cap/clamp rate is negligible — the tests quantify the resulting bias
against exact SSA and analytic stationary moments.

``ssa_exact`` is a host-side numpy oracle (the reference-fidelity
implementation tests compare against); never call it in device code.

**Samplers.** The Poisson event draw is the measured hot spot of the
expression stack (~750 FLOPs/draw of threefry-based rejection in
``jax.random.poisson`` — ``bench_mfu.py`` round 5), so both entry
points take a ``sampler`` argument (``ops.sampling``):

- ``"exact"`` (ops-level default): ``jax.random.poisson`` with the
  original per-substep key split — bitwise-identical RNG consumption
  to the pre-fast-path code, for oracle tests and resuming checkpoints
  recorded under it.
- ``"hybrid"``: the batched quantile-transform sampler. The window
  draws ONE fused ``[n_substeps, R]`` uniform threefry block up front
  and pushes slices through the hybrid inverse CDF — exact inversion
  below ``threshold`` mean events, normal+Cornish–Fisher above (error
  budget in ``ops.sampling``; well under the tau-leap bias this module
  already accepts). The expression processes default to this path.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.ops.sampling import (
    DEFAULT_THRESHOLD,
    check_sampler,
    poisson_from_uniform,
    uniform_block,
)

Array = jax.Array
PropensityFn = Callable[[Array], Array]  # counts [S] -> propensities [R]


def _fire(counts: Array, stoich: Array, events: Array) -> Array:
    """Apply capped/clamped reaction firings: counts [S] -> counts [S]."""
    # Cap each channel by what its consumed species allow (pre-leap).
    consumed = jnp.maximum(-stoich, 0.0)  # [R, S] units consumed per firing
    supportable = jnp.where(
        consumed > 0, counts[None, :] / jnp.maximum(consumed, 1e-12), jnp.inf
    )  # [R, S]
    max_fire = jnp.floor(jnp.min(supportable, axis=1))  # [R]
    events = jnp.minimum(events, max_fire)
    # Full f32 precision: TPU matmuls default to bfloat16, whose 8-bit
    # mantissa would round event/count sums above 256 to non-integers —
    # molecule counts must stay exact integers.
    new = counts + jnp.matmul(
        events, stoich, precision=jax.lax.Precision.HIGHEST
    )
    return jnp.maximum(new, 0.0)


def tau_leap_step(
    key: Array,
    counts: Array,
    stoich: Array,
    propensity_fn: PropensityFn,
    tau: Array | float,
    sampler: str = "exact",
    threshold: float = DEFAULT_THRESHOLD,
) -> Array:
    """One tau-leap: counts [S] -> counts [S]. Pure, jit/vmap-safe.

    stoich: [R, S] net change per firing of each reaction.
    """
    check_sampler(sampler)
    a = propensity_fn(counts)  # [R]
    lam = jnp.maximum(a, 0.0) * tau
    if sampler == "exact":
        events = jax.random.poisson(key, lam).astype(jnp.float32)  # [R]
    else:
        events = poisson_from_uniform(
            uniform_block(key, lam.shape), lam, threshold
        )
    return _fire(counts, stoich, events)


def tau_leap_window(
    key: Array,
    counts: Array,
    stoich: Array,
    propensity_fn: PropensityFn,
    timestep: Array | float,
    n_substeps: int,
    sampler: str = "exact",
    threshold: float = DEFAULT_THRESHOLD,
) -> Array:
    """Advance ``timestep`` in ``n_substeps`` leaps via lax.scan.

    Under ``sampler="hybrid"`` the WHOLE window's randomness is one
    fused uniform block ``[n_substeps, R]`` drawn before the scan (one
    threefry batch per window per agent — and one per colony once the
    caller vmaps), scanned over alongside the counts.
    """
    check_sampler(sampler)
    tau = timestep / n_substeps
    if sampler == "exact":
        keys = jax.random.split(key, n_substeps)

        def body(c, k):
            return tau_leap_step(k, c, stoich, propensity_fn, tau), None

        out, _ = jax.lax.scan(body, counts, keys)
        return out

    n_reactions = stoich.shape[0]
    u = uniform_block(key, (n_substeps, n_reactions))

    def body(c, u_t):
        lam = jnp.maximum(propensity_fn(c), 0.0) * tau
        return _fire(c, stoich, poisson_from_uniform(u_t, lam, threshold)), None

    out, _ = jax.lax.scan(body, counts, u)
    return out


def ssa_exact(
    rng: np.random.Generator,
    counts: np.ndarray,
    stoich: np.ndarray,
    propensity_fn: Callable[[np.ndarray], np.ndarray],
    t_end: float,
) -> np.ndarray:
    """Exact Gillespie direct method (host-side numpy oracle for tests)."""
    x = np.asarray(counts, dtype=np.float64).copy()
    t = 0.0
    while True:
        a = np.maximum(np.asarray(propensity_fn(x), dtype=np.float64), 0.0)
        a0 = a.sum()
        if a0 <= 0:
            return x
        t += rng.exponential(1.0 / a0)
        if t >= t_end:
            return x
        r = rng.choice(len(a), p=a / a0)
        x = np.maximum(x + stoich[r], 0.0)
