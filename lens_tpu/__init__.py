"""lens_tpu — a TPU-native agent-based cell-colony simulation framework.

A ground-up rebuild of the capabilities of CovertLab/Lens (multiscale,
agent-based cell simulation: per-cell composites of biochemical Process
modules coupled through a shared 2D diffusion lattice), re-architected for
TPU execution. The design (some layers land incrementally — see git log
for what exists at any given commit):

- the whole colony is ONE JAX/XLA SPMD program: homogeneous agent state is
  stacked into a single device pytree and ``vmap``-ed across the agent axis
  (where the reference runs one OS process per cell: ``lens/actor/inner.py``,
  reconstructed — see SURVEY.md header for mount caveat);
- inter-agent "messages" (the reference's Kafka exchange windows,
  ``lens/actor/outer.py``) are pure index/gather ops in HBM;
- the environment's diffusion lattice (``lens/environment/lattice.py``) is a
  Pallas stencil kernel co-resident with agent state;
- scaling across chips uses ``jax.sharding.Mesh`` + ``shard_map`` with XLA
  collectives over ICI/DCN instead of a message broker.

The load-bearing API kept from the reference is the Process plugin contract:
``next_update(timestep, states) -> update`` against named state stores, with
declarative updater/divider semantics, composed by topology wiring.
"""

__version__ = "0.1.0"

from lens_tpu.core.process import Deriver, Process
from lens_tpu.core.engine import Compartment

_LAZY = ("Experiment", "Colony", "Checkpointer")
__all__ = ["Process", "Deriver", "Compartment", "__version__", *_LAZY]


def __dir__():
    return sorted(set(globals()) | set(_LAZY))


def __getattr__(name):
    # Heavier layers load lazily so `import lens_tpu` stays light and the
    # core API has no import-order entanglement with jax-touching modules.
    if name == "Experiment":
        from lens_tpu.experiment import Experiment

        return Experiment
    if name == "Colony":
        from lens_tpu.colony.colony import Colony

        return Colony
    if name == "Checkpointer":
        from lens_tpu.checkpoint import Checkpointer

        return Checkpointer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
