from lens_tpu.core.process import Process
from lens_tpu.core.engine import Compartment
from lens_tpu.core.state import apply_update, UPDATERS, DIVIDERS

__all__ = ["Process", "Compartment", "apply_update", "UPDATERS", "DIVIDERS"]
