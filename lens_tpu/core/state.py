"""State stores: declarative update-merge and division semantics.

The reference's ``State`` objects accumulate per-process delta updates and
apply them between engine steps (reconstructed: ``State.apply_update`` in
``lens/actor/process.py``, SURVEY.md §2 — mount empty, see SURVEY header).
That merge semantics is the subtlest part of the contract surface
(SURVEY.md §7 "hard parts"), so the rebuild makes it fully declarative:

- every state variable carries an **updater** name (how a process delta is
  merged into the current value), and
- a **divider** name (how the value splits between two daughter cells).

Everything here is pure ``jnp`` on array leaves, so updaters run inside
``jit``/``vmap``/``scan`` with no Python branching on data.

Updaters
--------
``accumulate``              value + delta               (the reference default)
``nonnegative_accumulate``  max(value + delta, 0)
``set``                     delta (overwrite)
``null``                    value (ignore delta)

Dividers
--------
``split``     each daughter gets value / 2   (counts, mass, volume)
``copy``      each daughter gets value       (concentrations, parameters)
``zero``      daughters restart from 0       (clocks, accumulated flux)
``binomial``  stochastic integer split: daughter A ~ Binomial(n, 0.5)
``offset``    2D locations: daughters displaced +/- half a cell length
              along a uniformly random axis (division placement — the
              reference's lattice places daughters apart, reconstructed:
              SURVEY.md §2 "Spatial lattice" division placement)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Tuple

import jax
import jax.numpy as jnp

from lens_tpu.utils.dicts import Path, flatten_paths, set_path

Array = jax.Array

# ---------------------------------------------------------------------------
# Updaters: (current_value, delta) -> new_value
# ---------------------------------------------------------------------------


def _accumulate(value: Array, delta: Array) -> Array:
    return value + delta


def _nonnegative_accumulate(value: Array, delta: Array) -> Array:
    return jnp.maximum(value + delta, 0.0)


def _set(value: Array, delta: Array) -> Array:
    del value
    return delta


def _null(value: Array, delta: Array) -> Array:
    del delta
    return value


UPDATERS: Dict[str, Callable[[Array, Array], Array]] = {
    "accumulate": _accumulate,
    "nonnegative_accumulate": _nonnegative_accumulate,
    "set": _set,
    "null": _null,
}

# ---------------------------------------------------------------------------
# Dividers: (value, key) -> (daughter_a, daughter_b)
# ---------------------------------------------------------------------------


def _div_split(value: Array, key: Array) -> Tuple[Array, Array]:
    del key
    half = value / 2
    return half, half


def _div_copy(value: Array, key: Array) -> Tuple[Array, Array]:
    del key
    return value, value


def _div_zero(value: Array, key: Array) -> Tuple[Array, Array]:
    del key
    z = jnp.zeros_like(value)
    return z, z


# Exact CDF inversion handles counts up to this; above it the clipped
# normal approximation's bias is < 1e-3 of a count and undetectable.
_BINOMIAL_EXACT_MAX = 64


def _binomial_half(key: Array, n: Array) -> Array:
    """Draw Binomial(n, 1/2), exactly for n <= _BINOMIAL_EXACT_MAX.

    Hand-rolled instead of ``jax.random.binomial`` because that sampler's
    internal ``while_loop`` seeds its carry with replicated scalar
    constants while the body outputs shard-varying values, so it fails
    shard_map's varying-manual-axes check — division inside the sharded
    colony runners (parallel.runner / parallel.multispecies) would not
    trace. Here every loop carry derives from ``n``/``u`` (varying where
    the inputs are), which is VMA-safe, and the fixed-trip ``fori_loop``
    is also friendlier to XLA than rejection sampling.

    Exact branch: CDF inversion with the p=1/2 pmf recurrence
    pmf(k+1) = pmf(k) * (n-k)/(k+1); smallest k with CDF(k) >= u is an
    exact draw. Above the cutoff: round(n/2 + sqrt(n)/2 * z) clipped to
    [0, n].
    """
    n = jnp.asarray(n, jnp.float32)
    ku, kz = jax.random.split(key)
    u = jax.random.uniform(ku, jnp.shape(n))
    n_small = jnp.minimum(n, float(_BINOMIAL_EXACT_MAX))
    pmf0 = jnp.exp2(-n_small)

    def body(k, carry):
        cdf, pmf, res = carry
        kf = jnp.float32(k)
        cdf = cdf + pmf
        hit = (cdf >= u) & (res < 0.0)
        res = jnp.where(hit, kf, res)
        pmf = pmf * (n_small - kf) / (kf + 1.0)
        return cdf, pmf, res

    res0 = jnp.full_like(n, -1.0)
    exact = jax.lax.fori_loop(
        0, _BINOMIAL_EXACT_MAX + 1, body,
        (jnp.zeros_like(n), pmf0, res0),
    )[2]
    # float roundoff can leave CDF(n) a hair under u: land on n
    exact = jnp.where(exact < 0.0, n_small, exact)
    z = jax.random.normal(kz, jnp.shape(n))
    approx = jnp.clip(jnp.round(0.5 * n + 0.5 * jnp.sqrt(n) * z), 0.0, n)
    return jnp.where(n <= float(_BINOMIAL_EXACT_MAX), exact, approx)


def _div_binomial(value: Array, key: Array) -> Tuple[Array, Array]:
    # Integer-valued molecule counts partition binomially between daughters.
    # Exact Binomial(n, 0.5) draw — this divider exists for small-count
    # molecules (plasmids, transcription factors) where the clipped-normal
    # approximation is visibly biased below n ~ 20.
    n = jnp.maximum(jnp.asarray(value, jnp.float32), 0.0)
    a = _binomial_half(key, n)
    return a.astype(value.dtype), (n - a).astype(value.dtype)


# Separation between daughter centers after division is one cell length
# (each daughter displaced half of it): a 2 um E. coli divides into two
# 1 um-spaced daughters. Shared by the jitted `offset` divider and the
# host bridge's division placement so both paths agree.
DIVISION_SEPARATION_UM = 1.0


def _div_offset(value: Array, key: Array) -> Tuple[Array, Array]:
    # Division placement for a [2] location leaf: daughters move apart
    # along a uniformly random axis. (The reference divides along the
    # cell's long axis; headings are not part of this leaf, so a random
    # axis is the isotropic equivalent.) The spatial wrapper clips
    # locations to the lattice domain after division.
    theta = jax.random.uniform(key, (), minval=0.0, maxval=2.0 * jnp.pi)
    half = (DIVISION_SEPARATION_UM / 2.0) * jnp.stack(
        [jnp.cos(theta), jnp.sin(theta)]
    ).astype(value.dtype)
    return value + half, value - half


_div_offset.stochastic = True


# Randomness policy lives WITH the divider definition: the colony layer
# only generates per-row key material for dividers marked stochastic
# (threefry batches are among the most expensive per-step TPU ops), so a
# new randomness-consuming divider must carry this attribute or it will
# receive dummy keys.
_div_binomial.stochastic = True


DIVIDERS: Dict[str, Callable[[Array, Array], Tuple[Array, Array]]] = {
    "split": _div_split,
    "copy": _div_copy,
    "zero": _div_zero,
    "binomial": _div_binomial,
    "offset": _div_offset,
}

# ---------------------------------------------------------------------------
# Schema-driven application
# ---------------------------------------------------------------------------


def apply_update(
    state: dict,
    update: Mapping,
    updaters: Mapping[Path, str] | None = None,
) -> dict:
    """Merge one update tree into a state tree.

    ``update`` mirrors a sub-structure of ``state``; each leaf is merged via
    the updater registered for its path (default ``accumulate``, matching
    the reference's delta-update convention).
    """
    updaters = updaters or {}

    def merge(path: Path, node: Any, upd: Any) -> Any:
        if isinstance(upd, Mapping):
            if not isinstance(node, Mapping):
                raise TypeError(
                    f"update has a dict at {path} but state has a leaf there"
                )
            out = dict(node)
            for key, sub in upd.items():
                if key not in node:
                    raise KeyError(f"update path {path + (key,)} not in state")
                out[key] = merge(path + (key,), node[key], sub)
            return out
        if isinstance(node, Mapping):
            raise TypeError(
                f"update has a leaf at {path} but state has a dict there"
            )
        fn = UPDATERS[updaters.get(path, "accumulate")]
        return fn(node, upd)

    return merge((), state, update)


def divide_state(
    state: dict,
    key: Array,
    dividers: Mapping[Path, str] | None = None,
) -> Tuple[dict, dict]:
    """Split one agent's state tree into two daughter trees.

    The reference serializes daughter state dicts through the division
    handshake (reconstructed: ``Inner.divide``, SURVEY.md §3.3); here the
    split is a pure function usable inside ``jit`` — the colony layer turns
    it into "write two rows of the stacked state".
    """
    dividers = dividers or {}
    leaves = list(flatten_paths(state))
    keys = jax.random.split(key, max(len(leaves), 1))
    out_a: dict = state
    out_b: dict = state
    for (path, value), k in zip(leaves, keys):
        fn = DIVIDERS[dividers.get(path, "split")]
        a, b = fn(value, k)
        out_a = set_path(out_a, path, a)
        out_b = set_path(out_b, path, b)
    return out_a, out_b
