"""State stores: declarative update-merge and division semantics.

The reference's ``State`` objects accumulate per-process delta updates and
apply them between engine steps (reconstructed: ``State.apply_update`` in
``lens/actor/process.py``, SURVEY.md §2 — mount empty, see SURVEY header).
That merge semantics is the subtlest part of the contract surface
(SURVEY.md §7 "hard parts"), so the rebuild makes it fully declarative:

- every state variable carries an **updater** name (how a process delta is
  merged into the current value), and
- a **divider** name (how the value splits between two daughter cells).

Everything here is pure ``jnp`` on array leaves, so updaters run inside
``jit``/``vmap``/``scan`` with no Python branching on data.

Updaters
--------
``accumulate``              value + delta               (the reference default)
``nonnegative_accumulate``  max(value + delta, 0)
``set``                     delta (overwrite)
``null``                    value (ignore delta)

Dividers
--------
``split``     each daughter gets value / 2   (counts, mass, volume)
``copy``      each daughter gets value       (concentrations, parameters)
``zero``      daughters restart from 0       (clocks, accumulated flux)
``binomial``  stochastic integer split: daughter A ~ Binomial(n, 0.5)
``offset``    2D locations: daughters displaced +/- half a cell length
              along a uniformly random axis (division placement — the
              reference's lattice places daughters apart, reconstructed:
              SURVEY.md §2 "Spatial lattice" division placement)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Tuple

import jax
import jax.numpy as jnp

from lens_tpu.utils.dicts import Path, flatten_paths, set_path

Array = jax.Array

# ---------------------------------------------------------------------------
# Updaters: (current_value, delta) -> new_value
# ---------------------------------------------------------------------------


def _accumulate(value: Array, delta: Array) -> Array:
    return value + delta


def _nonnegative_accumulate(value: Array, delta: Array) -> Array:
    return jnp.maximum(value + delta, 0.0)


def _set(value: Array, delta: Array) -> Array:
    del value
    return delta


def _null(value: Array, delta: Array) -> Array:
    del delta
    return value


UPDATERS: Dict[str, Callable[[Array, Array], Array]] = {
    "accumulate": _accumulate,
    "nonnegative_accumulate": _nonnegative_accumulate,
    "set": _set,
    "null": _null,
}

# ---------------------------------------------------------------------------
# Dividers: (value, key) -> (daughter_a, daughter_b)
# ---------------------------------------------------------------------------


def _div_split(value: Array, key: Array) -> Tuple[Array, Array]:
    del key
    half = value / 2
    return half, half


def _div_copy(value: Array, key: Array) -> Tuple[Array, Array]:
    del key
    return value, value


def _div_zero(value: Array, key: Array) -> Tuple[Array, Array]:
    del key
    z = jnp.zeros_like(value)
    return z, z


def _div_binomial(value: Array, key: Array) -> Tuple[Array, Array]:
    # Integer-valued molecule counts partition binomially between daughters.
    # Exact Binomial(n, 0.5) draw — this divider exists for small-count
    # molecules (plasmids, transcription factors) where the clipped-normal
    # approximation is visibly biased below n ~ 20.
    n = jnp.maximum(jnp.asarray(value, jnp.float32), 0.0)
    a = jax.random.binomial(key, n, 0.5, shape=jnp.shape(value))
    return a.astype(value.dtype), (n - a).astype(value.dtype)


# Separation between daughter centers after division is one cell length
# (each daughter displaced half of it): a 2 um E. coli divides into two
# 1 um-spaced daughters. Shared by the jitted `offset` divider and the
# host bridge's division placement so both paths agree.
DIVISION_SEPARATION_UM = 1.0


def _div_offset(value: Array, key: Array) -> Tuple[Array, Array]:
    # Division placement for a [2] location leaf: daughters move apart
    # along a uniformly random axis. (The reference divides along the
    # cell's long axis; headings are not part of this leaf, so a random
    # axis is the isotropic equivalent.) The spatial wrapper clips
    # locations to the lattice domain after division.
    theta = jax.random.uniform(key, (), minval=0.0, maxval=2.0 * jnp.pi)
    half = (DIVISION_SEPARATION_UM / 2.0) * jnp.stack(
        [jnp.cos(theta), jnp.sin(theta)]
    ).astype(value.dtype)
    return value + half, value - half


_div_offset.stochastic = True


# Randomness policy lives WITH the divider definition: the colony layer
# only generates per-row key material for dividers marked stochastic
# (threefry batches are among the most expensive per-step TPU ops), so a
# new randomness-consuming divider must carry this attribute or it will
# receive dummy keys.
_div_binomial.stochastic = True


DIVIDERS: Dict[str, Callable[[Array, Array], Tuple[Array, Array]]] = {
    "split": _div_split,
    "copy": _div_copy,
    "zero": _div_zero,
    "binomial": _div_binomial,
    "offset": _div_offset,
}

# ---------------------------------------------------------------------------
# Schema-driven application
# ---------------------------------------------------------------------------


def apply_update(
    state: dict,
    update: Mapping,
    updaters: Mapping[Path, str] | None = None,
) -> dict:
    """Merge one update tree into a state tree.

    ``update`` mirrors a sub-structure of ``state``; each leaf is merged via
    the updater registered for its path (default ``accumulate``, matching
    the reference's delta-update convention).
    """
    updaters = updaters or {}

    def merge(path: Path, node: Any, upd: Any) -> Any:
        if isinstance(upd, Mapping):
            if not isinstance(node, Mapping):
                raise TypeError(
                    f"update has a dict at {path} but state has a leaf there"
                )
            out = dict(node)
            for key, sub in upd.items():
                if key not in node:
                    raise KeyError(f"update path {path + (key,)} not in state")
                out[key] = merge(path + (key,), node[key], sub)
            return out
        if isinstance(node, Mapping):
            raise TypeError(
                f"update has a leaf at {path} but state has a dict there"
            )
        fn = UPDATERS[updaters.get(path, "accumulate")]
        return fn(node, upd)

    return merge((), state, update)


def divide_state(
    state: dict,
    key: Array,
    dividers: Mapping[Path, str] | None = None,
) -> Tuple[dict, dict]:
    """Split one agent's state tree into two daughter trees.

    The reference serializes daughter state dicts through the division
    handshake (reconstructed: ``Inner.divide``, SURVEY.md §3.3); here the
    split is a pure function usable inside ``jit`` — the colony layer turns
    it into "write two rows of the stacked state".
    """
    dividers = dividers or {}
    leaves = list(flatten_paths(state))
    keys = jax.random.split(key, max(len(leaves), 1))
    out_a: dict = state
    out_b: dict = state
    for (path, value), k in zip(leaves, keys):
        fn = DIVIDERS[dividers.get(path, "split")]
        a, b = fn(value, k)
        out_a = set_path(out_a, path, a)
        out_b = set_path(out_b, path, b)
    return out_a, out_b
