"""The Process plugin contract — the load-bearing abstraction of the framework.

The reference defines a ``Process`` base class whose subclasses declare named
ports ("roles") and implement ``next_update(timestep, states) -> update``
returning a delta-update dict (reconstructed: ``lens/actor/process.py``,
corroborated by BASELINE.json's north star; SURVEY.md §1 L2/L2.5). The
rebuild keeps this contract exactly, with two TPU-first strengthenings:

1. ``next_update`` MUST be a pure, traceable function of ``(timestep,
   states)`` — no Python side effects, no data-dependent Python control
   flow. This is what lets the engine ``jit`` a whole exchange window and
   ``vmap`` it across 100k agents.
2. The port schema is declarative: every variable declares its default
   value, updater (merge rule) and divider (division rule), so the engine
   can build the stacked state tree and merge machinery without running
   any process code.

Schema leaf descriptors are dicts with keys:
``_default`` (scalar/array), ``_updater`` (see core.state.UPDATERS),
``_divider`` (see core.state.DIVIDERS), ``_emit`` (bool — include in
emitter output).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax.numpy as jnp

from lens_tpu.utils.dicts import deep_merge

SchemaLeaf = Dict[str, Any]
PortsSchema = Dict[str, Dict[str, SchemaLeaf]]

LEAF_KEYS = frozenset({"_default", "_updater", "_divider", "_emit"})


def is_schema_leaf(node: Any) -> bool:
    return isinstance(node, Mapping) and "_default" in node


class Process:
    """Base class for all biochemical/mechanistic process modules.

    Subclasses override:

    - ``defaults``: class-level dict of parameters.
    - ``ports_schema()``: declare ports -> variables -> schema leaves.
    - ``next_update(timestep, states)``: pure function from the port-view of
      the state to an update dict with the same port/variable structure.

    Parameters are resolved at construction (``defaults`` <- ``config``) and
    must be treated as static: arrays/floats baked into the traced
    computation.
    """

    defaults: Dict[str, Any] = {}
    name: str = "process"
    #: Stochastic processes receive a ``key=`` kwarg in ``next_update``
    #: (a fresh per-agent, per-step PRNG key supplied by the engine).
    #: Randomness must be fixed-shape (Poisson/normal draws, not
    #: variable-length event lists) to stay jit/vmap-compatible.
    stochastic: bool = False

    def __init__(self, config: Mapping | None = None):
        self.config = deep_merge(self.defaults, config)

    # -- declarative surface -------------------------------------------------

    def ports_schema(self) -> PortsSchema:
        raise NotImplementedError

    # -- dynamics ------------------------------------------------------------

    def next_update(
        self, timestep, states: Mapping, key=None
    ) -> Dict[str, Dict[str, Any]]:
        """Compute this process's contribution for one timestep.

        ``states`` maps port name -> {variable: value} (a read-only view the
        engine assembled through the topology). The return value mirrors
        that structure; each leaf is merged by the variable's declared
        updater. Must be pure and jnp-traceable. ``key`` is only passed
        when ``stochastic = True``.
        """
        raise NotImplementedError

    # -- convenience ---------------------------------------------------------

    def initial_state(self) -> Dict[str, Dict[str, Any]]:
        """Port-structured defaults (as jnp arrays) from the schema."""
        out: Dict[str, Dict[str, Any]] = {}
        for port, variables in self.ports_schema().items():
            out[port] = {
                var: jnp.asarray(leaf["_default"]) for var, leaf in variables.items()
            }
        return out

    def __repr__(self) -> str:
        return f"{type(self).__name__}(name={self.name!r})"


class Deriver(Process):
    """A Process that computes derived/bookkeeping state (not mechanistic).

    The reference runs derivers after each engine step to keep quantities
    like volume-from-mass and concentrations-from-counts consistent
    (reconstructed: ``lens/processes/derive_*.py``, SURVEY.md §2). Derivers
    use ``_updater: set`` leaves and run after all mechanistic updates are
    merged, in registration order.
    """

    name = "deriver"
