"""The compartment engine: compose Processes into one pure, jittable step.

The reference's inner loop (reconstructed: ``Compartment.update`` in
``lens/actor/process.py``; hot path in SURVEY.md §3.2) is::

    for process in processes: update = process.next_update(dt, states)
    for store: state.apply_update(...)

The rebuild keeps those semantics exactly — every mechanistic process sees
the state as of the start of the step; updates merge afterwards via each
variable's declared updater; derivers then run in order against the merged
state — but packages the whole thing as a **pure function**
``step(state, dt) -> state`` that is jittable, vmappable across an agent
axis, and scannable over inner timesteps. That single design move replaces
the reference's per-cell OS processes and Kafka exchange windows with one
SPMD program (BASELINE.json north star).
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.core.process import Deriver, Process, is_schema_leaf
from lens_tpu.core.schedule import scan_schedule
from lens_tpu.core.state import apply_update, divide_state
from lens_tpu.core.topology import Path, TopologySpec, normalize_topology
from lens_tpu.utils.dicts import deep_merge, flatten_paths, get_path, set_path


def _strong(x) -> jnp.ndarray:
    """To a jnp array with a STRONG (non-weak) dtype.

    Python scalars become weak-typed jax arrays; a state built from them
    changes aval signature after one scan (outputs are strong), forcing a
    full recompile on the second call of any jitted step/run — measured at
    0.3-4 s per composite, and the round-1 config-3 "throughput" number
    was in fact this recompile. Routing defaults/overrides through numpy
    (whose dtypes are never weak) makes initial states aval-identical to
    evolved states; jnp.asarray canonicalizes the width itself (64->32
    under default config, preserved under x64 mode).
    """
    return jnp.asarray(np.asarray(x))


class Compartment:
    """A wired set of Processes sharing a state tree.

    Parameters
    ----------
    processes:
        Ordered mapping name -> Process instance. Instances of ``Deriver``
        run after the mechanistic merge, in order.
    topology:
        Mapping process name -> {port: store path}. See ``core.topology``.

    The constructor builds, from the declared schemas alone:

    - ``initial_state()``: the nested-dict pytree of jnp defaults,
    - ``updaters`` / ``dividers`` / ``emit_paths``: per-path merge,
      division, and emission metadata.

    ``step`` and ``run`` are pure functions of the state pytree.
    """

    def __init__(self, processes: Mapping[str, Process], topology: TopologySpec):
        self.processes: Dict[str, Process] = dict(processes)
        self.topology = normalize_topology(topology)
        missing = set(self.processes) - set(self.topology)
        if missing:
            raise ValueError(f"processes missing from topology: {sorted(missing)}")

        self.mechanistic = {
            name: p for name, p in self.processes.items() if not isinstance(p, Deriver)
        }
        self.derivers = {
            name: p for name, p in self.processes.items() if isinstance(p, Deriver)
        }

        self.updaters: Dict[Path, str] = {}
        self.dividers: Dict[Path, str] = {}
        self.emit_paths: List[Path] = []
        self._defaults: dict = {}
        self._build_schema()

    # -- schema assembly -----------------------------------------------------

    def _resolve(self, name: str, port: str) -> Path:
        ports = self.topology[name]
        if port not in ports:
            raise ValueError(f"process {name!r} port {port!r} missing from topology")
        return ports[port]

    def _build_schema(self) -> None:
        for name, process in self.processes.items():
            for port, variables in process.ports_schema().items():
                base = self._resolve(name, port)
                for var, leaf in variables.items():
                    if not is_schema_leaf(leaf):
                        raise ValueError(
                            f"{name}.{port}.{var}: schema leaf needs '_default'"
                        )
                    path = base + (var,)
                    default = _strong(leaf["_default"])
                    if path in self.updaters:
                        # Shared variable: declarations must agree — silent
                        # first-wins hides wiring bugs.
                        prev_default = get_path(self._defaults, path)
                        conflicts = []
                        if leaf.get("_updater", self.updaters[path]) != self.updaters[path]:
                            conflicts.append("_updater")
                        if leaf.get("_divider", self.dividers[path]) != self.dividers[path]:
                            conflicts.append("_divider")
                        if leaf.get("_emit", path in self.emit_paths) != (
                            path in self.emit_paths
                        ):
                            conflicts.append("_emit")
                        if not np.array_equal(
                            np.asarray(prev_default), np.asarray(default)
                        ):
                            conflicts.append("_default")
                        if conflicts:
                            raise ValueError(
                                f"{name}.{port}.{var}: conflicting declarations "
                                f"for shared path {path}: {conflicts}"
                            )
                        continue
                    self.updaters[path] = leaf.get("_updater", "accumulate")
                    self.dividers[path] = leaf.get("_divider", "split")
                    if leaf.get("_emit", True):
                        self.emit_paths.append(path)
                    self._defaults = set_path(self._defaults, path, default)

    def initial_state(self, overrides: Mapping | None = None) -> dict:
        state = jax.tree.map(lambda x: x, self._defaults)  # deep copy of dicts
        if overrides:
            known = set(self.updaters)
            for path, _ in flatten_paths(overrides):
                if path not in known:
                    raise KeyError(
                        f"initial_state override {path} does not match any "
                        f"schema variable (typo?)"
                    )
            state = deep_merge(state, overrides)
        return jax.tree.map(_strong, state)

    # -- views ---------------------------------------------------------------

    def _port_view(self, state: dict, name: str) -> Dict[str, Dict[str, Any]]:
        view: Dict[str, Dict[str, Any]] = {}
        for port, variables in self.processes[name].ports_schema().items():
            base = self._resolve(name, port)
            store = get_path(state, base)
            view[port] = {var: store[var] for var in variables}
        return view

    def _absolute_update(self, name: str, update: Mapping) -> dict:
        """Re-root a port-structured update at its topology paths."""
        tree: dict = {}
        for port, variables in update.items():
            base = self._resolve(name, port)
            for var, delta in variables.items():
                tree = set_path(tree, base + (var,), delta)
        return tree

    # -- stepping ------------------------------------------------------------

    @property
    def has_stochastic(self) -> bool:
        return any(p.stochastic for p in self.processes.values())

    def step(self, state: dict, timestep, key: Optional[jax.Array] = None) -> dict:
        """One engine step: all mechanistic updates off the pre-step state,
        merged in declaration order; then derivers in order.

        ``key`` is required iff any process is stochastic; the engine
        derives an independent subkey per stochastic process.
        """
        if self.has_stochastic and key is None:
            raise ValueError(
                "this compartment has stochastic processes; step() needs a key"
            )
        order = list(self.processes)

        def run_process(view_state: dict, name: str) -> dict:
            process = self.processes[name]
            view = self._port_view(view_state, name)
            if process.stochastic:
                update = process.next_update(
                    timestep, view, key=jax.random.fold_in(key, order.index(name))
                )
            else:
                update = process.next_update(timestep, view)
            return self._absolute_update(name, update)

        updates = [run_process(state, n) for n in self.mechanistic]
        for update in updates:
            state = apply_update(state, update, self.updaters)
        for name in self.derivers:
            # derivers see the merged state (view rebuilt against it)
            state = apply_update(state, run_process(state, name), self.updaters)
        return state

    def run(
        self,
        state: dict,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        key: Optional[jax.Array] = None,
    ) -> Tuple[dict, dict]:
        """Advance ``total_time`` in increments of ``timestep`` via ``lax.scan``.

        Returns ``(final_state, trajectory)`` where ``trajectory`` stacks the
        emitted state every ``emit_every`` steps along a leading time axis.
        The scan is the jit/compile unit — one trace regardless of step
        count (SURVEY.md §7 step 2: "jit the whole exchange window").
        """
        if self.has_stochastic and key is None:
            raise ValueError(
                "this compartment has stochastic processes; run() needs a key"
            )
        if key is None:
            key = jax.random.PRNGKey(0)  # unused, but keeps the carry uniform

        def step_fn(carry):
            s, k = carry
            k, sub = jax.random.split(k)
            return (self.step(s, timestep, sub), k)

        (state, _), trajectory = scan_schedule(
            step_fn, lambda c: self.emit(c[0]), (state, key),
            total_time, timestep, emit_every,
        )
        return state, trajectory

    # -- emission / division -------------------------------------------------

    def emit(self, state: dict) -> dict:
        """The emittable slice of the state tree (paths with ``_emit``)."""
        out: dict = {}
        for path in self.emit_paths:
            out = set_path(out, path, get_path(state, path))
        return out

    def divide(self, state: dict, key: jax.Array) -> Tuple[dict, dict]:
        """Split a single agent's state into two daughters per the declared
        dividers (the rebuild's analogue of the reference's division
        handshake, SURVEY.md §3.3)."""
        return divide_state(state, key, self.dividers)
