"""Shared run-loop scaffolding: validated scan over timesteps with emits.

Compartment.run, Colony.run and SpatialColony.run all advance a carry by
``total_time`` in ``timestep`` increments and emit a slice every
``emit_every`` steps. The validation (duration divisibility — silently
simulating a different duration is the failure mode) and the nested-scan
shape live here once.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax


def n_steps_for(total_time: float, timestep: float) -> int:
    """Step count, insisting total_time is an integer multiple of timestep."""
    n_steps = int(round(total_time / timestep))
    if abs(n_steps * timestep - total_time) > 1e-6 * max(abs(total_time), 1.0):
        raise ValueError(
            f"total_time={total_time} is not an integer multiple of "
            f"timestep={timestep} (would silently simulate {n_steps * timestep})"
        )
    return n_steps


def scan_schedule(
    step_fn: Callable[[Any], Any],
    emit_fn: Callable[[Any], Any],
    carry: Any,
    total_time: float,
    timestep: float,
    emit_every: int = 1,
) -> Tuple[Any, Any]:
    """``lax.scan`` ``step_fn`` for total_time/timestep steps, collecting
    ``emit_fn(carry)`` every ``emit_every`` steps (stacked on a leading
    time axis). One trace regardless of step count."""
    n_steps = n_steps_for(total_time, timestep)
    if emit_every < 1 or n_steps % emit_every != 0:
        raise ValueError(
            f"total steps ({n_steps}) must be a positive multiple of "
            f"emit_every ({emit_every})"
        )

    def body(c, _):
        def inner(c, _):
            return step_fn(c), None

        c, _ = jax.lax.scan(inner, c, None, length=emit_every)
        return c, emit_fn(c)

    return jax.lax.scan(body, carry, None, length=n_steps // emit_every)
