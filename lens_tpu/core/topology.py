"""Topology wiring: mapping process ports onto shared state stores.

The reference wires each process's ports ("roles") to named state stores via
a topology dict on the compartment (reconstructed: ``Compartment`` in
``lens/actor/process.py``, SURVEY.md §1 L2.5). The rebuild keeps the same
dict-of-dicts surface::

    topology = {
        "transport": {"internal": ("cell",), "external": ("boundary", "external")},
        "growth":    {"global": ("global",)},
    }

Paths are tuples of store names (a bare string is promoted to a 1-tuple).
The engine resolves ``port + variable`` to an absolute path in the state
pytree; variables from different processes wired to the same path share
state — that IS the inter-process communication mechanism.
"""

from __future__ import annotations

from typing import Dict, Mapping, Sequence, Tuple, Union

Path = Tuple[str, ...]
TopologySpec = Mapping[str, Mapping[str, Union[str, Sequence[str]]]]


def normalize_path(path: Union[str, Sequence[str]]) -> Path:
    if isinstance(path, str):
        return (path,)
    return tuple(path)


def normalize_topology(topology: TopologySpec) -> Dict[str, Dict[str, Path]]:
    """Canonicalize a topology spec to {process: {port: path tuple}}."""
    return {
        process: {port: normalize_path(path) for port, path in ports.items()}
        for process, ports in topology.items()
    }
