"""Composite factories: named cell types as (processes, topology) bundles.

Mirrors the reference's composite layer, where boot functions assemble a
compartment from processes + topology for a named agent type
(reconstructed: ``lens/environment/boot.py`` agent-type constructors,
SURVEY.md §1 L5, §2 "Composites"). Factories take a plain config dict
(deep-merged over defaults, same semantics as process configs) and return
wired objects, so the experiment layer can treat model choice as data.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Tuple

from lens_tpu.colony.colony import Colony
from lens_tpu.core.engine import Compartment
from lens_tpu.environment.lattice import Lattice
from lens_tpu.environment.spatial import SpatialColony
from lens_tpu.processes import (
    BrownianMotility,
    Degradation,
    DeriveVolume,
    DeathTrigger,
    DivideTrigger,
    FBAMetabolism,
    FlagellarMotor,
    GlucosePTS,
    Growth,
    Lysis,
    Metabolism,
    MichaelisMentenTransport,
    MWCChemoreceptor,
    RunTumbleMotility,
    StochasticExpression,
    ToggleSwitch,
    Transcription,
    Translation,
)
from lens_tpu.utils.dicts import deep_merge

composite_registry: Dict[str, Callable[..., Any]] = {}


def register_composite(fn: Callable[..., Any]) -> Callable[..., Any]:
    composite_registry[fn.__name__] = fn
    return fn


def _cfg(defaults: dict, config: Mapping | None) -> dict:
    return deep_merge(defaults, config)


def _thread_sampler(c: dict, *process_cfgs: dict) -> None:
    """Composite-level ``sampler`` knob -> the named process configs
    (``setdefault``: an explicit per-process sampler wins). ``None``
    leaves process defaults alone — the expression processes default to
    "hybrid" themselves; the knob exists so one experiment-config key
    can pin the WHOLE composite to "exact" (oracle runs, resuming
    pre-fast-path checkpoints) without spelunking nested configs."""
    sampler = c.get("sampler")
    if sampler is None:
        return
    for cfg in process_cfgs:
        if cfg is not None:
            cfg.setdefault("sampler", sampler)


def _death_trigger_of(compartment: Compartment):
    """The compartment's death flag, if it has one.

    Resolved from the topology of its ``DeathTrigger`` process(es) — the
    trigger's logical ``global`` port may be wired onto another store
    (e.g. ``("cell",)`` to watch a nutrient pool), so the flag's path
    follows the wiring, never name-matching arbitrary schema variables
    (a gene that happens to be named ``die`` must NOT become a kill
    switch). Custom death processes fall back to the conventional
    ``("global", "die")`` path when they declare it.
    """
    hits = set()
    for name, proc in compartment.processes.items():
        if isinstance(proc, DeathTrigger):
            store = compartment.topology[name]["global"]
            hits.add(tuple(store) + ("die",))
    if not hits and ("global", "die") in compartment.updaters:
        hits.add(("global", "die"))
    if len(hits) > 1:
        raise ValueError(
            f"compartment wires multiple death flags {sorted(hits)}; a "
            f"colony watches exactly one death trigger"
        )
    return hits.pop() if hits else None


def _default_boot_yolk(transport_cfg: Dict, death_over: Mapping) -> Dict:
    """Starvation death must not fire at t=0: a 'below'-threshold death
    on a pool that boots empty would kill every initial cell before its
    first meal. Unless overridden, boot cells with a yolk (5x the
    threshold) — set on BOTH the transport's ``internal_default`` and
    the trigger's ``variable_default`` so the shared declaration stays
    consistent. Returns the adjusted death config."""
    death = dict(death_over)
    if death.get("when", "below") == "below":
        thr = float(death.get("threshold", 0.01))
        yolk = float(transport_cfg.get("internal_default", 5.0 * thr))
        transport_cfg["internal_default"] = yolk
        death.setdefault("variable_default", yolk)
    return death


def _add_cell_store_death(
    processes: Dict, topology: Dict, variable: str, death_over: Mapping
) -> None:
    """Wire an optional starvation DeathTrigger watching a CELL-store
    variable (the trigger's logical ``global`` port maps onto
    ``("cell",)``, so the die flag lands at ``("cell", "die")`` and
    ``_death_trigger_of`` resolves it from this wiring). Mutates
    ``processes``/``topology`` in place. Rejects a watched variable no
    existing process writes — the trigger would watch its own frozen
    default and silently never fire."""
    death_cfg = _cfg(
        {"variable": variable, "threshold": 0.01, "when": "below",
         "variable_default": 0.0, "lysis": None},
        death_over,
    )
    lysis = death_cfg.pop("lysis")
    probe = Compartment(processes=dict(processes), topology=dict(topology))
    watched = ("cell", str(death_cfg["variable"]))
    if watched not in probe.updaters:
        raise ValueError(
            f"death watches {watched}, which no process writes — pick a "
            f"cell-store variable (e.g. {variable!r})"
        )
    processes["death_trigger"] = DeathTrigger(death_cfg)
    topology["death_trigger"] = {"global": ("cell",)}
    if lysis is not None:
        # {"lysis": fraction}: the dying cell's pool returns to its
        # lattice bin through the ordinary exchange path (field credit
        # BEFORE the alive bit clears). Inserted after death_trigger —
        # derivers run in insertion order, so the flag read is this
        # step's verdict.
        mol = str(death_cfg["variable"])
        if mol.endswith("_internal"):
            mol = mol[: -len("_internal")]
        # mirror the watched-variable guard: the release must land in an
        # exchange some transport already owns (and the lattice scatters),
        # else the pool drains into a dead-end variable and the mass the
        # config asked to conserve silently vanishes
        release_to = ("boundary", "exchange", f"{mol}_exchange")
        if release_to not in probe.updaters:
            raise ValueError(
                f"lysis would release to {release_to}, which no transport "
                f"writes — death['variable'] must be a '<molecule>_internal' "
                f"pool whose molecule is lattice-wired"
            )
        processes["lysis"] = Lysis(
            {
                "pool": str(death_cfg["variable"]),
                "exchange": f"{mol}_exchange",
                "fraction": float(lysis),
            }
        )
        topology["lysis"] = {
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        }


def _make_lattice(c: Mapping, molecules, diffusion, initial) -> Lattice:
    """The standard lattice from a composite config: ``size`` defaults to
    10 um bins; ``impl`` selects the diffusion scheme ("auto" =
    pallas/xla by backend, "xla", "pallas", "adi" — reaches the CLI as
    e.g. ``--config '{"impl": "adi"}'``)."""
    shape = tuple(c["shape"])
    size = c["size"] or (10.0 * shape[0], 10.0 * shape[1])
    return Lattice(
        molecules=molecules,
        shape=shape,
        size=size,
        diffusion=diffusion,
        initial=initial,
        timestep=c["timestep"],
        impl=c.get("impl", "auto"),
    )


def _coupling_of(c: Mapping) -> str:
    """Composite-level ``coupling`` knob (experiment.py threads its
    top-level key here, like ``sampler``): "fused" (default) or
    "reference" — the oracle path for A/B and numerics checks."""
    return str(c.get("coupling") or "fused")


def _spatial_colony(
    compartment: Compartment,
    molecules: list,
    c: Mapping,
    diffusion,
    initial,
) -> Tuple[SpatialColony, Compartment]:
    """Shared assembly tail for lattice composites: Colony + Lattice +
    SpatialColony with the standard boundary port wiring (one
    ``boundary.external.<mol>`` / ``boundary.exchange.<mol>_exchange``
    pair per field molecule, location at ``boundary.location``)."""
    colony = Colony(
        compartment,
        capacity=int(c["capacity"]),
        division_trigger=("global", "divide") if c["division"] else None,
        death_trigger=_death_trigger_of(compartment),
    )
    lattice = _make_lattice(c, molecules, diffusion, initial)
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            mol: (
                ("boundary", "external", mol),
                ("boundary", "exchange", f"{mol}_exchange"),
            )
            for mol in molecules
        },
        location_path=("boundary", "location"),
        coupling=_coupling_of(c),
    )
    return spatial, compartment


@register_composite
def minimal_ode(config: Mapping | None = None) -> Compartment:
    """Config 0: single-agent glucose-uptake ODE cell (CPU-reference model)."""
    c = _cfg({"glucose_pts": {}}, config)
    return Compartment(
        processes={"glucose_pts": GlucosePTS(c["glucose_pts"])},
        topology={
            "glucose_pts": {
                "internal": ("cell",),
                "external": ("environment",),
                "exchange": ("boundary", "exchange"),
            },
        },
    )


@register_composite
def toggle_colony(config: Mapping | None = None) -> Compartment:
    """Config 1: 4-species toggle-switch expression cell (no lattice)."""
    c = _cfg(
        {"toggle_switch": {}, "growth": {}, "divide": {}, "sampler": None},
        config,
    )
    _thread_sampler(c, c["toggle_switch"])
    return Compartment(
        processes={
            "toggle_switch": ToggleSwitch(c["toggle_switch"]),
            "growth": Growth(c["growth"]),
            "divide_trigger": DivideTrigger(c["divide"]),
        },
        topology={
            "toggle_switch": {"internal": ("cell",)},
            "growth": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
        },
    )


@register_composite
def grow_divide(config: Mapping | None = None) -> Compartment:
    """Minimal growth+division cell (the lifecycle-machinery exerciser).

    Optional ``death`` config adds a DeathTrigger (default: starvation —
    die when volume shrinks below its threshold), closing the full
    birth/growth/death loop: freed rows recycle into the division pool.
    """
    c = _cfg({"growth": {}, "divide": {}, "death": None}, config)
    processes = {
        "growth": Growth(c["growth"]),
        "divide_trigger": DivideTrigger(c["divide"]),
    }
    topology = {
        "growth": {"global": ("global",)},
        "divide_trigger": {"global": ("global",)},
    }
    if c["death"] is not None:
        processes["death_trigger"] = DeathTrigger(c["death"])
        topology["death_trigger"] = {"global": ("global",)}
    return Compartment(processes=processes, topology=topology)


@register_composite
def hybrid_cell(config: Mapping | None = None) -> Compartment:
    """Config 4 cell: hybrid tau-leap Gillespie + ODE per agent.

    Stochastic gene expression (discrete counts, tau-leaping) runs beside
    deterministic glucose-uptake ODE kinetics and growth/division in the
    same compartment — the engine's per-step merge is what couples the
    two integrators (the reference runs mixed ODE/stochastic process sets
    the same way, reconstructed: SURVEY.md §2 process inventory).

    Mixed-species colonies: override the ``rates`` store per-agent at
    ``Colony.initial_state`` (see StochasticExpression docstring).
    """
    c = _cfg(
        {"expression": {}, "glucose_pts": {}, "growth": {}, "divide": {},
         "sampler": None},
        config,
    )
    _thread_sampler(c, c["expression"])
    return Compartment(
        processes={
            "expression": StochasticExpression(c["expression"]),
            "glucose_pts": GlucosePTS(c["glucose_pts"]),
            "growth": Growth(c["growth"]),
            "divide_trigger": DivideTrigger(c["divide"]),
        },
        topology={
            "expression": {"counts": ("counts",), "rates": ("rates",)},
            "glucose_pts": {
                "internal": ("cell",),
                "external": ("environment",),
                "exchange": ("boundary", "exchange"),
            },
            "growth": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
        },
    )


@register_composite
def minimal_wcecoli(config: Mapping | None = None) -> Compartment:
    """Config 3: the wcEcoli-minimal composite — metabolism + expression +
    division.

    Regulated kinetic metabolism (Covert–Palsson core network) grows mass;
    constitutive transcription/translation/degradation maintain an
    expression machinery proxy; DeriveVolume keeps geometry consistent and
    the cell divides on volume doubling. This is the shape of the
    reference's minimal whole-cell composite (metabolism + transcription,
    256 agents with division — BASELINE.json configs[3]); the full wcEcoli
    model rides the bridge (lens_tpu.bridge) instead.
    """
    c = _cfg(
        {
            "metabolism": {},
            "transcription": {"rates": {"rnap_mrna": 0.08}},
            "translation": {"pairs": {"rnap": ("rnap_mrna", 0.02)}},
            "degradation": {"rates": {"rnap_mrna": 0.01, "rnap": 0.0002}},
            "divide": {},
        },
        config,
    )
    return Compartment(
        processes={
            "metabolism": Metabolism(c["metabolism"]),
            "transcription": Transcription(c["transcription"]),
            "translation": Translation(c["translation"]),
            "degradation": Degradation(c["degradation"]),
            "derive_volume": DeriveVolume(),
            "divide_trigger": DivideTrigger(c["divide"]),
        },
        topology={
            "metabolism": {
                "metabolites": ("metabolites",),
                "global": ("global",),
                "fluxes": ("fluxes",),
            },
            "transcription": {"counts": ("counts",)},
            "translation": {"counts": ("counts",)},
            "degradation": {"counts": ("counts",)},
            "derive_volume": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
        },
    )


@register_composite
def chemotaxis_lattice(
    config: Mapping | None = None,
) -> Tuple[SpatialColony, Compartment]:
    """The reference's signature demo: chemotactic cells on an attractant
    lattice.

    MWC chemoreceptor (temporal gradient sensing via methylation
    adaptation) -> flagellar motor (stochastic run/tumble switching) ->
    run/tumble motility, plus Michaelis–Menten consumption of the
    attractant and growth/division — the "minimal chemotaxis cell" the
    reference boots onto its lattice (reconstructed: SURVEY.md §2
    "Composites", "Chemotaxis processes"). Cells climb gradients they
    simultaneously eat, so the colony both chases and reshapes the field.

    The default field is uniform; set a gradient by overwriting
    ``state.fields`` (tests) or via a media timeline.
    """
    c = _cfg(
        {
            "capacity": 1024,
            "shape": (64, 64),
            "size": None,            # defaults to 10 um bins
            "diffusion": 100.0,
            "initial_attractant": 0.1,  # mM, mid receptor range
            "timestep": 1.0,
            "molecule": "glucose",
            "receptor": {},
            "motor": {},
            "motility": {},
            "transport": {},
            "growth": {},
            "divide": {},
            "division": True,
        },
        config,
    )
    mol = c["molecule"]
    ext = float(c["initial_attractant"])
    processes = {
        "receptor": MWCChemoreceptor(
            {**c["receptor"], "molecule": mol, "external_default": ext}
        ),
        "motor": FlagellarMotor(c["motor"]),
        "motility": RunTumbleMotility(c["motility"]),
        "transport": MichaelisMentenTransport(
            {**c["transport"], "molecule": mol, "external_default": ext}
        ),
        "growth": Growth(c["growth"]),
        "divide_trigger": DivideTrigger(c["divide"]),
    }
    topology = {
        "receptor": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
        },
        "motor": {"internal": ("cell",)},
        "motility": {"boundary": ("boundary",), "internal": ("cell",)},
        "transport": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        },
        "growth": {"global": ("global",)},
        "divide_trigger": {"global": ("global",)},
    }
    compartment = Compartment(processes=processes, topology=topology)
    return _spatial_colony(
        compartment,
        [mol],
        c,
        diffusion=c["diffusion"],
        initial=c["initial_attractant"],
    )


def _rfba_network_fill(metab: dict, diffusion: dict, initial: dict):
    """Per-network lattice/LP conditioning shared by every rFBA composite:
    the larger data-layer networks bring more external species (lattice
    fields need diffusion/initial entries) and need the measured float32
    LP envelope (ops.linprog: Ruiz equilibration + pinned presolve +
    d-cap + weighted polish)."""
    if metab.get("network") == "ecoli_core_full":
        # The TRUE e_coli_core (72 metabolites x 95 canonical reactions,
        # data/ecoli_core_full_*.tsv): 17 lattice fields. tol 1e-5 keeps
        # the anaerobic optimum within ~3% of the float64 solve.
        metab = _cfg(
            {"lp_leak": 1.5e-3, "lp_tol": 1e-5, "lp_iterations": 45},
            metab,
        )
        diffusion = _cfg(
            {"glc": 600.0, "fru": 600.0, "ace": 900.0, "acald": 1000.0,
             "akg": 700.0, "etoh": 1200.0, "for": 1400.0, "fum": 800.0,
             "gln": 700.0, "glu": 700.0, "lac": 900.0, "mal": 800.0,
             "nh4": 1800.0, "o2": 2000.0, "co2": 1900.0, "pyr": 900.0,
             "succ": 800.0},
            diffusion,
        )
        initial = _cfg(
            {"glc": 10.0, "fru": 0.0, "ace": 0.0, "acald": 0.0,
             "akg": 0.0, "etoh": 0.0, "for": 0.0, "fum": 0.0, "gln": 0.0,
             "glu": 0.0, "lac": 0.0, "mal": 0.0, "nh4": 5.0, "o2": 5.0,
             "co2": 0.0, "pyr": 0.0, "succ": 0.0},
            initial,
        )
    if metab.get("network") == "ecoli_core":
        # Reference-scale network: the loader supplies 7 external species;
        # fill lattice defaults for the ones the small-network defaults
        # don't name, and give the float32 LP the conditioning recipe it
        # needs at this size (see FBAMetabolism.defaults["lp_leak"]).
        # lp_iterations=45 is a CAP (the while-loop solve exits once the
        # whole batch is accepted at tolerance — typically ~10 iterations
        # on these environments): measured (64 random environments,
        # CPU+TPU) that convergence fraction and converged objectives are
        # IDENTICAL from 40 to 60 iterations, so 45 keeps margin over the
        # measured 40 floor at zero typical-case cost.
        metab = _cfg(
            {"lp_leak": 1.5e-3, "lp_tol": 1e-4, "lp_iterations": 45},
            metab,
        )
        diffusion = _cfg(
            {"lcts": 500.0, "nh4": 1800.0, "co2": 1900.0, "eth": 1200.0},
            diffusion,
        )
        initial = _cfg(
            {"lcts": 0.0, "nh4": 5.0, "co2": 0.0, "eth": 0.0},
            initial,
        )
    return metab, diffusion, initial


def _rfba_cell(
    metab_cfg: Mapping, divide_cfg: Mapping, motility_cfg: Mapping
) -> Tuple[FBAMetabolism, Dict, Dict]:
    """The rFBA cell shared by every rFBA composite: exact-LP metabolism
    + volume derivation + division trigger + Brownian motility. Returns
    ``(metabolism, processes, topology)`` so callers can extend both
    dicts (rfba_lattice adds genome expression) before building the
    Compartment."""
    metabolism = FBAMetabolism(metab_cfg)
    processes = {
        "metabolism": metabolism,
        "derive_volume": DeriveVolume(),
        "divide_trigger": DivideTrigger(divide_cfg),
        "motility": BrownianMotility(motility_cfg),
    }
    topology = {
        "metabolism": {
            "external": ("boundary", "external"),
            "exchange": ("boundary", "exchange"),
            "global": ("global",),
            "fluxes": ("fluxes",),
        },
        "derive_volume": {"global": ("global",)},
        "divide_trigger": {"global": ("global",)},
        "motility": {"boundary": ("boundary",)},
    }
    if metabolism.config["lp_warm_start"]:
        topology["metabolism"]["lp_state"] = ("lp_state",)
    return metabolism, processes, topology


def _field_species(
    compartment: Compartment,
    capacity: int,
    lattice: Lattice,
    mols,
    division: bool,
    coupling: str = "fused",
) -> SpatialColony:
    """One species of a multi-species lattice: Colony + SpatialColony
    with the standard boundary port wiring for ``mols`` (shared by
    mixed_species_lattice and rfba_cross_feeding — species on ONE
    lattice, so the Lattice is passed in, unlike ``_spatial_colony``)."""
    colony = Colony(
        compartment,
        capacity=int(capacity),
        division_trigger=("global", "divide") if division else None,
        death_trigger=_death_trigger_of(compartment),
    )
    return SpatialColony(
        colony,
        lattice,
        field_ports={
            mol: (
                ("boundary", "external", mol),
                ("boundary", "exchange", f"{mol}_exchange"),
            )
            for mol in mols
        },
        location_path=("boundary", "location"),
        coupling=coupling,
    )


@register_composite
def rfba_lattice(
    config: Mapping | None = None,
) -> Tuple[SpatialColony, Compartment]:
    """Regulated-FBA E. coli colony on a glucose/acetate/oxygen lattice.

    The exact-LP metabolism model (Covert–Palsson 2002 lineage — see
    :mod:`lens_tpu.processes.fba_metabolism`): each cell maximizes biomass
    flux over the core-carbon network with boolean regulation, secreting
    acetate under overflow and re-consuming it after glucose exhaustion —
    colony-scale diauxie with spatial nutrient gradients. Mass from
    biomass flux drives volume (DeriveVolume) and division.
    """
    c = _cfg(
        {
            "capacity": 1024,
            "shape": (64, 64),
            "size": None,             # defaults to 10 um bins
            "diffusion": {"glc": 600.0, "ace": 900.0, "o2": 2000.0},
            "initial": {"glc": 10.0, "ace": 0.0, "o2": 5.0},
            "timestep": 1.0,
            "metabolism": {},
            "expression": None,
            "divide": {},
            "motility": {"sigma": 0.5},
            "division": True,
            "sampler": None,
        },
        config,
    )
    _thread_sampler(c, c["expression"])
    c["metabolism"], c["diffusion"], c["initial"] = _rfba_network_fill(
        c["metabolism"], c["diffusion"], c["initial"]
    )
    metabolism, processes, topology = _rfba_cell(
        c["metabolism"], c["divide"], c["motility"]
    )
    if c.get("expression") is not None:
        # Metabolism + transcription in one compartment (config 3's
        # composite shape): the gene table's regulation rules read the
        # SAME boundary concentrations the LP's rules do, so e.g. the lac
        # genes and the lcts_uptake reaction switch together.
        from lens_tpu.processes.genome_expression import GenomeExpression

        expr = GenomeExpression(c["expression"])
        missing = [
            mol for mol in expr.rule_species
            if mol not in metabolism.external
        ]
        if missing:
            raise ValueError(
                f"expression rules read {missing}, not lattice molecules "
                f"of this network ({list(metabolism.external)})"
            )
        # Shared boundary variables: declarations must agree (core.engine).
        # external_defaults is only read by ports_schema (lazily), so the
        # one constructed instance can be configured after the fact — the
        # gene table is parsed and its rules compiled exactly once.
        expr.config["external_defaults"] = {
            mol: 10.0 for mol in expr.rule_species
        }
        processes["expression"] = expr
        topology["expression"] = {
            "counts": ("counts",),
            "rates": ("rates",),
            "external": ("boundary", "external"),
        }
    compartment = Compartment(processes=processes, topology=topology)
    return _spatial_colony(
        compartment,
        list(metabolism.external),
        c,
        diffusion=c["diffusion"],
        initial=c["initial"],
    )


@register_composite
def rfba_cross_feeding(
    config: Mapping | None = None,
):
    """Cross-feeding at network scale: exact-rFBA E. coli + an acetate
    scavenger on one lattice.

    The ``ecoli`` species runs the regulated core-carbon LP per cell
    (:mod:`lens_tpu.processes.fba_metabolism`, Covert–Palsson lineage):
    under glucose-rich aerobic growth the network OVERFLOWS, secreting
    acetate into the cell's lattice bin. The ``scavenger`` species
    (Michaelis–Menten acetate transport + growth + division + motility)
    lives off that secretion — the classic E. coli syntrophy loop, with
    the two populations coupled ONLY through the shared acetate field.
    The reference boots different agent types onto one environment
    through its broker (SURVEY.md §7 hard-part #1); here each species is
    its own vmap inside one program, and the cross-feeding flux is a
    gather/scatter through the field.
    """
    c = _cfg(
        {
            "capacity": {"ecoli": 256, "scavenger": 256},
            "shape": (32, 32),
            "size": None,             # defaults to 10 um bins
            "diffusion": {"glc": 600.0, "ace": 900.0, "o2": 2000.0},
            "initial": {"glc": 10.0, "ace": 0.0, "o2": 5.0},
            "timestep": 1.0,
            "division": True,
            "ecoli": {
                "metabolism": {},
                "divide": {},
                "motility": {"sigma": 0.5},
            },
            "scavenger": {
                # starts on an EMPTY acetate field: everything it eats
                # was secreted by the rFBA species
                "transport": {
                    "molecule": "ace",
                    "vmax": 0.05,
                    "external_default": 0.0,
                },
                "growth": {"rate": 0.0003},
                "divide": {},
                "motility": {"sigma": 0.5},
                # optional starvation: {"variable": "ace_internal",
                # "threshold": x, "when": "below", ...} — the trigger's
                # global port wires onto ("cell",) so it watches the food
                # pool; scavenger deaths then track the overflow supply
                "death": None,
            },
        },
        config,
    )
    from lens_tpu.environment.multispecies import MultiSpeciesColony

    e = c["ecoli"]
    e["metabolism"], c["diffusion"], c["initial"] = _rfba_network_fill(
        e["metabolism"], c["diffusion"], c["initial"]
    )
    metabolism, ecoli_procs, ecoli_topo = _rfba_cell(
        e["metabolism"], e["divide"], e["motility"]
    )
    ecoli = Compartment(processes=ecoli_procs, topology=ecoli_topo)
    s = c["scavenger"]
    if s["death"] is not None:
        s["death"] = _default_boot_yolk(s["transport"], s["death"])
    scav_procs = {
        "transport": MichaelisMentenTransport(s["transport"]),
        "growth": Growth(s["growth"]),
        "divide_trigger": DivideTrigger(s["divide"]),
        "motility": BrownianMotility(s["motility"]),
    }
    scav_topo = {
        "transport": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        },
        "growth": {"global": ("global",)},
        "divide_trigger": {"global": ("global",)},
        "motility": {"boundary": ("boundary",)},
    }
    if s["death"] is not None:
        _add_cell_store_death(scav_procs, scav_topo, "ace_internal", s["death"])
    scavenger = Compartment(processes=scav_procs, topology=scav_topo)
    lattice = _make_lattice(
        c, list(metabolism.external), c["diffusion"], c["initial"]
    )
    coupling = _coupling_of(c)
    multi = MultiSpeciesColony(
        species={
            "ecoli": _field_species(
                ecoli, c["capacity"]["ecoli"], lattice,
                list(metabolism.external), c["division"], coupling,
            ),
            "scavenger": _field_species(
                scavenger, c["capacity"]["scavenger"], lattice, ["ace"],
                c["division"], coupling,
            ),
        },
        lattice=lattice,
        coupling=coupling,
    )
    return multi, {"ecoli": ecoli, "scavenger": scavenger}


@register_composite
def mixed_species_lattice(
    config: Mapping | None = None,
):
    """Config 4, genuinely mixed: two species with DISTINCT process sets
    on one lattice (SURVEY.md §7 hard-part #1; the reference boots
    different agent types onto the same environment).

    - ``ecoli``: deterministic kinetics — Michaelis–Menten glucose
      transport + exponential growth + division + Brownian motility.
    - ``scavenger``: hybrid stochastic — tau-leap Gillespie gene
      expression beside Michaelis–Menten ACETATE transport + growth +
      division + motility.

    The species couple through the shared two-molecule field (combined
    bin occupancy) while running entirely different programs — each is
    its own vmap, so neither pays for the other's processes. Returns
    ``(multi, {"ecoli": compartment, "scavenger": compartment})``.
    """
    c = _cfg(
        {
            "capacity": {"ecoli": 512, "scavenger": 512},
            "shape": (64, 64),
            "size": None,             # defaults to 10 um bins
            "diffusion": {"glucose": 600.0, "acetate": 900.0},
            "initial": {"glucose": 10.0, "acetate": 5.0},
            "timestep": 1.0,
            "division": True,
            "ecoli": {
                "transport": {},
                "growth": {},
                "divide": {},
                "motility": {"sigma": 0.5},
            },
            "scavenger": {
                "transport": {"molecule": "acetate", "vmax": 0.05},
                "expression": {},
                "growth": {"rate": 0.0003},
                "divide": {},
                "motility": {"sigma": 0.5},
            },
            "sampler": None,
        },
        config,
    )
    _thread_sampler(c, c["scavenger"]["expression"])
    from lens_tpu.environment.multispecies import MultiSpeciesColony

    lattice = _make_lattice(
        c, ["glucose", "acetate"], c["diffusion"], c["initial"]
    )

    e = c["ecoli"]
    ecoli = Compartment(
        processes={
            "transport": MichaelisMentenTransport(e["transport"]),
            "growth": Growth(e["growth"]),
            "divide_trigger": DivideTrigger(e["divide"]),
            "motility": BrownianMotility(e["motility"]),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "growth": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
            "motility": {"boundary": ("boundary",)},
        },
    )
    s = c["scavenger"]
    # "expression": None drops the (stochastic) expression process — a
    # fully deterministic scavenger for equality tests / dry runs.
    scav_procs = {"transport": MichaelisMentenTransport(s["transport"])}
    scav_topo = {
        "transport": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        },
    }
    if s["expression"] is not None:
        scav_procs["expression"] = StochasticExpression(s["expression"])
        scav_topo["expression"] = {"counts": ("counts",), "rates": ("rates",)}
    scav_procs.update(
        growth=Growth(s["growth"]),
        divide_trigger=DivideTrigger(s["divide"]),
        motility=BrownianMotility(s["motility"]),
    )
    scav_topo.update(
        growth={"global": ("global",)},
        divide_trigger={"global": ("global",)},
        motility={"boundary": ("boundary",)},
    )
    scavenger = Compartment(processes=scav_procs, topology=scav_topo)
    coupling = _coupling_of(c)
    multi = MultiSpeciesColony(
        species={
            "ecoli": _field_species(
                ecoli, c["capacity"]["ecoli"], lattice, ["glucose"],
                c["division"], coupling,
            ),
            "scavenger": _field_species(
                scavenger, c["capacity"]["scavenger"], lattice, ["acetate"],
                c["division"], coupling,
            ),
        },
        lattice=lattice,
        coupling=coupling,
    )
    return multi, {"ecoli": ecoli, "scavenger": scavenger}


@register_composite
def ecoli_lattice(
    config: Mapping | None = None,
) -> Tuple[SpatialColony, Compartment]:
    """Config 2 flagship: E. coli-like cells on a diffusion lattice.

    Michaelis–Menten glucose transport + exponential growth + division +
    Brownian motility, coupled to a shared glucose field. This is the
    rebuild of the reference's standard lattice experiment (outer lattice
    agent + N transport/growth inner agents, reconstructed:
    ``lens/environment/boot.py`` lattice experiment, SURVEY.md §3.1).
    Returns ``(spatial, compartment)``; build state via
    ``spatial.initial_state(n_alive, key)``.
    """
    c = _cfg(
        {
            "capacity": 10240,
            "shape": (256, 256),
            "size": None,            # defaults to 10 um bins
            "diffusion": 600.0,      # um^2/s, glucose-ish
            "initial_glucose": 10.0,  # mM
            "timestep": 1.0,
            "transport": {},
            "growth": {},
            "divide": {},
            "motility": {"sigma": 0.5},
            "division": True,
            # optional starvation: die when the internal glucose pool
            # drains (same pattern as rfba_cross_feeding's scavenger —
            # the trigger's global port wires onto the cell store)
            "death": None,
        },
        config,
    )
    if c["death"] is not None:
        c["death"] = _default_boot_yolk(c["transport"], c["death"])
    processes = {
        "transport": MichaelisMentenTransport(c["transport"]),
        "growth": Growth(c["growth"]),
        "divide_trigger": DivideTrigger(c["divide"]),
        "motility": BrownianMotility(c["motility"]),
    }
    topology = {
        "transport": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        },
        "growth": {"global": ("global",)},
        "divide_trigger": {"global": ("global",)},
        "motility": {"boundary": ("boundary",)},
    }
    if c["death"] is not None:
        _add_cell_store_death(
            processes, topology, "glucose_internal", c["death"]
        )
    compartment = Compartment(processes=processes, topology=topology)
    return _spatial_colony(
        compartment,
        ["glucose"],
        c,
        diffusion=c["diffusion"],
        initial=c["initial_glucose"],
    )
