"""Model compositions: pre-wired cell types and colony builders.

The reference ships pre-wired compartments — processes + topology for
named cell types — in its composites/boot layer (reconstructed:
``lens/composites/`` / ``lens/environment/boot.py``, SURVEY.md §2
"Composites"). This package is the rebuild's equivalent: factory functions
that assemble a ``Compartment`` (and, for spatial models, a
``SpatialColony``) from a config dict, so experiment configs stay pure
data.
"""

from lens_tpu.models.composites import (
    composite_registry,
    register_composite,
    chemotaxis_lattice,
    ecoli_lattice,
    grow_divide,
    hybrid_cell,
    minimal_ode,
    minimal_wcecoli,
    mixed_species_lattice,
    rfba_lattice,
    toggle_colony,
)

__all__ = [
    "composite_registry",
    "register_composite",
    "chemotaxis_lattice",
    "ecoli_lattice",
    "grow_divide",
    "hybrid_cell",
    "minimal_ode",
    "minimal_wcecoli",
    "mixed_species_lattice",
    "rfba_lattice",
    "toggle_colony",
]
