"""CLI entry point: ``python -m lens_tpu <command> ...``.

Replaces the reference's control/boot command surface
(``python -m lens.actor.control experiment --number N ...``, boot scripts;
reconstructed SURVEY.md §1 L5, §3.1) with eight commands against the
experiment layer:

- ``run``     start an experiment from a composite name + JSON config
- ``resume``  continue the latest checkpoint of an experiment
- ``serve``   continuous-batching scenario server: many small requests
  multiplexed onto one resident jitted multi-lane program
  (lens_tpu.serve; see docs/serving.md)
- ``frontdoor``  the same server behind an async HTTP front end:
  submit / status / SSE record streaming / cancel with multi-tenant
  fair-share admission, priority lanes, rate limits, and Prometheus
  ``/metrics`` (lens_tpu.frontdoor; docs/serving.md, "Front door");
  SIGTERM/SIGINT drain gracefully
- ``sweep``   resumable parameter sweep / adaptive search from a JSON
  spec: grid/random/LHS spaces, scalar objectives, successive-halving
  early stopping, crash-safe ledger resume (lens_tpu.sweep; see
  docs/sweeps.md)
- ``trace``   convert a serve span log (``serve --trace-dir``) to
  Chrome/Perfetto trace-event JSON (lens_tpu.obs; see
  docs/observability.md)
- ``list``    show registered composites, processes, emitters
- ``demo``    step ONE process standalone and plot it (the reference's
  per-process ``__main__`` dev harness)
- ``analyze`` render the standard offline plots for an emitted log (the
  reference's ``lens/analysis`` scripts)

Examples::

    python -m lens_tpu list
    python -m lens_tpu run --composite toggle_colony --n-agents 100 \\
        --time 200 --emitter log --out-dir out/exp1
    python -m lens_tpu run --composite ecoli_lattice --time 50 \\
        --config '{"capacity": 1024, "shape": [64, 64]}'
    python -m lens_tpu resume --composite toggle_colony --time 400 \\
        --out-dir out/exp1
    python -m lens_tpu serve --composite toggle_colony --lanes 8 \\
        --requests requests.json --out-dir out/served
    python -m lens_tpu sweep --spec sweep.json --out-dir out/sweep1
    python -m lens_tpu sweep --spec sweep.json --out-dir out/sweep1 \\
        --resume   # continue a killed sweep from its ledger
    python -m lens_tpu analyze out/exp1 --animate
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_mesh(value: str) -> dict:
    """'4x2' -> {"agents": 4, "space": 2}; '8' -> {"agents": 8, "space": 1}."""
    agents, _, space = value.lower().partition("x")
    try:
        return {"agents": int(agents), "space": int(space or 1)}
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"{value!r} is not AGENTSxSPACE (e.g. 4x2)"
        )


def _add_bucket_args(p: argparse.ArgumentParser) -> None:
    """The bucket knobs shared by ``serve`` and ``frontdoor`` (one
    bucket per CLI invocation; the in-process SimServer API takes
    arbitrary bucket maps)."""
    p.add_argument(
        "--composite", default="toggle_colony",
        help="the bucket's composite (one bucket per invocation; "
        "the in-process SimServer API takes arbitrary bucket maps)",
    )
    p.add_argument(
        "--config", default="{}", help="composite config as JSON"
    )
    p.add_argument("--capacity", type=int, default=None)
    p.add_argument(
        "--lanes", type=int, default=4, help="resident lane count L"
    )
    p.add_argument(
        "--window", type=int, default=32,
        help="steps per scheduler tick (amortizes dispatch; coarsens "
        "admission granularity)",
    )
    p.add_argument("--timestep", type=float, default=1.0)
    p.add_argument("--emit-every", type=int, default=1)


def _add_server_args(
    p: argparse.ArgumentParser, frontdoor_defaults: bool = False
) -> None:
    """The SimServer knobs shared by ``serve`` and ``frontdoor``.
    ``frontdoor_defaults`` flips the policies whose right default
    differs for a multi-tenant network server (sink errors scoped to
    one request instead of fatal)."""
    p.add_argument(
        "--queue-depth", type=int, default=64,
        help="bounded admission queue; a full queue rejects with a "
        "retry-after hint",
    )
    p.add_argument(
        "--pipeline", choices=["on", "off"], default="on",
        help="depth-2 serve pipeline: overlap device windows with "
        "background host-side streaming (off = the synchronous "
        "debugging path; results are bitwise identical either way)",
    )
    p.add_argument(
        "--stream-queue", type=int, default=2,
        help="max windows queued/processing on the background "
        "streamer before the scheduler stalls (pipeline "
        "backpressure depth)",
    )
    p.add_argument(
        "--flush-every", type=int, default=1,
        help="flush each request's result log every k-th window "
        "append (batched flush; 1 = tightest tailing-reader "
        "visibility)",
    )
    p.add_argument(
        "--snapshot-budget-mb", type=float, default=256.0,
        help="byte budget (MiB) for the content-addressed snapshot "
        "store behind request prefix caching and hold_state "
        "(unpinned prefix snapshots are evicted LRU-first past it; "
        "see docs/serving.md, 'Prefix caching & forking'). With "
        "--host-budget-mb/--tier-dir this bounds the DEVICE tier and "
        "eviction becomes demotion",
    )
    p.add_argument(
        "--host-budget-mb", type=float, default=None,
        help="arm the host-RAM snapshot tier (MiB): snapshots past "
        "the device budget demote to host memory instead of "
        "evicting, and promote back on a hit (docs/serving.md, "
        "'Tiered snapshots & speculative warming'). Default: no "
        "host tier",
    )
    p.add_argument(
        "--tier-dir", default=None, metavar="DIR",
        help="arm the DISK snapshot tier: overflow demotes to DIR "
        "via the checkpoint rename protocol, and the directory "
        "survives restarts — a fresh server re-adopts every "
        "content-addressed snapshot, so repeat traffic after a "
        "reboot hits warm disk entries instead of recomputing "
        "prefixes. Default: <recover-dir>/snapshots when tiers are "
        "armed, else no disk tier",
    )
    p.add_argument(
        "--result-cache-mb", type=float, default=None,
        metavar="MB",
        help="arm the durable content-addressed RESULT cache (MiB): "
        "each completed request's .lens log is filed under its "
        "request fingerprint in <tier-dir|recover-dir>/results, and "
        "an identical later submit is answered whole from disk — "
        "zero device windows, zero queueing (docs/serving.md, "
        "'Suffix dedup & result cache'). LRU-evicted past the "
        "budget, survives restarts. Needs --tier-dir or "
        "--recover-dir. Default: off",
    )
    p.add_argument(
        "--dedup", choices=["on", "off"], default="off",
        help="in-flight suffix dedup: concurrent identical requests "
        "coalesce onto ONE lane and fan out at the streamer, each "
        "getting its own byte-identical stream (docs/serving.md, "
        "'Suffix dedup & result cache'). Default: off (the bitwise "
        "round-17 path)",
    )
    p.add_argument(
        "--warm", action="store_true",
        help="speculative prefix warming: pre-run (serve: the "
        "request list's distinct prefixes; frontdoor: each tenant's "
        "repeated prefix shapes) in idle lanes ahead of demand — "
        "strictly scavenging, never delaying admitted work "
        "(docs/serving.md, 'Tiered snapshots & speculative warming')",
    )
    p.add_argument(
        "--check-finite", choices=["off", "window"], default="off",
        help="lane quarantine: per-window finite check over every "
        "lane's state; a lane that goes NaN/Inf fails ONLY its "
        "request (SimulationDiverged) and is reclaimed, co-batched "
        "lanes untouched (docs/serving.md, 'Fault tolerance & "
        "recovery'). off = the bitwise round-11 path",
    )
    p.add_argument(
        "--watchdog", type=float, default=None, metavar="SECONDS",
        help="expire a hung device-window/streamer handoff after this "
        "many stalled seconds (WatchdogTimeout) instead of wedging "
        "the scheduler forever; default: wait indefinitely",
    )
    p.add_argument(
        "--sink-errors", choices=["fatal", "request"],
        default="request" if frontdoor_defaults else "fatal",
        help="what a failed result-sink append does: 'fatal' parks "
        "the error on the stream pipe (single-operator batch "
        "serving), 'request' fails only the owning request and "
        "keeps serving everyone else (the multi-tenant policy; "
        "docs/serving.md)",
    )
    p.add_argument(
        "--recover-dir", default=None, metavar="DIR",
        help="serve write-ahead log + held-snapshot spills live here; "
        "if DIR already holds a WAL the server RECOVERS first "
        "(finished requests keep their logs, unfinished ones re-run "
        "bitwise) and the request list resumes past the requests "
        "already recorded",
    )
    p.add_argument(
        "--mesh", type=int, default=None, metavar="N",
        help="shard the server across N devices (one resident lane "
        "pool per device, a host scheduler ticking all shards; a "
        "dead device quarantines and its requests fail over to the "
        "survivors — docs/serving.md, 'Mesh serving & device "
        "failover'). On CPU, simulate devices with "
        "XLA_FLAGS=--xla_force_host_platform_device_count=N. "
        "Default: single default-device serving",
    )
    p.add_argument(
        "--device-watchdog", type=float, default=None,
        metavar="SECONDS",
        help="quarantine a device whose dispatched window makes no "
        "progress for this many seconds (whole-device fail-stop "
        "detection; requests re-queue onto surviving devices)",
    )
    p.add_argument(
        "--faults", default=None, metavar="JSON",
        help="fault-injection plan (a JSON file, or '-' for stdin): "
        '{"seed": 0, "faults": [{"kind": "nan", "request": '
        '"req-000001", "after_steps": 16}, ...]} — deterministic '
        "chaos for tests/CI (docs/serving.md, 'Fault injection')",
    )
    p.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="span tracing: append every request stage (queue wait, "
        "admission, window dispatch, device compute, streamer flush, "
        "retire, prefix resolution, spills, quarantines) to "
        "DIR/serve.trace; convert with 'python -m lens_tpu trace DIR "
        "--out trace.json' for Perfetto (docs/observability.md). "
        "Default: tracing off (the bitwise-identical fast path)",
    )
    p.add_argument(
        "--metrics-interval", type=float, default=None,
        metavar="SECONDS",
        help="sample server counters/gauges/latency histograms into a "
        "metrics.jsonl time-series ring (in --trace-dir, else "
        "--out-dir) every this many wall seconds; default: no "
        "sampling",
    )


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lens_tpu", description="TPU-native cell-colony simulations"
    )
    sub = p.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run an experiment")
    resume = sub.add_parser(
        "resume", help="continue the latest checkpoint of an experiment"
    )
    for sp in (run, resume):
        sp.add_argument("--composite", default="grow_divide")
        sp.add_argument(
            "--config", default="{}", help="composite config as JSON"
        )
        def _n_agents(value: str):
            # int for single-species composites; a JSON dict for
            # multi-species ones, e.g. '{"ecoli": 100, "scavenger": 50}'
            try:
                return int(value)
            except ValueError:
                parsed = json.loads(value)
                if not isinstance(parsed, dict):
                    raise argparse.ArgumentTypeError(
                        f"expected an int or a JSON dict, got {value!r}"
                    )
                return parsed

        sp.add_argument("--n-agents", type=_n_agents, default=1)
        sp.add_argument("--capacity", type=int, default=None)
        sp.add_argument("--time", type=float, default=100.0, help="sim seconds")
        sp.add_argument("--timestep", type=float, default=1.0)
        sp.add_argument("--emit-every", type=int, default=1)
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument(
            "--emitter", choices=["ram", "log", "null"], default="ram"
        )
        sp.add_argument(
            "--out-dir",
            default=None,
            help="directory for the emit log + checkpoints",
        )
        sp.add_argument(
            "--checkpoint-every",
            type=float,
            default=None,
            help="sim-seconds between checkpoints",
        )
        sp.add_argument(
            "--timeline",
            default=None,
            help='media timeline, e.g. "0 minimal, 500 minimal_lactose"',
        )
        sp.add_argument(
            "--mesh",
            default=None,
            type=_parse_mesh,
            metavar="AGENTSxSPACE",
            help="shard over a device mesh, e.g. 4x2 (spatial models)",
        )
        def _free_frac(value: str) -> float:
            frac = float(value)
            if not 0.0 < frac < 1.0:
                raise argparse.ArgumentTypeError(
                    f"FREE_FRAC must be a fraction in (0, 1), got {frac}"
                )
            return frac

        sp.add_argument(
            "--auto-expand",
            nargs="?",
            const=0.2,
            default=None,
            type=_free_frac,
            metavar="FREE_FRAC",
            help="double colony capacity at segment boundaries when the "
            "free-row fraction drops to this value (default 0.2); needs "
            "--checkpoint-every to define segments",
        )
        sp.add_argument(
            "--replicates",
            type=int,
            default=None,
            metavar="R",
            help="run R independent replicates as one device program "
            "(colony.Ensemble); emission gains a [T, R, ...] layout and "
            "`analyze` renders fan charts",
        )
        sp.add_argument(
            "--replicate-overrides",
            default=None,
            metavar="JSON",
            help="per-replicate initial conditions (leaves carry a "
            "leading [R] axis) turning --replicates into a parameter "
            'scan, e.g. \'{"global": {"volume": [1.0, 1.4, 1.9]}}\'; '
            "`analyze` then auto-plots the dose-response from the log "
            "header",
        )
        sp.add_argument("--quiet", action="store_true")
        sp.add_argument(
            "--trace",
            default=None,
            metavar="DIR",
            help="capture an XLA profiler trace of the run into DIR "
            "(view with TensorBoard's profile plugin or perfetto)",
        )

    serve = sub.add_parser(
        "serve",
        help="serve many scenario requests through one resident "
        "continuous-batching multi-lane program (docs/serving.md)",
    )
    _add_bucket_args(serve)
    serve.add_argument(
        "--requests", required=True,
        help="JSON file of request objects (or '-' for stdin): "
        '[{"seed": 1, "horizon": 50.0, "overrides": {...}, '
        '"deadline": 30.0, "emit": {"paths": ["alive"]}}, ...]',
    )
    serve.add_argument(
        "--out-dir", default="out/serve",
        help="per-request .lens result logs + server_meta.json land here",
    )
    serve.add_argument(
        "--hosts", type=int, default=None, metavar="N",
        help="CLUSTER mode: spawn N serve worker processes (one "
        "simulated host each, own SimServer/WAL/tiers) behind a "
        "locality-aware router with work-stealing and whole-host "
        "failover (docs/serving.md, 'Cluster serving'). --out-dir "
        "becomes the cluster root. Default: single-host in-process "
        "serving, bit for bit the pre-cluster path",
    )
    _add_server_args(serve)

    frontdoor = sub.add_parser(
        "frontdoor",
        help="expose the scenario server over an async HTTP front end "
        "with multi-tenant fair-share admission and priority lanes "
        "(docs/serving.md, 'Front door'); SIGTERM/SIGINT drain "
        "gracefully",
    )
    _add_bucket_args(frontdoor)
    frontdoor.add_argument(
        "--host", default="127.0.0.1",
        help="bind address (0.0.0.0 to accept remote clients)",
    )
    frontdoor.add_argument(
        "--port", type=int, default=8080,
        help="listen port (0 picks a free one, printed at startup)",
    )
    frontdoor.add_argument(
        "--tenants", default=None, metavar="JSON",
        help="tenant table — a JSON file path, or the JSON inline: "
        "{'tenants': [{'name': ..., 'api_key': ..., "
        "'weight': 2.0, 'rate': 50, 'burst': 100, 'max_inflight': 64, "
        "'queue_depth': 256, 'default_priority': 'interactive'}, ...]} "
        "— omit for one open unlimited 'default' tenant "
        "(docs/serving.md, 'Front door')",
    )
    frontdoor.add_argument(
        "--out-dir", default="out/frontdoor",
        help="per-request .lens result logs + server_meta.json land here",
    )
    frontdoor.add_argument(
        "--drain-grace", type=float, default=None, metavar="SECONDS",
        help="on SIGTERM/SIGINT, wait at most this long for queued + "
        "in-flight requests to finish before closing anyway "
        "(default: wait indefinitely; a second signal force-quits)",
    )
    frontdoor.add_argument(
        "--hosts", type=int, default=None, metavar="N",
        help="CLUSTER mode: the door fronts N spawned serve worker "
        "processes behind the cluster router instead of one "
        "in-process SimServer (docs/serving.md, 'Cluster serving')",
    )
    _add_server_args(frontdoor, frontdoor_defaults=True)

    wal = sub.add_parser(
        "wal",
        help="human-readable, seq-merged dump of a serve write-ahead "
        "log: per-shard files merge on the global seq stamp; a "
        "cluster dir dumps every host's WAL (docs/serving.md)",
    )
    wal.add_argument(
        "wal",
        help="a --recover-dir (or its serve.wal), or a cluster dir "
        "holding host<k>/wal/ subdirectories",
    )
    wal.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the merged event list as JSON instead of text",
    )
    wal.add_argument(
        "--rid", default=None,
        help="only events for this request id (and its ancestry)",
    )

    cache = sub.add_parser(
        "cache",
        help="inspect (and optionally GC) a durable result-cache "
        "directory written under --result-cache-mb "
        "(docs/serving.md, 'Suffix dedup & result cache')",
    )
    cache.add_argument(
        "cache",
        help="the results directory (<tier-dir|recover-dir>/results, "
        "or a cluster dir's tiers/results), or a parent holding one",
    )
    cache.add_argument(
        "--max-mb", type=float, default=None, metavar="MB",
        help="evict LRU entries until the cache fits this budget "
        "(offline GC; uses the same rename protocol as the server, "
        "so it is safe beside a live one)",
    )
    cache.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the entry table as JSON instead of text",
    )

    cw = sub.add_parser(
        "cluster-worker",
        help="one cluster serve worker (normally spawned by the "
        "router; run by hand only to join an external router — "
        "docs/serving.md, 'Cluster serving')",
    )
    cw.add_argument(
        "--config", required=True,
        help="worker config JSON written by the router (buckets, "
        "server knobs, host identity, join address)",
    )

    trace = sub.add_parser(
        "trace",
        help="convert a serve span log (--trace-dir) to Chrome/"
        "Perfetto trace-event JSON (docs/observability.md)",
    )
    trace.add_argument(
        "trace",
        help="the --trace-dir a server wrote (or the serve.trace file "
        "inside it)",
    )
    trace.add_argument(
        "--out", default=None, metavar="JSON",
        help="output path for the Chrome trace-event JSON (default: "
        "trace.json beside the span log); load it at "
        "https://ui.perfetto.dev or chrome://tracing",
    )

    sweep = sub.add_parser(
        "sweep",
        help="parameter sweep / adaptive search from a declarative JSON "
        "spec, with crash-safe ledger resume (docs/sweeps.md)",
    )
    sweep.add_argument(
        "--spec", required=True,
        help="sweep spec JSON file (or '-' for stdin): composite, "
        "space, horizon, objective, backend, optional asha — see "
        "docs/sweeps.md",
    )
    sweep.add_argument(
        "--out-dir", default=None,
        help="ledger + sweep_result.json (+ trials/ with "
        "--save-trajectories) land here; omit for an in-memory run",
    )
    sweep.add_argument(
        "--resume", action="store_true",
        help="continue a killed sweep from its ledger (re-runs only "
        "unfinished trials; refuses a changed spec)",
    )
    sweep.add_argument(
        "--save-trajectories", action="store_true",
        help="also write each trial's emitted trajectory as "
        "<out-dir>/trials/trial_<i>.lens (analysis.load_many reads "
        "them back)",
    )
    sweep.add_argument("--quiet", action="store_true")

    sub.add_parser("list", help="list composites, processes, emitters")

    ana = sub.add_parser(
        "analyze",
        help="render the standard plots for an emitted experiment log "
        "(the reference's offline analysis scripts)",
    )
    ana.add_argument(
        "log", help="emit log path (emit.lens) or the experiment out-dir"
    )
    ana.add_argument(
        "--out-dir", default=None, help="default: <log dir>/analysis"
    )
    ana.add_argument(
        "--molecule", type=int, default=0, help="field index for snapshots"
    )
    ana.add_argument(
        "--dx", type=float, default=1.0, help="um per lattice bin (overlays)"
    )
    ana.add_argument(
        "--animate", action="store_true", help="also write the field GIF"
    )

    demo = sub.add_parser(
        "demo",
        help="run ONE process standalone and save its timeseries plot "
        "(the reference's per-process __main__ dev harness)",
    )
    demo.add_argument("process", help="registered process name (see list)")
    demo.add_argument("--time", type=float, default=100.0)
    demo.add_argument("--timestep", type=float, default=1.0)
    demo.add_argument("--config", default="{}", help="process config JSON")
    demo.add_argument("--out-dir", default="out")
    demo.add_argument("--seed", type=int, default=0)
    return p


def _validate_run_args(args: argparse.Namespace) -> None:
    """Flag cross-checks that must fire BEFORE any jax import (backend
    init can block on a dead relay — fail fast on bad flags instead)."""
    if args.auto_expand is not None and not args.checkpoint_every:
        # expansion fires at segment boundaries; one big segment means
        # the flag would silently do nothing until the run is over
        raise SystemExit(
            "--auto-expand needs --checkpoint-every to define the "
            "segments at which expansion can happen"
        )
    # (--timeline with a non-lattice composite is rejected by Experiment
    # at construction — lattice-ness needs the composite registry, which
    # lives behind the jax import this function runs before.)
    if args.replicate_overrides is not None:
        if args.replicates is None:
            raise SystemExit(
                "--replicate-overrides needs --replicates to define the "
                "scan axis"
            )
        try:
            # parse once; _experiment_config consumes the dict
            args.replicate_overrides = json.loads(args.replicate_overrides)
        except json.JSONDecodeError as e:
            raise SystemExit(f"--replicate-overrides is not valid JSON: {e}")
    if args.replicates is not None:
        if args.replicates < 1:
            raise SystemExit(f"--replicates must be >= 1, got {args.replicates}")
        if args.mesh is not None:
            raise SystemExit(
                "--replicates does not compose with --mesh "
                "(see experiment.DEFAULT_CONFIG)"
            )


def _experiment_config(args: argparse.Namespace) -> dict:
    emitter: dict = {"type": args.emitter}
    checkpoint_dir = None
    if args.out_dir:
        if args.emitter == "log":
            emitter["path"] = f"{args.out_dir}/emit.lens"
        checkpoint_dir = f"{args.out_dir}/checkpoints"
    return {
        "mesh": args.mesh,
        "auto_expand": (
            {"free_frac": args.auto_expand, "factor": 2}
            if args.auto_expand is not None
            else None
        ),
        "composite": args.composite,
        "config": json.loads(args.config),
        "n_agents": args.n_agents,
        "capacity": args.capacity,
        "total_time": args.time,
        "timestep": args.timestep,
        "emit_every": args.emit_every,
        "seed": args.seed,
        "emitter": emitter,
        "checkpoint_dir": checkpoint_dir,
        "checkpoint_every": args.checkpoint_every,
        "timeline": args.timeline,
        "replicates": args.replicates,
        # _validate_run_args already parsed the JSON string to a dict
        "replicate_overrides": args.replicate_overrides or {},
    }


class _DrainSignals:
    """SIGTERM/SIGINT → graceful drain for the serving commands.

    The first signal flips ``draining`` (the command stops ACCEPTING —
    ``serve`` submits nothing further from its list, ``frontdoor``
    answers new submits 503) and the in-flight work runs to a clean
    close (streamer drained, sinks closed, WAL/meta written) — where a
    bare signal previously killed the process mid-window and left the
    next invocation to crash-recover. A second signal raises
    ``KeyboardInterrupt`` (the operator insists). Restores the prior
    handlers on exit; main-thread only (a signal constraint)."""

    def __init__(self, what: str = "serving"):
        self.draining = False
        self._what = what
        self._prior: list = []

    def __enter__(self) -> "_DrainSignals":
        import signal as _signal

        def handler(signum, frame):
            if self.draining:
                raise KeyboardInterrupt
            self.draining = True
            print(
                f"drain: caught signal {signum} — no new work "
                f"accepted; draining in-flight {self._what} "
                f"(signal again to force quit)",
                flush=True,
            )

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            self._prior.append((sig, _signal.signal(sig, handler)))
        return self

    def __exit__(self, *exc) -> None:
        import signal as _signal

        for sig, prior in self._prior:
            _signal.signal(sig, prior)


def _serve_requests(args, server, raw) -> int:
    """The serve CLI's drive loop, shared by the single-host SimServer
    and the --hosts cluster router (both present the same client
    surface)."""
    import time

    from lens_tpu.serve import QueueFull, ScenarioRequest

    with server, _DrainSignals("requests") as drain:
        if getattr(server, "recovered", 0) or any(
            not t.internal for t in server.tickets.values()
        ):
            # recovery replayed part of a previous invocation's list:
            # resume submitting past what the WAL already knows (the
            # CLI submits serially, so WAL submit order == list order)
            done_already = sum(
                1 for t in server.tickets.values() if not t.internal
            )
            print(
                f"recovered {done_already} request(s) from "
                f"{args.recover_dir or args.out_dir} "
                f"({getattr(server, 'recovered', 0)} re-queued); "
                f"resuming at request #{done_already}"
            )
            raw = raw[done_already:]
        if args.warm:
            # the REMAINING request list is the future traffic (the
            # truncation above already dropped what a recovered WAL
            # knows): pre-launch its distinct prefixes as warm
            # scavenger runs — the real submits below coalesce onto
            # (or hit) the warmed snapshots instead of paying their
            # own prefix misses
            warmed = set()
            for req in raw:
                try:
                    entry = dict(req or {})
                    entry.setdefault("composite", args.composite)
                    spec = ScenarioRequest.from_mapping(
                        entry
                    ).prefix_spec()
                    if spec is None:
                        continue
                    fp = json.dumps(spec, sort_keys=True, default=str)
                    if fp in warmed:
                        continue
                    warmed.add(fp)
                    server.prewarm(spec)
                except (ValueError, TypeError):
                    pass  # the real submit will report the bad block
        ids = []
        skipped = 0
        for i, req in enumerate(raw):
            if drain.draining:
                skipped = len(raw) - i
                break
            req = dict(req)
            req.setdefault("composite", args.composite)
            try:
                request = ScenarioRequest.from_mapping(req)
            except (ValueError, TypeError) as e:
                raise SystemExit(f"bad request {req!r}: {e}")
            while not drain.draining:
                try:
                    ids.append(server.submit(request))
                    break
                except QueueFull as e:
                    # the CLI is its own client: drain by ticking (a
                    # remote client would sleep e.retry_after instead)
                    server.tick()
                    time.sleep(min(e.retry_after, 0.05))
                except ValueError as e:
                    raise SystemExit(f"bad request {req!r}: {e}")
            else:
                skipped = len(raw) - i
                break
        # recovered re-queued requests report alongside fresh ones
        ids = [
            t.request_id
            for t in server.tickets.values()
            if not t.internal and t.request_id not in ids
        ] + ids
        if skipped:
            print(
                f"drain: stopped accepting after {len(ids)} of "
                f"{len(ids) + skipped} request(s); {skipped} never "
                f"submitted"
                + (
                    " (rerun with the same --recover-dir to serve "
                    "the rest)"
                    if args.recover_dir else ""
                ),
                flush=True,
            )
        server.run_until_idle()
        if skipped:
            print("drain: in-flight requests complete; closing "
                  "cleanly", flush=True)
        snap = server.metrics()
        by_status: dict = {}
        for rid in ids:
            st = server.status(rid)["status"]
            by_status[st] = by_status.get(st, 0) + 1
        occ = snap["occupancy"]  # None when no window ever ran
        print(
            f"served {len(ids)} requests "
            f"({', '.join(f'{k}={v}' for k, v in sorted(by_status.items()))}) "
            f"in {snap['counters']['ticks']} ticks / "
            f"{snap['counters']['windows']} windows; "
            f"occupancy={'n/a' if occ is None else f'{occ:.2f}'} "
            f"retraces={snap['retraces']}"
        )
        lat = snap["latency_seconds"]
        if lat["p50"] is not None:
            print(
                f"latency p50={lat['p50']:.3f}s p95={lat['p95']:.3f}s "
                f"p99={lat['p99']:.3f}s"
            )
        busy = snap.get("device_busy_fraction")
        if busy is not None:
            lag = snap["stream_lag_seconds"]
            print(
                f"pipeline {args.pipeline}: device_busy={busy:.2f} "
                f"stream_lag p50={lag['p50']:.4f}s "
                f"stalls={snap['stream_stalls']}"
            )
        c = snap["counters"]
        if c["prefix_hits"] + c["prefix_misses"]:
            print(
                f"prefix cache: hits={c['prefix_hits']} "
                f"misses={c['prefix_misses']} "
                f"coalesced={c['prefix_coalesced']} "
                f"forks={c['prefix_forks']} "
                f"evictions={c['snapshot_evictions']} "
                f"resident={snap.get('snapshots_resident', 0)} "
                f"({snap.get('snapshot_bytes', 0) / 2**20:.1f} MiB)"
            )
        tiers = snap.get("snapshot_tiers") or {}
        if any(
            row.get("promotions") or row.get("demotions")
            or (t != "device" and row.get("entries"))
            for t, row in tiers.items()
        ):
            print(
                "snapshot tiers: "
                + " ".join(
                    f"{t}={row['entries']}e/"
                    f"{row['bytes'] / 2**20:.1f}MiB "
                    f"(hits={row['hits']} promo={row['promotions']} "
                    f"demo={row['demotions']})"
                    for t, row in tiers.items()
                )
                + f" rejected={c['snapshot_rejected']}"
            )
        if c["warm_submitted"] or c["warm_hits"]:
            print(
                f"warming: submitted={c['warm_submitted']} "
                f"completed={c['warm_completed']} "
                f"hits={c['warm_hits']} "
                f"preempted={c['warm_preempted']}"
            )
        rhits = c.get("result_hits", 0) + c.get("router_result_hits", 0)
        rmiss = (
            c.get("result_misses", 0)
            + c.get("router_result_misses", 0)
        )
        if rhits or rmiss or c.get("suffix_coalesced", 0):
            # single-host metrics carry flat result_* gauges; the
            # cluster nests them under a "results" dict
            results = snap.get("results") or {}
            print(
                f"result cache: hits={rhits} misses={rmiss} "
                f"coalesced={c.get('suffix_coalesced', 0)} "
                f"evictions={c.get('result_evictions', 0)} "
                f"entries="
                f"{snap.get('result_entries', results.get('entries', 0))} "
                f"({snap.get('result_bytes', results.get('bytes', 0)) / 2**20:.1f} MiB) "
                f"device_seconds_saved="
                f"{c.get('device_seconds_saved', 0.0):.1f}"
            )
        if c["diverged"] or c["recovered"]:
            print(
                f"fault tolerance: diverged={c['diverged']} "
                f"recovered={c['recovered']}"
            )
        if args.mesh is not None and args.mesh > 1 \
                and "shards" in snap:
            rows = " ".join(
                f"shard{s['shard']}"
                f"{'[QUARANTINED]' if s['quarantined'] else ''}="
                f"{s['windows']}w"
                for s in snap["shards"]
            )
            print(
                f"mesh {args.mesh}: {rows} "
                f"quarantined={snap['quarantined_devices']} "
                f"requeued={c['requeued']}"
            )
        cl = snap.get("cluster")
        if cl:
            rows = " ".join(
                f"host{h['host']}{'' if h['alive'] else '[DOWN]'}="
                f"{h['adopted']}a/{h['stolen']}s"
                for h in cl["hosts"]
            )
            print(
                f"cluster {len(cl['hosts'])} hosts: {rows} "
                f"stolen={cl['stolen']} requeued={cl['requeued']} "
                f"hosts_down={len(cl['hosts_down'])}"
            )
        print(f"results: {server.out_dir}/<request-id>.lens")
        if cl:
            print(f"meta:    {args.out_dir}/cluster_meta.json "
                  f"(+ host<k>/server_meta.json)")
            print(f"wal:     {args.out_dir}/host<k>/wal "
                  f"(dump: python -m lens_tpu wal {args.out_dir})")
        else:
            print(f"meta:    {args.out_dir}/server_meta.json")
        if args.recover_dir:
            print(f"wal:     {args.recover_dir}/serve.wal")
        if args.trace_dir:
            print(
                f"trace:   {args.trace_dir}/serve.trace (render: "
                f"python -m lens_tpu trace {args.trace_dir})"
            )
        if args.metrics_interval is not None:
            print(
                f"metrics: "
                f"{args.trace_dir or args.out_dir}/metrics.jsonl"
            )
    return 0




def _split_fault_spec(spec):
    """Split a CLI fault spec between the cluster router (host_down)
    and the workers (everything else). Returns (router_faults,
    worker_spec) — either may be None."""
    import json as _json

    if spec is None:
        return None, None
    if isinstance(spec, str):
        with open(spec) as f:
            spec = _json.load(f)
    if isinstance(spec, dict):
        seed = spec.get("seed", 0)
        faults = spec.get("faults") or []
    else:
        seed, faults = 0, list(spec)
    router = [f for f in faults if f.get("kind") == "host_down"]
    workers = [f for f in faults if f.get("kind") != "host_down"]
    from lens_tpu.serve import FaultPlan

    return (
        FaultPlan(router, seed=seed) if router else None,
        {"seed": seed, "faults": workers} if workers else None,
    )


def _build_cluster(args, frontdoor_defaults=False):
    """ClusterServer from the shared serve/frontdoor CLI knobs
    (--hosts N; docs/serving.md, "Cluster serving"). --out-dir is the
    cluster root: shared logs in out/, shared snapshot tier in
    tiers/, per-host WAL dirs in host<k>/."""
    from lens_tpu.cluster import ClusterServer

    if args.recover_dir:
        raise SystemExit(
            "--hosts and --recover-dir are exclusive: cluster mode "
            "always arms one WAL per host under the cluster dir "
            "(<out-dir>/host<k>/wal)"
        )
    router_faults, worker_faults = None, None
    if args.faults is not None:
        try:
            router_faults, worker_faults = _split_fault_spec(
                json.load(sys.stdin) if args.faults == "-"
                else args.faults
            )
        except (ValueError, OSError) as e:
            raise SystemExit(f"--faults: {e}")
    worker = {
        "pipeline": args.pipeline,
        "stream_queue": args.stream_queue,
        "flush_every": args.flush_every,
        "snapshot_budget_mb": args.snapshot_budget_mb,
        "check_finite": args.check_finite,
        "watchdog_s": args.watchdog,
        "sink_errors": args.sink_errors,
    }
    if args.host_budget_mb is not None:
        worker["host_budget_mb"] = args.host_budget_mb
    if args.tier_dir:
        worker["tier_dir"] = args.tier_dir
    if args.mesh is not None:
        worker["mesh"] = args.mesh
    if args.device_watchdog is not None:
        worker["device_watchdog_s"] = args.device_watchdog
    if worker_faults is not None:
        worker["faults"] = worker_faults
    if args.metrics_interval is not None:
        if args.trace_dir:
            worker["metrics_interval_s"] = args.metrics_interval
        else:
            print(
                "cluster mode samples per-host metrics.jsonl only "
                "under --trace-dir (the shared out dir would clobber); "
                "skipping --metrics-interval",
                file=sys.stderr,
            )
    return ClusterServer(
        {
            args.composite: {
                "config": json.loads(args.config),
                "capacity": args.capacity,
                "lanes": args.lanes,
                "window": args.window,
                "timestep": args.timestep,
                "emit_every": args.emit_every,
            }
        },
        hosts=args.hosts,
        cluster_dir=args.out_dir,
        queue_depth=args.queue_depth,
        worker=worker,
        faults=router_faults,
        trace_dir=args.trace_dir,
        result_cache_mb=args.result_cache_mb,
        dedup=args.dedup,
    )


def _cmd_serve(args: argparse.Namespace) -> int:
    """Drive a SimServer over a JSON request list: submit (respecting
    backpressure by retrying after the hinted delay), run to idle,
    report. Results stream to per-request ``.lens`` logs while the
    scheduler is still running — tail them with
    ``lens_tpu.emit.log.tail_records``. SIGTERM/SIGINT drain: no
    further list entries are submitted, everything in flight finishes
    and closes cleanly (the WAL, if armed, lets a rerun pick up the
    skipped tail)."""
    import time

    from lens_tpu.serve import (
        FaultPlan,
        QueueFull,
        ScenarioRequest,
        SimServer,
    )

    if args.requests == "-":
        raw = json.load(sys.stdin)
    else:
        with open(args.requests) as f:
            raw = json.load(f)
    if not isinstance(raw, list):
        raise SystemExit(
            f"--requests must be a JSON list of request objects, got "
            f"{type(raw).__name__}"
        )
    faults = None
    if args.faults is not None:
        if args.faults == "-" and args.requests == "-":
            raise SystemExit(
                "--requests - and --faults - cannot both read stdin; "
                "put at least one in a file"
            )
    if args.hosts:
        server = _build_cluster(args)
        return _serve_requests(args, server, raw)
    if args.faults is not None:
        try:
            faults = FaultPlan.from_spec(
                json.load(sys.stdin) if args.faults == "-"
                else args.faults
            )
        except (ValueError, OSError, json.JSONDecodeError) as e:
            raise SystemExit(f"--faults: {e}")

    server = SimServer.single_bucket(
        args.composite,
        config=json.loads(args.config),
        capacity=args.capacity,
        lanes=args.lanes,
        window=args.window,
        timestep=args.timestep,
        emit_every=args.emit_every,
        queue_depth=args.queue_depth,
        out_dir=args.out_dir,
        sink="log",
        pipeline=args.pipeline,
        stream_queue=args.stream_queue,
        flush_every=args.flush_every,
        snapshot_budget_mb=args.snapshot_budget_mb,
        host_budget_mb=args.host_budget_mb,
        tier_dir=args.tier_dir,
        check_finite=args.check_finite,
        watchdog_s=args.watchdog,
        sink_errors=args.sink_errors,
        recover_dir=args.recover_dir,
        faults=faults,
        mesh=args.mesh,
        device_watchdog_s=args.device_watchdog,
        trace_dir=args.trace_dir,
        metrics_interval_s=args.metrics_interval,
        result_cache_mb=args.result_cache_mb,
        dedup=args.dedup,
    )
    return _serve_requests(args, server, raw)


def _cmd_frontdoor(args: argparse.Namespace) -> int:
    """Run the HTTP front door until a signal, then drain gracefully:
    stop accepting (503 + Retry-After), finish queued + in-flight
    requests, close streamer/WAL/sinks, write server_meta.json."""
    import threading

    from lens_tpu.frontdoor import FrontDoor
    from lens_tpu.serve import FaultPlan, SimServer

    if args.hosts:
        try:
            server = _build_cluster(args)
        except (ValueError, RuntimeError, TimeoutError) as e:
            raise SystemExit(str(e))
        return _run_frontdoor(args, server)
    faults = None
    if args.faults is not None:
        try:
            faults = FaultPlan.from_spec(
                json.load(sys.stdin) if args.faults == "-"
                else args.faults
            )
        except (ValueError, OSError, json.JSONDecodeError) as e:
            raise SystemExit(f"--faults: {e}")
    try:
        server = SimServer.single_bucket(
            args.composite,
            config=json.loads(args.config),
            capacity=args.capacity,
            lanes=args.lanes,
            window=args.window,
            timestep=args.timestep,
            emit_every=args.emit_every,
            queue_depth=args.queue_depth,
            out_dir=args.out_dir,
            sink="log",
            pipeline=args.pipeline,
            stream_queue=args.stream_queue,
            flush_every=args.flush_every,
            snapshot_budget_mb=args.snapshot_budget_mb,
            host_budget_mb=args.host_budget_mb,
            tier_dir=args.tier_dir,
            check_finite=args.check_finite,
            watchdog_s=args.watchdog,
            sink_errors=args.sink_errors,
            recover_dir=args.recover_dir,
            faults=faults,
            mesh=args.mesh,
            device_watchdog_s=args.device_watchdog,
            trace_dir=args.trace_dir,
            metrics_interval_s=args.metrics_interval,
            result_cache_mb=args.result_cache_mb,
            dedup=args.dedup,
        )
    except ValueError as e:
        raise SystemExit(str(e))
    return _run_frontdoor(args, server)


def _run_frontdoor(args, server) -> int:
    """The front-door CLI's serve loop, shared by the single-host
    SimServer and the --hosts cluster router."""
    import threading

    from lens_tpu.frontdoor import FrontDoor

    try:
        fd = FrontDoor(
            server,
            tenants=args.tenants,
            host=args.host,
            port=args.port,
            warm=args.warm,
        ).start()
    except (ValueError, OSError) as e:
        server.close()
        raise SystemExit(f"frontdoor: {e}")
    with server:
        base = f"http://{args.host}:{fd.port}"
        tenant_note = (
            f"{len(fd.tenants)} tenant(s): "
            f"{', '.join(sorted(fd.tenants))}"
            if args.tenants
            else "open mode (single 'default' tenant; --tenants "
                 "arms multi-tenancy)"
        )
        print(f"front door listening on {base}")
        print(f"tenants: {tenant_note}")
        if args.hosts:
            print(
                f"cluster: {args.hosts} hosts x {args.composite} "
                f"x{args.lanes} lanes (window {args.window}); "
                f"wal/tiers under {args.out_dir}"
            )
        else:
            print(f"bucket:  {args.composite} x{args.lanes} lanes "
                  f"(window {args.window})")
        print(f"results: {server.out_dir}/<request-id>.lens")
        print("endpoints: POST /v1/requests | GET /v1/requests/RID"
              "[/stream] | DELETE /v1/requests/RID | /healthz | "
              "/metrics | /v1/status")
        print(
            f"try:     curl -s {base}/v1/requests -d "
            f"'{{\"seed\": 1, \"horizon\": 8.0}}'"
        )
        stop = threading.Event()
        with _DrainSignals("HTTP requests") as drain:
            while not stop.is_set() and not drain.draining:
                stop.wait(0.2)
            drained = fd.drain(timeout=args.drain_grace)
        snap = server.metrics()
        c = snap["counters"]
        print(
            f"drained: submitted={c['submitted']} "
            f"retired={c['retired']} failed={c['failed']} "
            f"cancelled={c['cancelled']} rejected={c['rejected']}"
        )
        for name, row in sorted(snap.get("tenants", {}).items()):
            print(
                f"tenant {name}: admitted={row['admitted']} "
                f"rejected={row['rejected']} "
                f"throttled={row['throttled']} "
                f"streamed={row['streamed_bytes']}B"
            )
        if not drained:
            print(
                f"drain: grace ({args.drain_grace}s) expired with "
                f"work still in flight; closed anyway",
                file=sys.stderr,
            )
    if args.hosts:
        print(f"meta:    {args.out_dir}/cluster_meta.json "
              f"(+ host<k>/server_meta.json)")
    else:
        print(f"meta:    {args.out_dir}/server_meta.json")
    return 0 if drained else 1


def _cmd_wal(args: argparse.Namespace) -> int:
    """Dump serve WALs human-readably: per-shard files of one server
    merge on the global seq stamp (the scheduler's exact total order);
    a cluster directory dumps every host's WAL in host order — the
    day-one debugging surface for multi-host recovery."""
    import glob
    import os

    from lens_tpu.serve.wal import classify_events, read_events

    target = args.wal
    wals = []
    if os.path.isfile(target) or os.path.exists(
        os.path.join(target, "serve.wal")
    ):
        wals.append((target, read_events(target)))
    else:
        for hw in sorted(
            glob.glob(os.path.join(target, "host*", "wal"))
        ):
            if os.path.exists(os.path.join(hw, "serve.wal")):
                host = os.path.basename(os.path.dirname(hw))
                wals.append((f"{host} ({hw})", read_events(hw)))
    if not wals:
        print(
            f"no serve.wal under {target!r} (expected a --recover-dir "
            f"or a cluster dir with host*/wal/)",
            file=sys.stderr,
        )
        return 2

    def ancestry(events, rid):
        """rid plus its resubmit parent chain (the events worth
        reading when debugging one request)."""
        _, recs, *_ = classify_events(events)
        keep = set()
        walk = rid
        while walk is not None and walk not in keep:
            keep.add(walk)
            walk = (recs.get(walk) or {}).get("parent")
        return keep

    def detail(ev):
        kind = ev.get("event")
        if kind == "server_begin":
            return (
                f"fingerprint={ev.get('fingerprint')} "
                f"buckets={sorted(ev.get('buckets') or {})}"
            )
        if kind == "submit":
            r = ev.get("request") or {}
            bits = [
                f"composite={r.get('composite')}",
                f"seed={r.get('seed', 0)}",
                f"horizon={r.get('horizon')}",
            ]
            if r.get("prefix"):
                bits.append(
                    f"prefix@{dict(r['prefix']).get('horizon')}"
                )
            if r.get("hold_state"):
                bits.append("hold_state")
            if r.get("tenant"):
                bits.append(f"tenant={r['tenant']}")
            return " ".join(bits)
        if kind == "resubmit":
            return (
                f"parent={ev.get('parent')} "
                f"extra_horizon={ev.get('extra_horizon')}"
            )
        if kind == "retire":
            out = f"status={ev.get('status')} steps={ev.get('steps')}"
            if ev.get("error"):
                out += f" error={ev['error']!r}"
            return out
        if kind == "hold":
            return f"spill={ev.get('name')}"
        if kind == "coalesced":
            return f"leader={ev.get('leader')}"
        if kind == "device_quarantined":
            return f"shard={ev.get('shard')} reason={ev.get('reason')}"
        return ""

    if args.as_json:
        out = []
        for label, events in wals:
            if args.rid:
                keep = ancestry(events, args.rid)
                events = [
                    e for e in events
                    if e.get("rid") in keep
                    or e.get("event") == "server_begin"
                ]
            out.append({"wal": label, "events": events})
        print(json.dumps(out, indent=1, default=str))
        return 0
    for label, events in wals:
        keep = ancestry(events, args.rid) if args.rid else None
        print(f"== {label}: {len(events)} event(s)")
        shown = 0
        for ev in events:
            if keep is not None and ev.get("rid") not in keep \
                    and ev.get("event") != "server_begin":
                continue
            seq = ev.get("seq", "-")
            shard = ev.get("shard", "")
            shard_s = f"shard{shard}" if shard != "" else ""
            print(
                f"  {seq!s:>6} {shard_s:<8} "
                f"{ev.get('event', '?'):<20} "
                f"{ev.get('rid') or '-':<14} {detail(ev)}"
            )
            shown += 1
        if args.rid:
            print(f"  ({shown} of {len(events)} events match "
                  f"{args.rid} + ancestry)")
    return 0


def _cmd_cache(args: argparse.Namespace) -> int:
    """Inspect / offline-GC a durable result-cache directory (jax-free:
    the cache is sidecar JSON + framed logs). Accepts the results dir
    itself or any parent the server layouts put it under."""
    import glob
    import os

    from lens_tpu.serve.results import RESULT_META, ResultCache

    target = args.cache
    # accept the dir itself, a --tier-dir/--recover-dir, or a cluster
    # dir (tiers/results) — first layout that holds entries wins
    candidates = [
        target,
        os.path.join(target, "results"),
        os.path.join(target, "tiers", "results"),
    ]
    found = next(
        (
            d for d in candidates
            if os.path.exists(os.path.join(d, RESULT_META))
            or glob.glob(os.path.join(d, "res_*.lens"))
        ),
        None,
    )
    if found is None:
        print(
            f"no result cache under {target!r} (expected a results/ "
            f"dir written by --result-cache-mb)",
            file=sys.stderr,
        )
        return 2
    # fingerprint=None: inspection never serves hits, so it must not
    # refuse a dir whose owning server config we don't know
    cache = ResultCache(found, fingerprint=None)
    evicted: list = []
    if args.max_mb is not None:
        evicted = cache.gc(int(float(args.max_mb) * 2**20))
    rows = cache.entries()
    if args.as_json:
        print(json.dumps(
            {
                "dir": found,
                "entries": rows,
                "total_bytes": cache.total_bytes(),
                "evicted": evicted,
            },
            indent=1, default=str,
        ))
        return 0
    print(
        f"== {found}: {len(rows)} entr{'y' if len(rows) == 1 else 'ies'}, "
        f"{cache.total_bytes() / 2**20:.1f} MiB"
    )
    if rows:
        print(
            f"  {'fingerprint':<16} {'MiB':>8} {'hits':>5} "
            f"{'age':>8} {'idle':>8}  composite@horizon"
        )
    for row in rows:
        age = row["age_s"]
        idle = row["idle_s"]
        print(
            f"  {row['fingerprint'][:16]:<16} "
            f"{row['nbytes'] / 2**20:>8.2f} {row['hits']:>5} "
            f"{'-' if age is None else f'{age:>7.0f}s':>8} "
            f"{'-' if idle is None else f'{idle:>7.0f}s':>8}  "
            f"{row['composite']}@{row['horizon']}"
        )
    if args.max_mb is not None:
        print(
            f"gc --max-mb {args.max_mb:g}: evicted {len(evicted)} "
            f"entr{'y' if len(evicted) == 1 else 'ies'}"
        )
    return 0


def _cmd_trace(args: argparse.Namespace) -> int:
    """Convert a serve span log to Chrome trace-event JSON (jax-free:
    the span log is framed JSON, the converter pure Python)."""
    import os

    from lens_tpu.obs.trace import TRACE_NAME, chrome_trace, read_trace

    path = args.trace
    if os.path.isdir(path):
        path = os.path.join(path, TRACE_NAME)
    if not os.path.exists(path):
        print(
            f"no span log at {path!r} (serve with --trace-dir to "
            f"produce one)",
            file=sys.stderr,
        )
        return 2
    events = read_trace(path)
    out = args.out or os.path.join(os.path.dirname(path), "trace.json")
    rendered = chrome_trace(events)
    with open(out, "w") as f:
        json.dump(rendered, f)
    spans = sum(1 for e in events if e.get("ev") == "span")
    names: dict = {}
    for e in events:
        names[e.get("name")] = names.get(e.get("name"), 0) + 1
    wall = max((e.get("ts", 0.0) + e.get("dur", 0.0) for e in events),
               default=0.0)
    top = ", ".join(
        f"{n}x{c}"
        for n, c in sorted(names.items(), key=lambda kv: -kv[1])[:8]
    )
    print(
        f"{len(events)} events ({spans} spans) over {wall:.3f}s: {top}"
    )
    print(f"chrome trace: {out}")
    print("view: https://ui.perfetto.dev (open trace file)")
    return 0


def _cmd_sweep(args: argparse.Namespace) -> int:
    """Run (or resume) a sweep spec and print its trial table."""
    from lens_tpu.sweep import run_sweep

    if args.spec == "-":
        spec = json.load(sys.stdin)
    else:
        with open(args.spec) as f:
            spec = json.load(f)
    if not isinstance(spec, dict):
        raise SystemExit(
            f"--spec must be a JSON object, got {type(spec).__name__}"
        )
    if args.resume and not args.out_dir:
        # without the ledger directory there is nothing to resume FROM;
        # silently re-running everything in memory is the opposite of
        # what the flag promises
        raise SystemExit(
            "--resume needs --out-dir (the sweep.ledger it resumes "
            "from lives there)"
        )
    if args.save_trajectories:
        if not args.out_dir:
            raise SystemExit("--save-trajectories needs --out-dir")
        spec["save_trajectories"] = True

    progress = None
    if not args.quiet:
        def progress(index, event):
            obj = event.get("objective")
            obj = "-" if obj is None else f"{obj:.6g}"
            print(
                f"trial {index:>4} {event.get('status', '?'):>7} "
                f"objective={obj}",
                flush=True,
            )

    result = run_sweep(
        spec,
        out_dir=args.out_dir,
        resume=args.resume,
        on_trial=progress,
    )
    by_status: dict = {}
    for row in result.table:
        by_status[row["status"]] = by_status.get(row["status"], 0) + 1
    counts = ", ".join(
        f"{k}={v}" for k, v in sorted(by_status.items())
    )
    print(
        f"sweep: {len(result.table)} trials ({counts}) in "
        f"{result.metrics['wall_seconds']:.1f}s "
        f"[{result.metrics['backend']} backend]"
    )
    if result.best is not None:
        print(
            f"best: trial {result.best['trial']} "
            f"objective={result.best['objective']:.6g} "
            f"params={json.dumps(result.best['params'])}"
        )
    if result.path:
        print(f"table:  {result.path}")
        print(f"ledger: {args.out_dir}/sweep.ledger")
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)

    if args.command == "list":
        # imports deferred so `list` stays fast and jax-free paths obvious
        from lens_tpu.emit import EMITTERS
        from lens_tpu.models.composites import composite_registry
        from lens_tpu.processes import process_registry

        print("composites:", ", ".join(sorted(composite_registry)))
        print("processes: ", ", ".join(sorted(process_registry)))
        print("emitters:  ", ", ".join(sorted(EMITTERS)))
        return 0

    if args.command == "analyze":
        import os

        from lens_tpu.analysis import report

        log = args.log
        if os.path.isdir(log):
            log = os.path.join(log, "emit.lens")
        if not os.path.exists(log):
            print(
                f"no emit log at {log!r} (run with --emitter log "
                f"--out-dir <dir> to produce one)",
                file=sys.stderr,
            )
            return 2
        written = report(
            log,
            out_dir=args.out_dir,
            molecule_index=args.molecule,
            dx=args.dx,
            animate=args.animate,
        )
        for name, path in sorted(written.items()):
            print(f"{name}: {path}")
        return 0

    if args.command == "demo":
        from lens_tpu.processes.standalone import demo as run_demo

        out = run_demo(
            args.process,
            total_time=args.time,
            timestep=args.timestep,
            config=json.loads(args.config),
            out_dir=args.out_dir,
            seed=args.seed,
        )
        print(f"plot: {out['plot']}")
        return 0

    if args.command == "serve":
        return _cmd_serve(args)

    if args.command == "frontdoor":
        return _cmd_frontdoor(args)

    if args.command == "trace":
        return _cmd_trace(args)

    if args.command == "wal":
        return _cmd_wal(args)

    if args.command == "cache":
        return _cmd_cache(args)

    if args.command == "cluster-worker":
        from lens_tpu.cluster import run_worker

        return run_worker(args.config)

    if args.command == "sweep":
        return _cmd_sweep(args)

    _validate_run_args(args)

    import contextlib

    from lens_tpu.experiment import Experiment

    trace_dir = args.trace
    trace_ctx = contextlib.nullcontext()
    if trace_dir:
        from lens_tpu.utils.timers import xla_trace

        trace_ctx = xla_trace(trace_dir)

    with Experiment(_experiment_config(args)) as exp, trace_ctx:
        if args.command == "run":
            state = exp.run(verbose=not args.quiet)
        else:
            state = exp.resume(verbose=not args.quiet)
        import jax
        import numpy as np

        alive = int(np.asarray(jax.device_get(exp.n_alive(state))))
        print(f"done: {alive} live cells")
    if trace_dir:
        print(f"trace: {trace_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
