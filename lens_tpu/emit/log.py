"""The record-log format: framed, CRC-checked, npz-payload records.

One emit log file holds a sequence of records; each record is a pytree of
numpy arrays (flattened to ``path.joined/keys -> array``) plus scalar
metadata, encoded as an uncompressed ``.npz`` blob. Framing (written by
the native writer or the Python fallback, byte-identical):

    u32 magic "LENS" | u32 crc32(payload) | u64 payload_len | payload

The first record of a file is the experiment header (``__header__`` key:
experiment id, config JSON, schema). Readers verify magic + CRC per
record and stop cleanly at truncation (a killed run loses at most the
tail record — the reference's MongoDB emitter has the same at-most-one
semantics per row).
"""

from __future__ import annotations

import io
import json
import os
import struct
import threading
import zlib
from typing import Any, Dict, Iterator, List, Mapping, Optional, Tuple

import numpy as np

MAGIC = 0x4C454E53
_FRAME = struct.Struct("<IIQ")  # magic, crc32, payload_len

#: Path separator inside npz keys (state paths can't contain it).
SEP = "/"


def encode_record(record: Mapping[str, Any]) -> bytes:
    """Flatten a nested dict of arrays/scalars into npz payload bytes."""
    flat: Dict[str, np.ndarray] = {}

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, Mapping):
            for key, sub in node.items():
                key = str(key)
                if SEP in key:
                    raise ValueError(
                        f"record key {key!r} contains reserved separator {SEP!r}"
                    )
                walk(f"{prefix}{SEP}{key}" if prefix else key, sub)
        else:
            flat[prefix] = np.asarray(node)

    walk("", record)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    return buf.getvalue()


def decode_record(payload: bytes) -> Dict[str, Any]:
    """Inverse of :func:`encode_record` (nested dict of numpy arrays)."""
    npz = np.load(io.BytesIO(payload), allow_pickle=False)
    out: Dict[str, Any] = {}
    for key in npz.files:
        node = out
        parts = key.split(SEP)
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = npz[key]
    return out


def frame(payload: bytes) -> bytes:
    """Wrap payload bytes in the record frame (magic, crc, length)."""
    return _FRAME.pack(MAGIC, zlib.crc32(payload) & 0xFFFFFFFF, len(payload)) + payload


class FramedWriter:
    """Off-thread framed appender with a batched flush policy.

    The pure-Python peer of the native background writer
    (``lens_tpu/native/emit_writer.cpp``), relocated next to the frame
    format it writes: ``write(payload)`` frames the bytes and enqueues
    them; a daemon thread drains the queue to an append-only file, so
    the caller (the sim step loop, the serve streamer) never blocks on
    disk.

    ``flush_every=k`` flushes the file's user-space buffer after every
    ``k``-th frame — ON THE WRITER THREAD, so callers never pay the
    flush either. ``k=1`` makes every record promptly visible to a
    tailing reader (``tail_records``); larger ``k`` batches the
    syscalls for throughput; ``None`` flushes only on explicit
    :meth:`flush`/:meth:`close`. Whatever the policy, readers only ever
    see whole frames or a torn TAIL frame (appends are sequential), so
    ``tail_records``'s resume contract holds under any cadence.

    :meth:`flush` (explicit) still blocks until everything queued so
    far is on disk — the barrier close/checkpoint paths need.

    ``max_queue_bytes`` bounds the internal queue (the same 256 MiB
    default cap as the native writer): a ``write`` past it BLOCKS
    until the writer thread drains below the cap, so a disk slower
    than the producer throttles the producer instead of growing host
    RAM without bound — the serve pipeline's bounded-memory contract
    leans on this (a blocked append holds its streamer slot, which
    stalls the scheduler through ``stream_queue``).
    """

    def __init__(
        self,
        path: str,
        flush_every: Optional[int] = None,
        max_queue_bytes: int = 256 << 20,
    ):
        if flush_every is not None and flush_every < 1:
            raise ValueError(f"flush_every={flush_every} must be >= 1")
        if max_queue_bytes < 1:
            raise ValueError(
                f"max_queue_bytes={max_queue_bytes} must be >= 1"
            )
        self._file = open(path, "ab")
        self._flush_every = flush_every
        self._max_queue_bytes = int(max_queue_bytes)
        self._queued_bytes = 0
        self._since_flush = 0
        self._queue: List[bytes] = []
        self._cond = threading.Condition()
        self._pending = 0  # queued + currently being written
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._queue or self._stop)
                if not self._queue and self._stop:
                    return
                batch, self._queue = self._queue, []
                # bytes stay counted until WRITTEN: releasing them at
                # take would let the producer queue another full cap
                # while this batch is still in flight (~2x the bound)
            try:
                for chunk in batch:
                    self._file.write(chunk)
                    self._since_flush += 1
                    if (
                        self._flush_every is not None
                        and self._since_flush >= self._flush_every
                    ):
                        self._file.flush()
                        self._since_flush = 0
            except BaseException as e:  # surfaced at the next write/flush
                with self._cond:
                    self._error = e
                    self._pending -= len(batch)
                    self._queued_bytes -= sum(len(c) for c in batch)
                    self._cond.notify_all()
                return
            with self._cond:
                self._pending -= len(batch)
                self._queued_bytes -= sum(len(c) for c in batch)
                self._cond.notify_all()

    def _check(self) -> None:
        if self._error is not None:
            raise self._error
        if self._stop:
            # fail fast: the writer thread is (being) joined — a frame
            # enqueued now would be silently lost and a later flush
            # would wait forever on it
            raise RuntimeError("FramedWriter is closed")

    def write(self, payload: bytes) -> None:
        framed = frame(payload)
        with self._cond:
            self._check()
            # disk backpressure: block (don't buffer without bound)
            # while the writer thread is more than the cap behind
            # _pending == 0 (fully drained AND written) admits a
            # single frame larger than the cap rather than deadlocking
            self._cond.wait_for(
                lambda: self._queued_bytes + len(framed)
                <= self._max_queue_bytes
                or self._pending == 0
                or self._error is not None
                or self._stop
            )
            self._check()
            self._queue.append(framed)
            self._queued_bytes += len(framed)
            self._pending += 1
            self._cond.notify_all()

    def flush(self) -> None:
        """Block until every frame queued so far is written and the
        user-space buffer handed to the OS."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._pending == 0
                or self._error is not None
                or self._stop
            )
            self._check()
        self._file.flush()
        self._since_flush = 0

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        # Always close the fd, and never let a secondary flush/close
        # failure mask the parked writer-thread error (the root cause
        # of whatever went wrong on disk).
        close_error: Optional[BaseException] = None
        try:
            self._file.flush()
        except BaseException as e:
            close_error = e
        finally:
            try:
                self._file.close()
            except BaseException as e:
                close_error = close_error or e
        if self._error is not None:
            raise self._error
        if close_error is not None:
            raise close_error


class JsonFrameLog:
    """Append-only framed-JSON event file: the one crash-safe
    event-log discipline every durable scheduler in the repo shares.

    The sweep trial ledger (``lens_tpu.sweep.ledger``) and the serve
    write-ahead log (``lens_tpu.serve.wal``) both need the same thing:
    a sequence of small JSON events framed with the emit-log record
    frame (magic + CRC + length, so a kill mid-append loses at most the
    torn tail frame), replayed at open, appended durably afterwards.
    This class is that shared layer; the callers own the event
    vocabulary and the replayed state.

    Open semantics: every complete frame is decoded into ``events`` (a
    complete frame with undecodable JSON raises — the file is not an
    event log); a torn tail frame is TRUNCATED before reopening for
    append, so this run's events can never land after torn bytes and
    turn a cleanly-lost tail into corruption on the next replay.

    ``append(event)`` frames + writes + flushes to the OS (a SIGKILL'd
    process loses nothing already appended); ``fsync_every=True``
    (the ledger's policy) additionally fsyncs per append, while
    ``False`` defers the fsync to explicit :meth:`sync` calls (the
    serve WAL's group-commit policy — one fsync per scheduler tick
    covers every append since the last, and because appends are
    sequential a sync always makes a clean PREFIX durable).

    ``buffered=True`` drops even the per-append flush: frames sit in
    the user-space stdio buffer until it fills, :meth:`sync`, or
    :meth:`close` (which flushes). The observability policy — the serve
    span tracer (``lens_tpu.obs.trace``) rides this: a trace must not
    tax the hot path for durability it does not need, a kill loses at
    most the buffered tail, and the framing's truncation tolerance
    makes the survivors readable. Durable logs (the ledger, the WAL)
    must NOT set it.

    ``retain=False`` makes the log WRITE-ONLY: appends are framed to
    disk but not accumulated in ``events`` — without it a long-running
    emitter (the span tracer again) would grow one retained dict per
    event for the process lifetime. ``truncate=True`` starts the file
    fresh instead of replaying + appending (the tracer's policy: a
    trace describes ONE server run; replaying a prior run's events
    into RAM to append after them would be both a leak and a lie).
    Durable replayed logs keep the defaults.
    """

    def __init__(
        self, path: str, fsync_every: bool = True,
        buffered: bool = False, retain: bool = True,
        truncate: bool = False,
    ):
        self.path = path
        self.fsync_every = bool(fsync_every)
        self.buffered = bool(buffered)
        self.retain = bool(retain)
        if self.buffered and self.fsync_every:
            raise ValueError(
                "buffered=True contradicts fsync_every=True: a log "
                "cannot both defer flushes and fsync per append"
            )
        if truncate and os.path.exists(path):
            os.remove(path)
        self.events: List[Dict[str, Any]] = []
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            good = 0  # offset past the last COMPLETE frame
            for payload, end in iter_frames(path, with_offsets=True):
                try:
                    event = json.loads(payload.decode())
                except (UnicodeDecodeError, json.JSONDecodeError) as e:
                    raise ValueError(
                        f"{path}: complete frame with undecodable JSON "
                        f"payload ({e}) — not an event log?"
                    )
                self.events.append(event)
                good = end
            if os.path.getsize(path) > good:
                # kill mid-append left a torn tail frame: drop it NOW,
                # before reopening for append
                with open(path, "r+b") as f:
                    f.truncate(good)
        self._file = open(path, "ab")

    def __len__(self) -> int:
        return len(self.events)

    def append(self, event: Mapping[str, Any]) -> Dict[str, Any]:
        """Frame + write + flush one event (fsync per the policy);
        returns the plain-dict form appended to ``events``."""
        event = dict(event)
        payload = json.dumps(event, sort_keys=True, default=float).encode()
        self._file.write(frame(payload))
        if not self.buffered:
            self._file.flush()
            if self.fsync_every:
                os.fsync(self._file.fileno())
        if self.retain:
            self.events.append(event)
        return event

    def sync(self) -> None:
        """Group commit: fsync everything appended so far."""
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


def iter_frames(
    path: str, with_offsets: bool = False
) -> Iterator[bytes] | Iterator[Tuple[bytes, int]]:
    """Yield each complete frame's raw payload bytes; stop cleanly at EOF
    or a truncated tail frame.

    The payload-agnostic layer of the log format: ``read_records``
    decodes npz payloads on top of it, and the sweep trial ledger
    (``lens_tpu.sweep.ledger``) rides the same framing with JSON
    payloads — one framing/CRC/truncation discipline for every
    append-only file in the repo.

    ``with_offsets=True`` yields ``(payload, end_offset)`` pairs, where
    ``end_offset`` is the file offset just past the frame — what a
    writer REOPENING the file for append must truncate to, so a torn
    tail frame (kill mid-append) can never end up with later appends
    landing after it (which would turn a cleanly-lost tail into
    corruption on the next read).

    Raises ``ValueError`` on corruption that is NOT simple truncation
    (bad magic or CRC mismatch with a complete frame).
    """
    with open(path, "rb") as f:
        offset = 0
        while True:
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                return  # clean EOF / truncated header = lost tail record
            magic, crc, length = _FRAME.unpack(head)
            if magic != MAGIC:
                raise ValueError(
                    f"{path}: bad record magic {magic:#x} at offset "
                    f"{f.tell() - _FRAME.size}"
                )
            payload = f.read(length)
            if len(payload) < length:
                return  # truncated tail record
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise ValueError(f"{path}: CRC mismatch at offset {f.tell()}")
            offset += _FRAME.size + length
            yield (payload, offset) if with_offsets else payload


def read_records(path: str) -> Iterator[Dict[str, Any]]:
    """Yield decoded records; stop cleanly at EOF or a truncated tail.

    Raises ``ValueError`` on corruption that is NOT simple truncation
    (bad magic or CRC mismatch with a complete frame).
    """
    for payload in iter_frames(path):
        yield decode_record(payload)


def tail_frames(
    path: str, offset: int = 0
) -> Tuple[List[bytes], int]:
    """Incremental RAW read: complete frames (header + payload bytes,
    exactly as they sit in the file) past ``offset``, plus the new
    offset to resume from.

    The byte-transparent layer under :func:`tail_records`, and what
    the HTTP front door streams (``lens_tpu.frontdoor.streams``): the
    concatenation of every frame this yields across a request's
    lifetime is BYTE-IDENTICAL to the request's log file — the
    record-stream-over-HTTP == log-file pin rides this. Same
    reader-while-writer contract as :func:`tail_records`: a frame
    whose header or payload has not fully landed is left alone (the
    returned offset stops at the last complete frame), and a complete
    frame with bad magic/CRC raises (corruption, not a writer race).
    """
    if offset < 0:
        raise ValueError(f"offset must be >= 0, got {offset}")
    frames: List[bytes] = []
    with open(path, "rb") as f:
        f.seek(offset)
        good = offset
        while True:
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                return frames, good  # header not fully written yet
            magic, crc, length = _FRAME.unpack(head)
            if magic != MAGIC:
                raise ValueError(
                    f"{path}: bad record magic {magic:#x} at offset {good}"
                )
            payload = f.read(length)
            if len(payload) < length:
                return frames, good  # payload still being appended
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise ValueError(f"{path}: CRC mismatch at offset {good}")
            frames.append(head + payload)
            good += _FRAME.size + length


def tail_records(
    path: str, offset: int = 0
) -> Tuple[List[Dict[str, Any]], int]:
    """Incremental read: complete records past ``offset``, plus the new
    offset to resume from.

    The streaming counterpart of :func:`read_records`, safe against a
    CONCURRENTLY-APPENDING writer (the serve result streamer tails a
    log the scheduler is still writing): a frame whose header or payload
    has not fully landed yet is left alone — the returned ``new_offset``
    stops at the last byte of the last COMPLETE record, so the next call
    resumes exactly there and re-reads the (by then complete) frame.
    Returns ``([], offset)`` when nothing new is readable.

    A complete frame with a bad magic or CRC is real corruption, not a
    race with the writer (records are appended front-to-back, so bytes
    before a complete frame's end are final) — raises ``ValueError``,
    same as :func:`read_records`. Decoded form of :func:`tail_frames`.
    """
    frames, good = tail_frames(path, offset)
    return (
        [decode_record(f[_FRAME.size:]) for f in frames],
        good,
    )


def make_header(experiment_id: str, config: Mapping | None = None) -> Dict:
    """The experiment-header record (first record of every log)."""
    return {
        "__header__": {
            "experiment_id": np.asarray(experiment_id),
            "config_json": np.asarray(json.dumps(dict(config or {}))),
            "format_version": np.asarray(1),
        }
    }


def is_header(record: Mapping) -> bool:
    return "__header__" in record


def make_segment(trajectory: Mapping, times: np.ndarray) -> Dict[str, Any]:
    """A SEGMENT record: one record carrying a whole stacked [T, ...]
    trajectory + its times. Writing a segment is O(leaves) instead of the
    per-step O(T * leaves) — at 100k agents x dense emit the per-step
    Python serialization loop dominated the host path (the device already
    hands the trajectory over stacked; splitting it to re-stack at read
    time was pure overhead)."""
    return {"__segment__": dict(trajectory), "__times__": np.asarray(times)}


def is_segment(record: Mapping) -> bool:
    return "__segment__" in record


def expand_segment(record: Mapping) -> Iterator[Dict[str, Any]]:
    """Per-step records from a segment record (offline read path)."""
    seg = record["__segment__"]
    times = np.asarray(record["__times__"])

    def slice_t(node: Any, t: int) -> Any:
        if isinstance(node, Mapping):
            return {k: slice_t(v, t) for k, v in node.items()}
        return np.asarray(node)[t]

    for t in range(len(times)):
        row = slice_t(seg, t)
        row["__time__"] = times[t]
        yield row


def read_experiment(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Read a whole log: (header dict, list of data records)."""
    header: Dict[str, Any] = {}
    records: List[Dict[str, Any]] = []
    for record in read_records(path):
        if is_header(record):
            if header:
                # FIRST header wins: each resume appends another header
                # (LogEmitter writes one at construction), but the run
                # that CREATED the experiment is the provenance — a
                # resume invocation's config (fresh experiment_id, maybe
                # missing replicate_overrides) must not overwrite it.
                # Later headers are KEPT under "later_headers" so a
                # reused path (a different experiment appended to an old
                # log — user error, but silent) remains inspectable.
                h = record["__header__"]
                header.setdefault("later_headers", []).append(
                    {
                        "experiment_id": str(h["experiment_id"]),
                        "config": json.loads(str(h["config_json"])),
                    }
                )
                continue
            h = record["__header__"]
            header = {
                "experiment_id": str(h["experiment_id"]),
                "config": json.loads(str(h["config_json"])),
                "format_version": int(h["format_version"]),
            }
        elif is_segment(record):
            records.extend(expand_segment(record))
        else:
            records.append(record)
    return header, records


def _pad_stack(arrays: List[np.ndarray]) -> np.ndarray:
    """``np.stack`` that tolerates a growing agent (row) axis.

    Capacity expansion (``Colony.expanded``) doubles the agent dimension
    mid-experiment, so records from different segments may disagree in
    ONE axis: axis 0 for plain records, axis 1 for ensemble records
    (``[R, rows, ...]`` — the replicate count is fixed for a run).
    Shorter records are padded with zeros (``False`` for the alive mask,
    so dead-row masking keeps working); every other axis must agree.
    """
    shapes = {a.shape for a in arrays}
    if len(shapes) == 1:
        return np.stack(arrays)
    ndims = {a.ndim for a in arrays}
    if len(ndims) != 1 or 0 in ndims:
        raise ValueError(
            f"cannot stack records with shapes {sorted(shapes)}: only one "
            f"axis (the agent rows) may vary across segments"
        )
    # the single axis along which shapes differ = the row axis
    varying = {
        ax
        for ax in range(next(iter(ndims)))
        if len({s[ax] for s in shapes}) > 1
    }
    if len(varying) != 1:
        raise ValueError(
            f"cannot stack records with shapes {sorted(shapes)}: only one "
            f"axis (the agent rows) may vary across segments"
        )
    axis = varying.pop()
    n_max = max(a.shape[axis] for a in arrays)
    padded = []
    for a in arrays:
        if a.shape[axis] < n_max:
            width = [(0, 0)] * a.ndim
            width[axis] = (0, n_max - a.shape[axis])
            a = np.pad(a, width)
        padded.append(a)
    return np.stack(padded)


def stack_records(records: List[Mapping]) -> Dict[str, Any]:
    """Stack per-step records into one timeseries tree ([T, ...] leaves).

    Records must share a tree structure (the emitter guarantees this
    within one run segment); the leading agent axis may GROW across
    segments (capacity expansion) — see ``_pad_stack``.
    """
    if not records:
        return {}
    out: Dict[str, Any] = {}

    def walk(node_list: List[Any], target: Dict, key: str) -> None:
        first = node_list[0]
        if isinstance(first, Mapping):
            sub: Dict[str, Any] = {}
            for k in first:
                walk([n[k] for n in node_list], sub, k)
            target[key] = sub
        else:
            target[key] = _pad_stack([np.asarray(n) for n in node_list])

    for k in records[0]:
        walk([r[k] for r in records], out, k)
    return out
