"""Emitters: device -> host -> sink timeseries streaming.

The reference's agents emit timeseries rows to MongoDB keyed by
experiment/agent/time, consumed offline by ``lens/analysis`` scripts
(reconstructed: SURVEY.md §2 "Emitter", §3.5, §5 "Metrics/logging"). The
rebuild keeps the concepts — experiment id, per-step records, offline
analysis — and re-plumbs the transport for TPU:

- the jitted run produces an emit SLICE (schema ``_emit`` paths) already
  stacked on device; the emitter moves it device->host ONCE per segment
  (``jax.device_get`` of the trajectory), not per step per agent;
- the disk sink is an append-only record log (``lens_tpu.emit.log``)
  written by a native C++ background thread (``lens_tpu.native``) so
  serialization/disk never block the step loop; a pure-Python fallback
  writer produces byte-identical files when the toolchain is missing.

Pick an emitter by name via ``get_emitter({"type": "log", ...})`` — the
boot/experiment layer treats emitters as config, like the reference.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Mapping, Optional

import jax
import numpy as np

from lens_tpu.emit.log import (
    FramedWriter,
    encode_record,
    make_header,
    make_segment,
    read_experiment,
    stack_records,
)


class Emitter:
    """Base emitter: receives host-side record dicts, one per emit step."""

    def __init__(self, experiment_id: str | None = None, config: Mapping | None = None):
        self.experiment_id = experiment_id or uuid.uuid4().hex[:12]
        self.config = dict(config or {})

    def emit(self, record: Mapping[str, Any]) -> None:
        raise NotImplementedError

    def _host_trajectory(self, trajectory: Any, times: Any):
        """Shared preamble: device->host transfer + times default.
        Returns ``(host_tree, times)`` or ``None`` for an empty tree."""
        host = jax.device_get(trajectory)
        leaves = jax.tree.leaves(host)
        if not leaves:
            return None
        steps = leaves[0].shape[0]
        times = np.asarray(times) if times is not None else np.arange(steps)
        return host, times

    def emit_trajectory(self, trajectory: Any, times: Any = None) -> None:
        """Emit a device trajectory (leaves [T, ...]) as T records.

        One ``device_get`` for the whole segment; per-step splitting is
        host-side numpy slicing.
        """
        got = self._host_trajectory(trajectory, times)
        if got is None:
            return
        host, times = got
        for t in range(len(times)):
            record = jax.tree.map(lambda x: x[t], host)
            record["__time__"] = times[t]
            self.emit(record)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NullEmitter(Emitter):
    """Discard everything (benchmarks, throwaway runs)."""

    def emit(self, record: Mapping[str, Any]) -> None:
        pass


class RamEmitter(Emitter):
    """Keep records in memory; ``timeseries()`` stacks them for analysis."""

    def __init__(self, experiment_id: str | None = None, config: Mapping | None = None):
        super().__init__(experiment_id, config)
        self.records: List[Dict[str, Any]] = []

    def emit(self, record: Mapping[str, Any]) -> None:
        self.records.append(jax.tree.map(np.asarray, dict(record)))

    def timeseries(self) -> Dict[str, Any]:
        return stack_records(self.records)


class _NativeWriter:
    """ctypes shim over lens_tpu/native/libemit_writer.so."""

    def __init__(self, lib, path: str):
        self._lib = lib
        self._handle = lib.ew_open(path.encode())
        if not self._handle:
            raise OSError(f"native emit writer failed to open {path!r}")

    def write(self, payload: bytes) -> None:
        rc = self._lib.ew_write(self._handle, payload, len(payload))
        if rc != 0:
            raise OSError(
                f"native emit write failed: "
                f"{self._lib.ew_error(self._handle).decode()}"
            )

    def flush(self) -> None:
        if self._lib.ew_flush(self._handle) != 0:
            raise OSError("native emit flush failed")

    def close(self) -> None:
        if self._handle:
            self._lib.ew_close(self._handle)
            self._handle = None


class LogEmitter(Emitter):
    """Append records to a framed record log on disk.

    Uses the native C++ background writer when available; otherwise the
    pure-Python :class:`~lens_tpu.emit.log.FramedWriter` (identical
    bytes). ``path`` defaults to ``out/<experiment_id>.lens``.

    ``flush_every=k`` batches visibility flushes: the file buffer is
    flushed after every ``k``-th record, so a tailing reader
    (``log.tail_records``) sees records at that cadence without the
    writer paying a flush per record. ``None`` (default) flushes only
    on explicit :meth:`flush`/:meth:`close`. On the Python writer the
    batched flush runs on the background thread (never blocks the
    emitter); the native writer has no flush policy hook, so the
    emitter counts records and issues its (queue-draining) flush every
    ``k``-th — still amortized ``k``-fold.
    """

    def __init__(
        self,
        experiment_id: str | None = None,
        config: Mapping | None = None,
        path: str | None = None,
        native: bool = True,
        flush_every: int | None = None,
    ):
        super().__init__(experiment_id, config)
        if flush_every is not None and flush_every < 1:
            raise ValueError(f"flush_every={flush_every} must be >= 1")
        self.path = path or os.path.join("out", f"{self.experiment_id}.lens")
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._writer = None
        self._flush_every = flush_every
        self._since_flush = 0
        if native:
            from lens_tpu.native import emit_writer_lib

            lib = emit_writer_lib()
            if lib is not None:
                self._writer = _NativeWriter(lib, self.path)
        if self._writer is None:
            self._writer = FramedWriter(self.path, flush_every=flush_every)
            self._flush_every = None  # the writer thread owns the policy
        self.native = isinstance(self._writer, _NativeWriter)
        self._writer.write(
            encode_record(make_header(self.experiment_id, self.config))
        )

    def _write(self, payload: bytes) -> None:
        self._writer.write(payload)
        if self._flush_every is not None:
            self._since_flush += 1
            if self._since_flush >= self._flush_every:
                self._writer.flush()
                self._since_flush = 0

    def emit(self, record: Mapping[str, Any]) -> None:
        self._write(encode_record(record))

    def emit_trajectory(self, trajectory: Any, times: Any = None) -> None:
        """Write the whole segment as ONE record (O(leaves), not
        O(T * leaves)): the device hands the trajectory over already
        stacked; per-step splitting is deferred to the offline read path
        (``log.expand_segment``). The bytes still stream through the
        background writer, so the step loop never blocks on disk."""
        got = self._host_trajectory(trajectory, times)
        if got is None:
            return
        host, times = got
        self._write(encode_record(make_segment(host, times)))

    def flush(self) -> None:
        self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


EMITTERS = {
    "null": NullEmitter,
    "ram": RamEmitter,
    "log": LogEmitter,
}


def get_emitter(config: Mapping[str, Any] | None = None) -> Emitter:
    """Emitter from config: ``{"type": "log", "path": ..., ...}``."""
    config = dict(config or {"type": "ram"})
    kind = config.pop("type", "ram")
    if kind not in EMITTERS:
        raise ValueError(f"unknown emitter type {kind!r}; known: {sorted(EMITTERS)}")
    return EMITTERS[kind](**config)


__all__ = [
    "Emitter",
    "FramedWriter",
    "NullEmitter",
    "RamEmitter",
    "LogEmitter",
    "get_emitter",
    "read_experiment",
    "stack_records",
]
