"""Scalar objectives: an emitted trajectory -> one number per trial.

An objective is ``(path, reduction, mode)``: a schema-variable path into
the emitted tree, a reduction collapsing its ``[T, rows, ...]`` leaf to
a scalar, and whether bigger or smaller is better. It composes with
serve's per-request emit specs through :meth:`Objective.emit_paths` —
the sweep driver asks each trial's request to stream ONLY the leaves the
objective reads (plus ``alive`` for live-masked reductions), so a
thousand-trial sweep moves objective-sized traffic, not whole-state
traffic, off the device.

Reductions see the same timeseries trees every other consumer sees
(``SimServer.result`` ram sinks, ``analysis.load`` trees, sliced
ensemble trajectories); the ``__times__``/``__time__`` key carries the
emit times, which is what lets successive-halving score a PARTIAL
trajectory at a rung horizon (``up_to_time``) without touching the
device program.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from lens_tpu.emit.log import SEP
from lens_tpu.utils.dicts import get_path

#: reduction name -> (needs the alive mask, needs the full series)
REDUCTIONS: Dict[str, Tuple[bool, bool]] = {
    "final_live_sum": (True, False),
    "final_live_mean": (True, False),
    "final_sum": (False, False),
    "final_mean": (False, False),
    "final_alive_count": (True, False),
    "mean": (False, True),
    "max": (False, True),
    "min": (False, True),
}

MODES = ("max", "min")


def _times_of(timeseries: Mapping) -> Optional[np.ndarray]:
    """The emit-time vector under either spelling: ``__times__`` (serve
    ram sinks) or ``__time__`` (emit-log read path)."""
    for key in ("__times__", "__time__"):
        if key in timeseries:
            return np.asarray(timeseries[key])
    return None


class Objective:
    """One scalar read off a trajectory, plus its comparison direction.

    path:
        ``/``-joined string or component sequence into the emitted tree
        (e.g. ``"global/mass"`` or ``("global", "mass")``). Ignored by
        ``final_alive_count`` (which reads only the mask) but still
        accepted for uniform specs.
    reduction:
        One of :data:`REDUCTIONS`. ``final_*`` reductions read the last
        emitted frame (``live`` variants weight rows by the colony
        ``alive`` mask — the batch-culture "final live biomass" read);
        ``mean``/``max``/``min`` reduce over every frame and axis.
    mode:
        ``"max"`` or ``"min"`` — which direction the driver's ranking
        (and successive halving's survivor cut) treats as better.
    """

    def __init__(
        self,
        path: str | Sequence[str],
        reduction: str = "final_live_sum",
        mode: str = "max",
    ):
        if reduction not in REDUCTIONS:
            raise ValueError(
                f"unknown reduction {reduction!r}; known: "
                f"{sorted(REDUCTIONS)}"
            )
        if mode not in MODES:
            raise ValueError(
                f"unknown mode {mode!r}; known: {MODES}"
            )
        if isinstance(path, str):
            self.path: Tuple[str, ...] = tuple(
                p for p in path.split(SEP) if p
            )
        else:
            self.path = tuple(str(p) for p in path)
        if not self.path and reduction != "final_alive_count":
            raise ValueError("objective needs a non-empty path")
        self.reduction = reduction
        self.mode = mode

    @classmethod
    def from_spec(cls, spec: Mapping[str, Any] | "Objective") -> "Objective":
        if isinstance(spec, Objective):
            return spec
        if not isinstance(spec, Mapping) or "path" not in spec:
            raise ValueError(
                f"objective spec needs a 'path', got {spec!r}"
            )
        return cls(
            spec["path"],
            reduction=str(spec.get("reduction", "final_live_sum")),
            mode=str(spec.get("mode", "max")),
        )

    def spec(self) -> Dict[str, Any]:
        return {
            "path": SEP.join(self.path),
            "reduction": self.reduction,
            "mode": self.mode,
        }

    # -- emit coupling -------------------------------------------------------

    def emit_paths(self) -> List[str]:
        """The path prefixes a trial's serve request must stream for
        this objective to be computable — the per-request emit filter
        (``ScenarioRequest.emit["paths"]``) that keeps sweep traffic
        objective-sized."""
        needs_alive, _ = REDUCTIONS[self.reduction]
        paths = []
        if self.path:
            paths.append(SEP.join(self.path))
        if needs_alive and "alive" not in paths:
            paths.append("alive")
        return paths

    # -- evaluation ----------------------------------------------------------

    def value(
        self, timeseries: Mapping, up_to_time: Optional[float] = None
    ) -> float:
        """The objective scalar, optionally truncated to emits with
        ``time <= up_to_time`` — how halving scores a still-running
        trial at a rung horizon from its streamed prefix."""
        needs_alive, _ = REDUCTIONS[self.reduction]
        times = _times_of(timeseries)
        if up_to_time is not None:
            if times is None:
                raise ValueError(
                    "up_to_time needs a __times__/__time__ key in the "
                    "trajectory"
                )
            keep = times <= float(up_to_time) * (1.0 + 1e-9)
            n = int(np.count_nonzero(keep))
        else:
            n = None  # all rows

        def rows(leaf) -> np.ndarray:
            arr = np.asarray(leaf)
            return arr if n is None else arr[:n]

        if self.reduction == "final_alive_count":
            alive = rows(timeseries["alive"])
            self._require_rows(alive)
            return float(np.asarray(alive[-1], dtype=np.float64).sum())

        leaf = rows(get_path(timeseries, self.path))
        self._require_rows(leaf)
        if self.reduction in ("mean", "max", "min"):
            return float(getattr(np, self.reduction)(leaf))
        last = leaf[-1]
        if needs_alive:
            alive = np.asarray(rows(timeseries["alive"])[-1], bool)
            # alive is [rows]; broadcast across any trailing leaf axes
            mask = alive.reshape(
                alive.shape + (1,) * (last.ndim - alive.ndim)
            )
            masked = np.where(mask, last, 0.0)
            if self.reduction == "final_live_sum":
                return float(masked.sum())
            live = max(int(alive.sum()), 1) * max(
                int(np.prod(last.shape[alive.ndim:], dtype=int)), 1
            )
            return float(masked.sum() / live)
        if self.reduction == "final_sum":
            return float(np.asarray(last, dtype=np.float64).sum())
        return float(np.asarray(last, dtype=np.float64).mean())

    @staticmethod
    def _require_rows(arr: np.ndarray) -> None:
        if arr.shape[0] == 0:
            raise ValueError(
                "trajectory has no emitted rows in range — horizon "
                "shorter than one emit interval, or truncation before "
                "the first emit"
            )

    # -- comparison ----------------------------------------------------------

    def better(self, a: float, b: float) -> bool:
        """True when ``a`` beats ``b`` under this objective's mode."""
        return a > b if self.mode == "max" else a < b

    def rank(self, values: Mapping[int, float]) -> List[int]:
        """Trial indices best-first; ties break toward the LOWER trial
        index so rankings (and halving cuts) are deterministic."""
        sign = -1.0 if self.mode == "max" else 1.0
        return sorted(values, key=lambda i: (sign * values[i], i))
