"""Search spaces: a declarative spec -> a deterministic trial list.

The reference ran a parameter study as one submitted experiment cluster
per parameter point (SURVEY.md §3.3); the sweep subsystem's first job is
to make the *trial set itself* a pure function of the spec, so every
layer above it (driver scheduling, the crash-resume ledger, analysis)
can identify a trial by its index alone:

- trial parameters AND the per-trial simulation seed are derived
  deterministically from ``(sweep_seed, trial_index)`` via
  ``np.random.SeedSequence`` — the same spec + seed always enumerates
  the same trials, on any host, in any order, resumed or not;
- for the random space each trial's draw depends ONLY on its own
  ``(sweep_seed, index)`` pair, so growing ``n_trials`` extends the
  trial list without disturbing existing trials (the resume ledger
  stays valid under a widened sweep);
- the Latin hypercube is a whole-design object (its stratification
  couples trials by construction), so its generator is seeded from
  ``(sweep_seed, n_trials)`` — same spec, same design.

A trial's ``params`` map ``/``-joined schema-variable paths to values;
``overrides()`` nests them into the tree shape shared by
``Colony.initial_state(overrides=...)``, ``Ensemble.initial_state(
replicate_overrides=...)`` (via :func:`stack_overrides`) and serve's
``ScenarioRequest.overrides`` — one override language across the
one-shot, dense-grid, and served paths.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Tuple

import numpy as np

from lens_tpu.emit.log import SEP
from lens_tpu.utils.dicts import set_path

#: Spec keys recognized per parameter.
_GRID_KEYS = {"grid"}
_DIST_KEYS = {"low", "high", "scale"}


def trial_seed(sweep_seed: int, index: int) -> int:
    """The per-trial simulation seed: one 31-bit word from the
    ``(sweep_seed, trial_index)`` SeedSequence. Positive so it survives
    JSON/CLI round-trips that assume ordinary ints."""
    word = np.random.SeedSequence(
        [int(sweep_seed), int(index)]
    ).generate_state(1)[0]
    return int(word) & 0x7FFFFFFF


@dataclass(frozen=True)
class Trial:
    """One point of a sweep: immutable, identified by ``index``."""

    index: int
    seed: int
    params: Mapping[str, Any] = field(default_factory=dict)

    def overrides(self) -> Dict[str, Any]:
        """The nested override tree (``a/b`` keys split on the emit-log
        separator) the sim layers consume."""
        tree: Dict[str, Any] = {}
        for joined, value in self.params.items():
            tree = set_path(tree, tuple(str(joined).split(SEP)), value)
        return tree


def _scaled(u: np.ndarray | float, low: float, high: float, scale: str):
    if scale == "linear":
        return low + u * (high - low)
    if scale == "log":
        if low <= 0 or high <= 0:
            raise ValueError(
                f"log scale needs positive bounds, got [{low}, {high}]"
            )
        return float(np.exp(np.log(low) + u * (np.log(high) - np.log(low))))
    raise ValueError(f"unknown scale {scale!r}; known: linear, log")


def _check_bounds(path: str, spec: Mapping) -> Tuple[float, float, str]:
    try:
        low, high = float(spec["low"]), float(spec["high"])
    except KeyError as e:
        raise ValueError(
            f"parameter {path!r} needs low/high bounds, got {dict(spec)}"
        ) from e
    if not high > low:
        raise ValueError(
            f"parameter {path!r}: high ({high}) must exceed low ({low})"
        )
    return low, high, str(spec.get("scale", "linear"))


class GridSpace:
    """The explicit cartesian grid: every combination of every
    parameter's listed values, enumerated row-major in parameter
    insertion order (first parameter slowest). ``n_trials`` is the
    product — dense and finite, the shape the direct-ensemble backend
    eats whole."""

    kind = "grid"

    def __init__(self, params: Mapping[str, Mapping]):
        if not params:
            raise ValueError("grid space needs at least one parameter")
        self.axes: Dict[str, List[Any]] = {}
        for path, spec in params.items():
            values = spec.get("grid") if isinstance(spec, Mapping) else spec
            if values is None or not len(values):
                raise ValueError(
                    f"grid parameter {path!r} needs a non-empty "
                    f"'grid' list, got {spec!r}"
                )
            self.axes[str(path)] = [
                v if isinstance(v, (int, float)) else float(v)
                for v in values
            ]
        self.n_trials = math.prod(len(v) for v in self.axes.values())

    def trials(self, sweep_seed: int) -> List[Trial]:
        out = []
        for i, combo in enumerate(
            itertools.product(*self.axes.values())
        ):
            params = dict(zip(self.axes.keys(), combo))
            out.append(
                Trial(index=i, seed=trial_seed(sweep_seed, i), params=params)
            )
        return out


class RandomSpace:
    """Independent log/linear-uniform draws per trial. Each trial's
    parameter vector comes from the ``(sweep_seed, trial_index)``
    stream alone, so trial ``i`` is the same whether the sweep asks for
    8 trials or 800."""

    kind = "random"

    def __init__(self, params: Mapping[str, Mapping], n_trials: int):
        if n_trials < 1:
            raise ValueError(f"n_trials={n_trials} must be >= 1")
        self.bounds = {
            str(p): _check_bounds(str(p), spec)
            for p, spec in params.items()
        }
        self.n_trials = int(n_trials)

    def trials(self, sweep_seed: int) -> List[Trial]:
        out = []
        for i in range(self.n_trials):
            # sub-stream 1: parameter draws; the bare (seed, i) stream
            # is the sim seed (trial_seed) — kept disjoint so adding a
            # parameter never perturbs the sim seeds
            rng = np.random.default_rng(
                np.random.SeedSequence([int(sweep_seed), i, 1])
            )
            params = {
                p: _scaled(float(rng.random()), lo, hi, scale)
                for p, (lo, hi, scale) in self.bounds.items()
            }
            out.append(
                Trial(index=i, seed=trial_seed(sweep_seed, i), params=params)
            )
        return out


class LatinHypercubeSpace:
    """Latin hypercube: ``n_trials`` strata per dimension, one sample
    per stratum per dimension, strata assigned by an independent
    permutation per dimension — space-filling where pure random
    clumps. The design is a whole-sweep object (the permutations
    couple trials), so it is seeded from ``(sweep_seed, n_trials)``."""

    kind = "lhs"

    def __init__(self, params: Mapping[str, Mapping], n_trials: int):
        if n_trials < 1:
            raise ValueError(f"n_trials={n_trials} must be >= 1")
        self.bounds = {
            str(p): _check_bounds(str(p), spec)
            for p, spec in params.items()
        }
        self.n_trials = int(n_trials)

    def trials(self, sweep_seed: int) -> List[Trial]:
        n = self.n_trials
        rng = np.random.default_rng(
            np.random.SeedSequence([int(sweep_seed), n, 2])
        )
        columns = {}
        for p, (lo, hi, scale) in self.bounds.items():
            strata = rng.permutation(n)
            jitter = rng.random(n)
            u = (strata + jitter) / n
            columns[p] = [_scaled(float(x), lo, hi, scale) for x in u]
        return [
            Trial(
                index=i,
                seed=trial_seed(sweep_seed, i),
                params={p: columns[p][i] for p in columns},
            )
            for i in range(n)
        ]


def space_from_spec(spec: Mapping[str, Any]):
    """``{"kind": ..., "params": {...}, ["n_trials": N]}`` -> a space.

    ``kind`` defaults to ``grid``. Grid specs take
    ``{path: {"grid": [...]}}`` entries; random/lhs take
    ``{path: {"low": a, "high": b, "scale": "linear"|"log"}}`` plus a
    top-level ``n_trials``.
    """
    if not isinstance(spec, Mapping) or "params" not in spec:
        raise ValueError(
            f"space spec needs a 'params' mapping, got {spec!r}"
        )
    kind = str(spec.get("kind", "grid"))
    params = spec["params"]
    if kind == "grid":
        return GridSpace(params)
    n_trials = spec.get("n_trials")
    if n_trials is None:
        raise ValueError(f"{kind} space needs an explicit n_trials")
    if kind == "random":
        return RandomSpace(params, int(n_trials))
    if kind == "lhs":
        return LatinHypercubeSpace(params, int(n_trials))
    raise ValueError(
        f"unknown space kind {kind!r}; known: grid, random, lhs"
    )


def stack_overrides(trials: List[Trial]) -> Dict[str, Any]:
    """Trials -> one ``replicate_overrides`` tree: each parameter
    becomes a leaf with a leading ``[len(trials)]`` axis, in trial
    order — the shape ``Ensemble.initial_state`` scans over. All trials
    must share one parameter set (spaces guarantee it)."""
    if not trials:
        raise ValueError("no trials to stack")
    paths = list(trials[0].params.keys())
    for t in trials:
        if list(t.params.keys()) != paths:
            raise ValueError(
                f"trial {t.index} has parameters "
                f"{sorted(t.params)} != {sorted(paths)}"
            )
    tree: Dict[str, Any] = {}
    for p in paths:
        tree = set_path(
            tree,
            tuple(p.split(SEP)),
            np.asarray([t.params[p] for t in trials]),
        )
    return tree
