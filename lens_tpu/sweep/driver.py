"""The sweep driver: a declarative spec -> a scored, resumable trial table.

Two backends over one scheduling contract:

- **server** (:class:`_ServerSweep`): trials ride a
  :class:`~lens_tpu.serve.SimServer` as ordinary scenario requests —
  per-trial seed, overrides, horizon, and an emit spec narrowed to what
  the objective reads — with bounded in-flight concurrency. Trials
  inherit serve's co-batching determinism: a trial's trajectory (and so
  its objective) is BITWISE what a solo request with the same
  seed/overrides would produce, regardless of which other trials share
  the lanes or how the sweep is scheduled/resumed.
- **ensemble** (:class:`_EnsembleSweep`): dense grids skip the
  scheduler entirely — trials are packed into fixed-size chunks on the
  replicate axis of an :class:`~lens_tpu.colony.ensemble.Ensemble`, one
  compiled program per chunk size, per-trial PRNG keys derived from
  ``(sweep_seed, trial_index)`` via the explicit ``keys=`` hook. The
  chunk partition is a pure function of the trial list, so a resumed
  sweep re-runs each unfinished chunk with its original composition and
  reproduces the same bits.

Early stopping is successive halving (the ASHA family): rung horizons
``min_horizon * eta^r``, at each rung keep the top ``1/eta`` of
survivors and stop the rest. Survivors are EXTENDED, never rerun —
each rung's request asks ``hold_state=True``, and promotion is a
``SimServer.resubmit`` that re-arms the held lane state for the next
rung's extra steps (bitwise a longer original request; losers'
objectives are scored from the trajectory prefix they already
streamed). The wasted work of a classical restart-per-rung
implementation (re-simulating every survivor's prefix eta times) never
happens.

Crash safety is the ledger's (``lens_tpu.sweep.ledger``): every
terminal fact is fsynced before the driver acts on it, resume replays
the ledger and re-runs only trials without terminal events, and the
final table of a killed-and-resumed sweep is identical — objective
values bitwise — to an uninterrupted run's.
"""

from __future__ import annotations

import math
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from lens_tpu.obs.trace import SWEEP_TRACK
from lens_tpu.sweep.ledger import (
    LEDGER_NAME,
    TABLE_NAME,
    TRIAL_DONE,
    TRIAL_RUNG,
    TRIAL_STOPPED,
    MemoryLedger,
    TrialLedger,
    spec_fingerprint,
    write_table,
)
from lens_tpu.sweep.objective import Objective
from lens_tpu.sweep.space import Trial, space_from_spec, stack_overrides

#: statuses a trial row can carry in the result table
DONE_S, STOPPED_S, FAILED_S, PENDING_S = "done", "stopped", "failed", "pending"

_SPEC_KEYS = {
    "composite", "config", "space", "seed", "horizon", "objective",
    "backend", "asha", "n_agents", "capacity", "timestep", "emit_every",
    "save_trajectories", "warmup",
}

#: keys a spec's ``warmup`` block may carry
_WARMUP_KEYS = {"horizon", "overrides", "seed"}


@dataclass
class SweepSpec:
    """The declarative sweep description (see docs/sweeps.md).

    ``backend`` carries scheduling knobs only (``kind`` plus lanes /
    window / queue_depth / max_in_flight for the server backend,
    ``batch`` for the ensemble backend); everything that shapes the
    simulation or the trial set is a top-level field and part of the
    resume fingerprint.

    ``warmup`` (server backend only) declares a SHARED scenario prefix
    for every trial: ``{"horizon": h, "overrides": {...}, "seed": s}``.
    The warmup scenario — seed ``s`` (default: the sweep seed) plus the
    shared overrides — is simulated ONCE per server via serve's
    content-addressed snapshot store, and every trial (and every ASHA
    first-rung request) forks the warmed device-resident state, running
    only ``horizon - h`` suffix seconds with its own divergent
    parameters applied at the fork point (docs/sweeps.md, "Shared
    warmup"). Trials therefore share the warmup's PRNG stream — the
    counterfactual what-if-at-t semantics, not independent replicates.
    """

    composite: str
    space: Mapping[str, Any]
    horizon: float
    objective: Mapping[str, Any]
    config: Mapping[str, Any] = field(default_factory=dict)
    seed: int = 0
    backend: Mapping[str, Any] = field(default_factory=dict)
    asha: Optional[Mapping[str, Any]] = None
    n_agents: Any = 1
    capacity: Optional[int] = None
    timestep: float = 1.0
    emit_every: int = 1
    save_trajectories: bool = False
    warmup: Optional[Mapping[str, Any]] = None

    @classmethod
    def from_mapping(cls, spec: Mapping[str, Any] | "SweepSpec") -> "SweepSpec":
        if isinstance(spec, SweepSpec):
            return spec
        unknown = set(spec) - _SPEC_KEYS
        if unknown:
            raise ValueError(
                f"unknown sweep spec keys {sorted(unknown)}; known: "
                f"{sorted(_SPEC_KEYS)}"
            )
        missing = [
            k for k in ("composite", "space", "horizon", "objective")
            if k not in spec
        ]
        if missing:
            raise ValueError(f"sweep spec is missing {missing}")
        return cls(**dict(spec))

    @property
    def kind(self) -> str:
        return str((self.backend or {}).get("kind", "server"))

    def canonical(self) -> Dict[str, Any]:
        """The fields that determine the trial set and its scoring —
        the resume fingerprint. Scheduling knobs (lanes, window,
        queue depth, in-flight bound) are deliberately absent: serve's
        co-batching determinism makes results independent of them. The
        ensemble chunk size IS included — it fixes chunk composition,
        the unit of bitwise reproducibility for that backend.

        The space's ``params`` mapping is rendered as an ORDERED list
        of ``[path, spec]`` pairs: trial enumeration (grid product
        order, per-param draw order) follows parameter insertion
        order, so a spec whose params were merely re-keyed in a
        different order is a DIFFERENT sweep and must not pass the
        resume fingerprint (``sort_keys`` canonicalization would
        otherwise erase exactly the order that matters)."""
        space = dict(self.space)
        if isinstance(space.get("params"), Mapping):
            space["params"] = [
                [str(path), dict(p) if isinstance(p, Mapping) else p]
                for path, p in space["params"].items()
            ]
        out = {
            "composite": self.composite,
            "config": dict(self.config or {}),
            "space": space,
            "seed": int(self.seed),
            "horizon": float(self.horizon),
            "objective": Objective.from_spec(self.objective).spec(),
            "n_agents": self.n_agents,
            "capacity": self.capacity,
            "timestep": float(self.timestep),
            "emit_every": int(self.emit_every),
            "asha": dict(self.asha) if self.asha else None,
            "backend_kind": self.kind,
        }
        if self.warmup:
            # only present when set: a warmup-less spec must keep the
            # fingerprint its pre-round-11 ledger was begun with
            out["warmup"] = dict(self.warmup)
        if self.kind == "ensemble":
            out["batch"] = (self.backend or {}).get("batch")
        return out


@dataclass
class SweepResult:
    """What a sweep run hands back: the per-trial table (trial order),
    the best full-horizon trial, backend/server metrics, per-trial
    timeseries (emitted paths only; absent for trials finished in a
    PREVIOUS run — their objectives replay from the ledger but their
    trajectories were not re-simulated), and the written table path."""

    table: List[Dict[str, Any]]
    best: Optional[Dict[str, Any]]
    metrics: Dict[str, Any]
    timeseries: Dict[int, Dict[str, Any]]
    path: Optional[str] = None


def rung_steps(
    min_steps: int, eta: int, max_steps: int, emit_every: int
) -> List[int]:
    """Successive-halving rung horizons in steps: geometric in ``eta``
    from ``min_steps``, each snapped UP to the emit grid, capped and
    terminated at ``max_steps`` (always the last rung)."""
    if eta < 2:
        raise ValueError(f"eta={eta} must be >= 2")
    if min_steps < 1:
        raise ValueError(f"min_horizon must be >= one step")
    rungs: List[int] = []
    s = float(min_steps)
    while True:
        snapped = max(emit_every, int(math.ceil(s / emit_every)) * emit_every)
        if snapped >= max_steps:
            break
        if not rungs or snapped > rungs[-1]:
            rungs.append(snapped)
        s *= eta
    rungs.append(int(max_steps))
    return rungs


def _concat_ts(parts: List[Mapping]) -> Dict[str, Any]:
    """Stitch continuation segments ([T_i, ...] trees sharing one
    structure) into one timeseries along the time axis."""
    if len(parts) == 1:
        return dict(parts[0])
    import jax

    return jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *parts
    )


class _ServerSweep:
    """Drive trials through a SimServer with bounded in-flight
    concurrency; optionally successive-halving with hold-state
    extension."""

    def __init__(
        self,
        spec: SweepSpec,
        trials: List[Trial],
        objective: Objective,
        ledger: TrialLedger,
        server=None,
    ):
        from lens_tpu.serve import SimServer

        self.spec = spec
        self.trials = {t.index: t for t in trials}
        self.order = [t.index for t in trials]
        self.objective = objective
        self.ledger = ledger
        backend = dict(spec.backend or {})
        backend.pop("kind", None)
        self.max_in_flight = backend.pop("max_in_flight", None)
        # speculative warming (round 16): the driver KNOWS the first
        # thing every trial will need — the shared warmup prefix — so
        # `"warm": true` pre-launches it via SimServer.prewarm before
        # the first trial submits. A scheduling knob: it changes when
        # the prefix runs, never any trial's bits (and so stays out of
        # the resume fingerprint, like lanes/window).
        self.warm = bool(backend.pop("warm", False))
        self.owns_server = server is None
        if server is None:
            # a driver-owned store needs a finite budget: released
            # halving losers become evictable cache, and unbounded
            # they would stay device-resident until close (~n_trials x
            # state bytes). LRU keeps the hot warmup snapshot; an
            # evicted one falls back to a bitwise-equal prefix re-run.
            backend.setdefault("snapshot_budget_mb", 256)
            server = SimServer.single_bucket(
                spec.composite,
                config=dict(spec.config or {}),
                capacity=spec.capacity,
                n_agents=spec.n_agents,
                timestep=spec.timestep,
                emit_every=spec.emit_every,
                **backend,
            )
        if spec.composite not in server.buckets:
            raise ValueError(
                f"server has no bucket for composite "
                f"{spec.composite!r}; configured: "
                f"{sorted(server.buckets)}"
            )
        self.server = server
        pool = server.buckets[spec.composite].pool
        self.dt = pool.timestep
        self.emit_every = pool.emit_every
        if self.max_in_flight is None:
            self.max_in_flight = 2 * pool.n_lanes
        emit_paths = objective.emit_paths()
        self.emit_spec = {"paths": emit_paths} if emit_paths else None
        # QueueFull backoff jitter: seeded from the sweep seed so a
        # replayed sweep's sleep schedule (never its BITS — sleeps
        # cannot touch results) is reproducible too
        self._backoff_rng = np.random.default_rng(
            np.random.SeedSequence([int(spec.seed), 0xB0FF])
        )
        # per-trial spans (docs/observability.md): when the server is
        # tracing (trace_dir — inherited through the backend dict like
        # every other serve knob), each trial becomes an async span
        # from its first submit to its terminal ledger event, plus a
        # rung instant per ASHA promotion cut — so a sweep's timeline
        # shows trials racing across lanes, not just requests.
        self.trace = getattr(server, "trace", None)
        self._trial_t0: Dict[int, float] = {}
        self.warmup = (
            dict(spec.warmup) if spec.warmup is not None else None
        )
        if self.warmup is not None:
            unknown = set(self.warmup) - _WARMUP_KEYS
            if unknown:
                raise ValueError(
                    f"unknown warmup keys {sorted(unknown)}; known: "
                    f"{sorted(_WARMUP_KEYS)}"
                )
            if "horizon" not in self.warmup:
                raise ValueError("warmup needs a 'horizon'")
            warm_h = float(self.warmup["horizon"])
            if warm_h >= float(spec.horizon):
                raise ValueError(
                    f"warmup horizon ({warm_h}) must be shorter than "
                    f"the sweep horizon ({spec.horizon})"
                )
            min_h = (spec.asha or {}).get("min_horizon")
            if min_h is not None and warm_h >= float(min_h):
                raise ValueError(
                    f"warmup horizon ({warm_h}) must be shorter than "
                    f"the first asha rung (min_horizon={min_h}) — the "
                    f"rung's suffix needs at least one step"
                )

    # -- plumbing ------------------------------------------------------------

    def _retrying(self, attempt: Callable[[], str]) -> str:
        """Submit with honest client-side backpressure handling: the
        first retry just ticks (this driver IS the server's driver, so
        ticking drains our own backlog — sleeping first would only
        idle the device); past that, capped exponential backoff with
        seeded jitter, never sleeping longer than the server's
        occupancy-derived ``retry_after`` hint (the hint is an
        estimate of when space opens — sleeping past it wastes wall,
        sleeping a jittered fraction of it avoids every client
        retrying in lockstep). The remote-client policy, documented
        in docs/serving.md "Backpressure & backoff"."""
        from lens_tpu.serve import QueueFull

        attempts = 0
        while True:
            try:
                return attempt()
            except QueueFull as e:
                self.server.tick()
                attempts += 1
                if attempts < 2:
                    continue  # a tick freed a lane most of the time
                delay = min(0.01 * 2 ** (attempts - 2), 1.0)
                delay *= 0.5 + self._backoff_rng.uniform(0.0, 1.0)
                if e.retry_after > 0:
                    delay = min(delay, e.retry_after)
                time.sleep(delay)

    def _submit(self, request) -> str:
        return self._retrying(lambda: self.server.submit(request))

    def _resubmit(self, rid: str, extra_horizon: float) -> str:
        return self._retrying(
            lambda: self.server.resubmit(rid, extra_horizon)
        )

    def _request(self, trial: Trial, horizon: float, hold: bool):
        from lens_tpu.serve import ScenarioRequest

        if self.warmup is None:
            return ScenarioRequest(
                composite=self.spec.composite,
                seed=trial.seed,
                horizon=horizon,
                overrides=trial.overrides(),
                emit=self.emit_spec,
                hold_state=hold,
            )
        # shared-warmup trial: every trial declares the SAME prefix —
        # warmup seed + shared overrides to the warmup horizon — so the
        # server simulates it once and forks it per trial, applying the
        # trial's divergent params at the fork point. The trial's own
        # per-index seed is deliberately unused: the suffix continues
        # the warmed state's PRNG stream (what-if-at-t semantics).
        prefix: Dict[str, Any] = {
            "horizon": float(self.warmup["horizon"])
        }
        if self.warmup.get("overrides"):
            prefix["overrides"] = self.warmup["overrides"]
        return ScenarioRequest(
            composite=self.spec.composite,
            seed=int(self.warmup.get("seed", self.spec.seed)),
            horizon=horizon,
            overrides=trial.overrides(),
            emit=self.emit_spec,
            hold_state=hold,
            prefix=prefix,
        )

    def _trial_submitted(self, index: int) -> None:
        """Span mark: a trial's FIRST leg just submitted (rung
        promotions keep the original start — the span is the trial's
        whole life, not one leg's)."""
        if self.trace and index not in self._trial_t0:
            self._trial_t0[index] = time.perf_counter()

    def _record_done(self, index, objective, status, steps, on_trial):
        if self.ledger.terminal(index):
            return  # replay idempotence: never double-record a trial
        event = {
            "event": TRIAL_DONE,
            "trial": index,
            "seed": self.trials[index].seed,
            "objective": objective,
            "status": status,
            "steps": steps,
        }
        self.ledger.append(event)
        if self.trace:
            now = time.perf_counter()
            self.trace.emit_span(
                "trial", self._trial_t0.pop(index, now), now,
                track=SWEEP_TRACK, aid=f"trial-{index}",
                trial=index, status=status, objective=objective,
                steps=steps,
            )
        if on_trial is not None:
            on_trial(index, event)

    def run(self, on_trial=None) -> Tuple[Dict[int, Dict], Dict[str, Any]]:
        if self.warm and self.warmup is not None:
            # prewarm the shared warmup prefix: the first trial
            # submits moments later and COALESCES onto the warm run
            # (a speculative hit) instead of paying the miss on its
            # own latency path. n_agents deliberately None — trials
            # submit with None too, so the content addresses match.
            self.server.prewarm(
                composite=self.spec.composite,
                seed=int(self.warmup.get("seed", self.spec.seed)),
                horizon=float(self.warmup["horizon"]),
                overrides=self.warmup.get("overrides") or {},
            )
        if self.spec.asha:
            ts = self._run_halving(on_trial)
        else:
            ts = self._run_race(on_trial)
        return ts, {"backend": "server", "server": self.server.metrics()}

    def close(self) -> None:
        if self.owns_server:
            self.server.close()

    # -- race: every trial to the full horizon -------------------------------

    def _run_race(self, on_trial) -> Dict[int, Dict]:
        from lens_tpu.serve import CANCELLED, DONE, FAILED, TIMEOUT

        pending = [
            self.trials[i] for i in self.order
            if not self.ledger.terminal(i)
        ]
        inflight: Dict[str, Trial] = {}
        ts_by_trial: Dict[int, Dict] = {}
        k = 0
        while k < len(pending) or inflight:
            while k < len(pending) and len(inflight) < self.max_in_flight:
                t = pending[k]
                rid = self._submit(
                    self._request(t, self.spec.horizon, hold=False)
                )
                self._trial_submitted(t.index)
                inflight[rid] = t
                k += 1
            self.server.tick()
            for rid, t in list(inflight.items()):
                status = self.server.status(rid)["status"]
                if status == DONE:
                    ts = self.server.result(rid)
                    ts_by_trial[t.index] = ts
                    del inflight[rid]
                    self._record_done(
                        t.index,
                        self.objective.value(ts),
                        DONE_S,
                        self.server.status(rid)["steps_done"],
                        on_trial,
                    )
                elif status in (FAILED, TIMEOUT, CANCELLED):
                    del inflight[rid]
                    self._record_done(t.index, None, FAILED_S, 0, on_trial)
        return ts_by_trial

    # -- successive halving --------------------------------------------------

    def _run_halving(self, on_trial) -> Dict[int, Dict]:
        from lens_tpu.serve import CANCELLED, DONE, FAILED, TIMEOUT

        asha = dict(self.spec.asha)
        eta = int(asha.get("eta", 3))
        min_h = asha.get("min_horizon")
        if min_h is None:
            raise ValueError("asha spec needs min_horizon")
        max_steps = int(round(float(self.spec.horizon) / self.dt))
        rungs = rung_steps(
            int(round(float(min_h) / self.dt)),
            eta,
            max_steps,
            self.emit_every,
        )
        ledger = self.ledger
        rid_of: Dict[int, str] = {}
        # trials whose CURRENT chain leg is queued/running — maintained
        # explicitly (add on submit/resubmit, drop when the leg is
        # observed terminal) so the in-flight bound costs O(1) instead
        # of a status() poll over every rid ever created
        in_flight: set = set()
        segments: Dict[int, List[Mapping]] = {}
        scored: Dict[int, str] = {}  # rid whose result is already stitched
        ts_by_trial: Dict[int, Dict] = {}

        def participants(r: int) -> List[int]:
            """Trials ranked at rung ``r``: everything not stopped at an
            EARLIER rung and not failed. Trials already stopped AT rung
            ``r`` (a resume replaying a half-recorded cut) stay in, so
            the recomputed cut sees the original cohort size and
            re-derives the original decision; trials finished in a
            previous run stay in so the original winner can win again."""
            out = []
            for i in self.order:
                stop = ledger.stopped.get(i)
                if stop is not None and int(stop.get("rung", -1)) < r:
                    continue
                done = ledger.done.get(i)
                if done is not None and done.get("objective") is None:
                    continue  # failed trials are never ranked
                out.append(i)
            return out

        for r, steps_r in enumerate(rungs):
            t_r = steps_r * self.dt
            # drive every participant that still needs to REACH rung r
            # by simulation (finished-in-ledger trials replay their
            # recorded rung values instead)
            while True:
                need = [
                    i for i in participants(r)
                    if i not in ledger.done
                    and r not in ledger.rungs.get(i, {})
                ]
                if not need:
                    break
                for i in need:
                    if len(in_flight) >= self.max_in_flight:
                        break
                    if i not in rid_of:
                        # fresh submission straight to rung r's horizon
                        # (resume path: recorded earlier rungs replay)
                        rid_of[i] = self._submit(
                            self._request(self.trials[i], t_r, hold=True)
                        )
                        self._trial_submitted(i)
                        in_flight.add(i)
                self.server.tick()
                for i in list(need):
                    rid = rid_of.get(i)
                    if rid is None:
                        continue
                    status = self.server.status(rid)["status"]
                    if status == DONE and scored.get(i) != rid:
                        in_flight.discard(i)
                        segments.setdefault(i, []).append(
                            self.server.result(rid)
                        )
                        scored[i] = rid
                        ledger.append({
                            "event": TRIAL_RUNG,
                            "trial": i,
                            "rung": r,
                            "objective": self.objective.value(
                                _concat_ts(segments[i]), up_to_time=t_r
                            ),
                        })
                        if self.trace:
                            self.trace.instant(
                                "trial.rung", track=SWEEP_TRACK,
                                trial=i, rung=r,
                            )
                    elif status in (FAILED, TIMEOUT, CANCELLED):
                        in_flight.discard(i)
                        self._record_done(i, None, FAILED_S, 0, on_trial)

            cohort = participants(r)
            if r < len(rungs) - 1:
                # the halving cut over the FULL rung-r cohort (stops
                # already recorded at r re-derive identically and are
                # not re-appended)
                values = {
                    i: (
                        ledger.done[i]["objective"]
                        if i in ledger.done
                        and r not in ledger.rungs.get(i, {})
                        else ledger.rungs[i][r]
                    )
                    for i in cohort
                }
                ranked = self.objective.rank(values)
                keep = max(1, len(ranked) // eta)
                for i in ranked[keep:]:
                    if i not in ledger.stopped:
                        ledger.append({
                            "event": TRIAL_STOPPED,
                            "trial": i,
                            "rung": r,
                            "objective": values[i],
                        })
                        if self.trace:
                            now = time.perf_counter()
                            self.trace.emit_span(
                                "trial",
                                self._trial_t0.pop(i, now), now,
                                track=SWEEP_TRACK, aid=f"trial-{i}",
                                trial=i, status="stopped", rung=r,
                                objective=values[i],
                            )
                    if i in rid_of:
                        self.server.release_state(rid_of[i])
                    if i in segments:
                        ts_by_trial[i] = _concat_ts(segments.pop(i))
                extra = (rungs[r + 1] - steps_r) * self.dt
                for i in ranked[:keep]:
                    if i in ledger.done or i not in rid_of:
                        continue  # replayed trial; submits at its next rung
                    rid_of[i] = self._resubmit(rid_of[i], extra)
                    in_flight.add(i)
            else:
                for i in cohort:
                    if i in ledger.done:
                        continue
                    if i in segments:
                        ts = _concat_ts(segments.pop(i))
                        ts_by_trial[i] = ts
                        value = self.objective.value(ts)
                    else:
                        # resume killed between the final TRIAL_RUNG
                        # append and TRIAL_DONE: the full-horizon sim
                        # already ran, and the final rung's objective
                        # IS the full-horizon objective (same bits) —
                        # finish from the ledger, nothing to re-run
                        value = ledger.rungs[i][r]
                    if i in rid_of:
                        self.server.release_state(rid_of[i])
                    self._record_done(i, value, DONE_S, steps_r, on_trial)
        return ts_by_trial


class _EnsembleSweep:
    """Dense grids as chunked one-compile ensemble runs (no scheduler,
    no early stopping — every trial runs the full horizon)."""

    def __init__(
        self,
        spec: SweepSpec,
        trials: List[Trial],
        objective: Objective,
        ledger: TrialLedger,
    ):
        self.spec = spec
        self.trials = trials
        self.objective = objective
        self.ledger = ledger
        if spec.asha:
            raise ValueError(
                "the ensemble backend has no early stopping; use "
                "backend kind 'server' for asha sweeps"
            )
        if spec.warmup is not None:
            raise ValueError(
                "the ensemble backend has no snapshot store; use "
                "backend kind 'server' for shared-warmup sweeps"
            )
        batch = (spec.backend or {}).get("batch")
        self.batch = int(batch) if batch else min(len(trials), 64)
        if self.batch < 1:
            raise ValueError(f"batch={self.batch} must be >= 1")

    def run(self, on_trial=None) -> Tuple[Dict[int, Dict], Dict[str, Any]]:
        import jax
        import jax.numpy as jnp

        from lens_tpu.colony.ensemble import Ensemble
        from lens_tpu.experiment import build_model
        from lens_tpu.utils.hostio import copy_tree_to_host_async

        spec, ledger = self.spec, self.ledger
        steps = int(round(float(spec.horizon) / spec.timestep))
        if steps < 1 or steps % spec.emit_every != 0:
            raise ValueError(
                f"horizon={spec.horizon} must be a positive multiple of "
                f"timestep*emit_every "
                f"({spec.timestep}*{spec.emit_every})"
            )
        sim = build_model(
            spec.composite,
            dict(spec.config or {}),
            capacity=spec.capacity,
            n_agents=spec.n_agents,
        ).sim
        times = (
            np.arange(1, steps // spec.emit_every + 1)
            * spec.emit_every
            * spec.timestep
        )
        # The chunk partition is fixed by (trial list, batch): the unit
        # of resume. A partially-finished chunk re-runs WHOLE (same
        # composition -> same compiled program -> same bits) and only
        # its unfinished trials append ledger events.
        chunks = [
            self.trials[i:i + self.batch]
            for i in range(0, len(self.trials), self.batch)
        ]
        runners: Dict[int, Any] = {}  # chunk size -> jitted program
        ts_by_trial: Dict[int, Dict] = {}
        windows = 0

        def score_chunk(chunk, traj) -> None:
            # blocking fetch (the async copy started at dispatch) +
            # per-trial slicing, ledger appends, callbacks — all host
            host = jax.device_get(traj)
            for r, t in enumerate(chunk):
                ts = jax.tree.map(lambda x: np.asarray(x)[:, r], host)
                ts["__times__"] = times
                ts_by_trial[t.index] = ts
                if ledger.terminal(t.index):
                    continue
                event = {
                    "event": TRIAL_DONE,
                    "trial": t.index,
                    "seed": t.seed,
                    "objective": self.objective.value(ts),
                    "status": DONE_S,
                    "steps": steps,
                }
                ledger.append(event)
                if on_trial is not None:
                    on_trial(t.index, event)

        # Depth-2 pipeline over chunks (the serve path's policy, via
        # the same utils.hostio helper): dispatch chunk k+1 and start
        # its trajectory's host copy BEFORE scoring chunk k, so chunk
        # k's host-side slicing/objective/ledger work overlaps chunk
        # k+1's device compute. Purely a reordering of host work —
        # each chunk's program and bits are untouched, so resumed ==
        # uninterrupted still holds, and a crash between dispatch and
        # scoring just leaves the chunk unfinished in the ledger
        # (re-run whole, the existing resume unit).
        pending = None  # (chunk, traj) dispatched but not yet scored
        try:
            for chunk in chunks:
                if all(ledger.terminal(t.index) for t in chunk):
                    continue
                n = len(chunk)
                ens = Ensemble(sim, n)
                keys = jnp.stack(
                    [jax.random.PRNGKey(t.seed) for t in chunk]
                )
                rep = stack_overrides(chunk) if chunk[0].params else None
                states = ens.initial_state(
                    spec.n_agents, keys=keys, replicate_overrides=rep
                )
                runner = runners.get(n)
                if runner is None:
                    runner = jax.jit(
                        lambda s, e=ens: e.run(
                            s,
                            float(spec.horizon),
                            spec.timestep,
                            emit_every=spec.emit_every,
                        )
                    )
                    runners[n] = runner
                _, traj = runner(states)
                copy_tree_to_host_async(traj)
                windows += 1
                if pending is not None:
                    done, pending = pending, None
                    score_chunk(*done)
                pending = (chunk, traj)
        finally:
            # score the trailing in-flight chunk even if a later
            # dispatch raised — its results are real and its ledger
            # events keep the resume honest
            if pending is not None:
                if sys.exc_info()[0] is None:
                    score_chunk(*pending)
                else:
                    # already unwinding (device likely unhealthy):
                    # best-effort score, but never let a secondary
                    # failure here mask the root-cause exception —
                    # the chunk just stays unfinished in the ledger
                    try:
                        score_chunk(*pending)
                    except BaseException:
                        pass
        return ts_by_trial, {
            "backend": "ensemble",
            "batch": self.batch,
            "chunks_run": windows,
            "chunks_total": len(chunks),
        }

    def close(self) -> None:
        pass


def _build_table(
    trials: List[Trial], ledger: TrialLedger, objective: Objective
) -> Tuple[List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    rows = []
    for t in trials:
        row = {
            "trial": t.index,
            "seed": t.seed,
            "params": dict(t.params),
        }
        if t.index in ledger.done:
            ev = ledger.done[t.index]
            row.update(
                status=ev.get("status", DONE_S),
                objective=ev.get("objective"),
                steps=ev.get("steps"),
            )
        elif t.index in ledger.stopped:
            ev = ledger.stopped[t.index]
            row.update(
                status=STOPPED_S,
                objective=ev.get("objective"),
                rung=ev.get("rung"),
            )
        else:
            row.update(status=PENDING_S, objective=None)
        rows.append(row)
    finished = {
        r["trial"]: r["objective"]
        for r in rows
        if r["status"] == DONE_S and r["objective"] is not None
    }
    best = None
    if finished:
        best_index = objective.rank(finished)[0]
        best = next(r for r in rows if r["trial"] == best_index)
    return rows, best


def _save_trajectories(
    out_dir: str, timeseries: Mapping[int, Mapping], spec: SweepSpec
) -> str:
    """One framed emit log per trial under ``<out_dir>/trials/`` — the
    layout ``analysis.load_many`` loads back."""
    from lens_tpu.emit import LogEmitter

    trial_dir = os.path.join(out_dir, "trials")
    os.makedirs(trial_dir, exist_ok=True)
    for index, ts in sorted(timeseries.items()):
        path = os.path.join(trial_dir, f"trial_{index:05d}.lens")
        if os.path.exists(path):
            os.remove(path)  # re-run of this trial wholly owns its log
        tree = {k: v for k, v in ts.items() if k != "__times__"}
        emitter = LogEmitter(
            experiment_id=f"trial_{index:05d}",
            config={"sweep": spec.canonical(), "trial": index},
            path=path,
        )
        emitter.emit_trajectory(tree, times=ts.get("__times__"))
        emitter.close()
    return trial_dir


def run_sweep(
    spec: Mapping[str, Any] | SweepSpec,
    out_dir: Optional[str] = None,
    resume: bool = False,
    server=None,
    on_trial: Optional[Callable[[int, Mapping], None]] = None,
) -> SweepResult:
    """Run (or resume) a sweep to completion. The one entry point the
    CLI, examples, benches, and tests share.

    out_dir:
        Where the ledger, ``sweep_result.json``, and (with
        ``save_trajectories``) per-trial logs live. Without it the
        sweep runs with an in-memory ledger — fine for interactive use,
        nothing to resume from.
    resume:
        Required to reuse an out_dir holding a non-empty ledger (the
        crash-recovery path); refused otherwise so two different sweeps
        cannot interleave one ledger. The spec fingerprint must match.
    server:
        An existing ``SimServer`` to drive (the bench reuses one across
        reps to keep compiles out of timings); the sweep then does NOT
        close it.
    on_trial:
        ``(trial_index, terminal_event_dict)`` callback after each
        trial's terminal ledger append — progress reporting, or a test
        harness raising mid-sweep to exercise the resume contract.
    """
    spec = SweepSpec.from_mapping(spec)
    space = space_from_spec(spec.space)
    trials = space.trials(spec.seed)
    objective = Objective.from_spec(spec.objective)
    fingerprint = spec_fingerprint(spec.canonical())

    if out_dir:
        path = os.path.join(out_dir, LEDGER_NAME)
        exists = os.path.exists(path) and os.path.getsize(path) > 0
        if exists and not resume:
            raise ValueError(
                f"{path} already holds a sweep ledger; pass "
                f"resume=True to continue it (or use a fresh out_dir)"
            )
        ledger: TrialLedger = TrialLedger(path)
    else:
        ledger = MemoryLedger()

    t0 = time.perf_counter()
    backend_cls = {
        "server": _ServerSweep,
        "ensemble": _EnsembleSweep,
    }.get(spec.kind)
    if backend_cls is None:
        raise ValueError(
            f"unknown backend kind {spec.kind!r}; known: server, ensemble"
        )
    try:
        ledger.begin(
            fingerprint,
            {"n_trials": len(trials), "composite": spec.composite},
        )
        if backend_cls is _ServerSweep:
            backend = _ServerSweep(
                spec, trials, objective, ledger, server=server
            )
        else:
            if server is not None:
                raise ValueError(
                    "server= only applies to the server backend"
                )
            backend = _EnsembleSweep(spec, trials, objective, ledger)
        try:
            timeseries, metrics = backend.run(on_trial)
        finally:
            backend.close()
        metrics["wall_seconds"] = time.perf_counter() - t0
        table, best = _build_table(trials, ledger, objective)
        result = SweepResult(
            table=table,
            best=best,
            metrics=metrics,
            timeseries=timeseries,
        )
        if out_dir:
            if spec.save_trajectories:
                _save_trajectories(out_dir, timeseries, spec)
            result.path = write_table(
                os.path.join(out_dir, TABLE_NAME),
                {
                    "fingerprint": fingerprint,
                    "spec": spec.canonical(),
                    "n_trials": len(trials),
                    "best": best,
                    "metrics": metrics,
                    "table": table,
                },
            )
        return result
    finally:
        ledger.close()
