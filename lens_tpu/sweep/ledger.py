"""The trial ledger: append-only framed JSON events = crash-safe resume.

The sweep driver's only durable state. Every scheduling fact that must
survive a kill — which trials finished and with what objective, which
rung objectives were recorded, which trials halving stopped — is one
JSON event appended to ``sweep.ledger`` and flushed+fsynced before the
driver acts on it. Resume is replay: re-enumerate the (deterministic)
trial list from the spec, replay the ledger into per-trial state, and
re-run only what has no terminal event. Because trials are bitwise
reproducible within a backend, the resumed table is identical to an
uninterrupted run's.

Framing rides :class:`~lens_tpu.emit.log.JsonFrameLog` (the emit-log
record frame — magic + crc + length — with JSON payloads, shared with
the serve WAL) — same truncation semantics: a kill mid-append loses at
most the torn tail frame, which replay silently drops. The
final ``sweep_result.json`` table is written with ``checkpoint.py``'s
write-tmp-then-rename discipline so a kill mid-write can never leave a
torn table shadowing a good ledger.

A ``sweep_begin`` event pins the spec fingerprint: resuming with a spec
whose trial set or scoring could differ (changed space, seed, horizon,
objective, ...) is refused instead of silently mixing two sweeps'
trials in one ledger.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Mapping, Optional

from lens_tpu.emit.log import JsonFrameLog

#: Event types (the full vocabulary — replay ignores unknown events so
#: old readers tolerate newer ledgers).
SWEEP_BEGIN = "sweep_begin"
TRIAL_RUNG = "trial_rung"     # {trial, rung, objective}
TRIAL_STOPPED = "trial_stopped"  # {trial, rung, objective} halving loser
TRIAL_DONE = "trial_done"     # {trial, objective, status, ...} terminal

LEDGER_NAME = "sweep.ledger"
TABLE_NAME = "sweep_result.json"


def spec_fingerprint(canonical: Mapping[str, Any]) -> str:
    """sha256 over the canonical (sorted-key) JSON of the spec fields
    that determine the trial set and its scoring."""
    blob = json.dumps(canonical, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def write_table(path: str, table: Mapping[str, Any]) -> str:
    """Atomic JSON write (tmp + rename), same discipline as
    ``Checkpointer.save``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=2, default=str)
    os.replace(tmp, path)
    return path


class TrialLedger:
    """One sweep's event log, replayed at open.

    Replayed state (all idempotent — a re-appended duplicate event,
    possible when a resumed run re-derives a decision, just overwrites
    with identical content):

    - ``meta``: the ``sweep_begin`` payload, or ``None`` on a fresh file;
    - ``done``: ``{trial_index: trial_done event}``;
    - ``stopped``: ``{trial_index: trial_stopped event}``;
    - ``rungs``: ``{trial_index: {rung: objective}}``.
    """

    def __init__(self, path: str):
        self.path = path
        self.meta: Optional[Dict[str, Any]] = None
        self.done: Dict[int, Dict[str, Any]] = {}
        self.stopped: Dict[int, Dict[str, Any]] = {}
        self.rungs: Dict[int, Dict[int, float]] = {}
        self.events: List[Dict[str, Any]] = []
        # JsonFrameLog owns the framing, replay, and torn-tail
        # truncation (shared with the serve WAL); fsync-per-append is
        # the ledger's durability policy — an event is on disk before
        # the driver acts on it
        self._log = JsonFrameLog(path, fsync_every=True)
        for event in self._log.events:
            self._apply(event)

    def __len__(self) -> int:
        return len(self.events)

    def _apply(self, event: Dict[str, Any]) -> None:
        self.events.append(event)
        kind = event.get("event")
        if kind == SWEEP_BEGIN:
            self.meta = event
        elif kind == TRIAL_DONE:
            self.done[int(event["trial"])] = event
        elif kind == TRIAL_STOPPED:
            self.stopped[int(event["trial"])] = event
        elif kind == TRIAL_RUNG:
            self.rungs.setdefault(int(event["trial"]), {})[
                int(event["rung"])
            ] = event["objective"]
        # unknown events: kept in .events, no state

    def terminal(self, index: int) -> bool:
        """True when the trial needs no further simulation (finished or
        stopped by halving)."""
        return index in self.done or index in self.stopped

    def begin(self, fingerprint: str, meta: Mapping[str, Any]) -> None:
        """Pin (or verify) the sweep identity. On a replayed ledger the
        recorded fingerprint must match — resuming under a different
        spec is refused."""
        if self.meta is not None:
            if self.meta.get("fingerprint") != fingerprint:
                raise ValueError(
                    f"{self.path} belongs to sweep fingerprint "
                    f"{self.meta.get('fingerprint')!r}, not "
                    f"{fingerprint!r} — the spec changed; use a fresh "
                    f"out_dir (or restore the original spec) instead "
                    f"of resuming"
                )
            return
        self.append(
            {"event": SWEEP_BEGIN, "fingerprint": fingerprint, **meta}
        )

    def append(self, event: Mapping[str, Any]) -> None:
        """Durably append one event: framed, flushed, fsynced BEFORE the
        driver acts on it — the ordering that makes replay an upper
        bound on lost work (at most the in-flight trials)."""
        self._apply(self._log.append(event))

    def close(self) -> None:
        if self._log is not None:
            self._log.close()
            self._log = None

    def __enter__(self) -> "TrialLedger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class MemoryLedger(TrialLedger):
    """The no-``out_dir`` stand-in: same replayed-state interface, no
    disk, nothing to resume from. Lets the driver run one code path."""

    def __init__(self):
        self.path = "<memory>"
        self.meta = None
        self.done = {}
        self.stopped = {}
        self.rungs = {}
        self.events = []
        self._log = None

    def append(self, event: Mapping[str, Any]) -> None:
        self._apply(dict(event))

    def begin(self, fingerprint: str, meta: Mapping[str, Any]) -> None:
        if self.meta is None:
            self.append(
                {"event": SWEEP_BEGIN, "fingerprint": fingerprint, **meta}
            )

    def close(self) -> None:
        pass
