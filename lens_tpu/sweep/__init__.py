"""lens_tpu.sweep: resumable parameter sweeps & adaptive search.

The fleet layer the reference ran as one submitted experiment cluster
per parameter point (SURVEY.md §3.3), rebuilt over this repo's
substrates: a declarative spec names a search space (grid / random /
Latin hypercube), a scalar objective read off emitted trajectories, and
a backend — the continuous-batching scenario server
(:mod:`lens_tpu.serve`) for scheduled trials with successive-halving
early stopping, or a direct vmapped :class:`~lens_tpu.colony.Ensemble`
for dense grids. Trials and their PRNG seeds are a deterministic
function of ``(sweep_seed, trial_index)``; every terminal fact lands in
an fsynced append-only ledger, so a killed sweep resumes by replay and
re-runs only unfinished trials. See docs/sweeps.md.

    from lens_tpu.sweep import run_sweep
    result = run_sweep({
        "composite": "minimal_ode",
        "space": {"kind": "grid", "params": {
            "environment/glucose_external": {"grid": [0.2, 1.0, 5.0]},
        }},
        "horizon": 40.0,
        "objective": {"path": "cell/glucose_internal",
                      "reduction": "final_live_sum", "mode": "max"},
    }, out_dir="out/sweep1")
    print(result.best)

or from the CLI: ``python -m lens_tpu sweep --spec sweep.json``.
"""

from lens_tpu.sweep.driver import (
    SweepResult,
    SweepSpec,
    run_sweep,
    rung_steps,
)
from lens_tpu.sweep.ledger import (
    LEDGER_NAME,
    TABLE_NAME,
    MemoryLedger,
    TrialLedger,
    spec_fingerprint,
)
from lens_tpu.sweep.objective import REDUCTIONS, Objective
from lens_tpu.sweep.space import (
    GridSpace,
    LatinHypercubeSpace,
    RandomSpace,
    Trial,
    space_from_spec,
    stack_overrides,
    trial_seed,
)

__all__ = [
    "GridSpace",
    "LatinHypercubeSpace",
    "LEDGER_NAME",
    "MemoryLedger",
    "Objective",
    "RandomSpace",
    "REDUCTIONS",
    "SweepResult",
    "SweepSpec",
    "TABLE_NAME",
    "Trial",
    "TrialLedger",
    "run_sweep",
    "rung_steps",
    "space_from_spec",
    "spec_fingerprint",
    "stack_overrides",
    "trial_seed",
]
