"""Metrics instruments: counters, gauges, histograms, a sampled ring.

Before round 14 the serving metrics were a hand-rolled pile of ints and
lists inside ``ServerMetrics`` — readable only as one point-in-time
snapshot, mutated from two threads with no lock, and exportable only as
the JSON blob ``snapshot()`` happened to build. This module factors the
pile into the three standard instrument kinds every metrics system
(Prometheus, OpenTelemetry) converges on, plus the two read surfaces
the repo needs:

- :class:`MetricsRegistry` — named :class:`Counter` (monotonic),
  :class:`Gauge` (set or computed-at-read), and :class:`Histogram`
  (locked sample buffer with percentile reads) instruments.
  ``sample()`` renders one time-series point; ``prometheus_text()``
  renders the standard text exposition format for a pull scraper.
- :class:`MetricsRing` — an append-only ``metrics.jsonl`` file with
  ring semantics (bounded records, oldest rewritten away), giving
  occupancy/queue-depth/latency HISTORY instead of one final number:
  ``jq``-able, plottable, tailable while the server runs.

Everything here is host-side plain Python; nothing imports jax.
Thread-safety: counters are single-writer-per-name by convention (the
scheduler), histograms lock internally (the stream thread observes
latency samples while the scheduler reads percentiles — the round-14
fix for the ``reset_samples``-vs-``tick`` race), gauges are reads of
single attributes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional


def percentiles(
    samples, points=(50.0, 95.0, 99.0)
) -> Dict[str, Optional[float]]:
    """{"p50": ..., "p95": ..., "p99": ...} by linear interpolation —
    tiny and dependency-free so metrics never import numpy for three
    numbers. Empty input yields ``None`` entries (a server that served
    nothing has no latency, not a zero latency)."""
    out: Dict[str, Optional[float]] = {}
    ordered = sorted(samples)
    for p in points:
        key = f"p{p:g}"
        if not ordered:
            out[key] = None
            continue
        rank = (len(ordered) - 1) * (p / 100.0)
        lo = int(rank)
        hi = min(lo + 1, len(ordered) - 1)
        out[key] = ordered[lo] + (ordered[hi] - ordered[lo]) * (rank - lo)
    return out


class Counter:
    """A monotonic counter. One writer (the scheduler) by convention;
    int increments are atomic enough under the GIL for the read side,
    and the registry's sample/export paths only read."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0

    def inc(self, by: int = 1) -> None:
        self.value += by


class Gauge:
    """A point-in-time value: either ``set()`` by the owner or computed
    at read time from a callable (``fn``) — the "recompute at call"
    semantics ``SimServer.metrics()`` promises (a gauge read mid-run
    reflects NOW, not the last tick)."""

    __slots__ = ("name", "help", "fn", "_value")

    def __init__(
        self, name: str, help: str = "",
        fn: Optional[Callable[[], Any]] = None,
    ):
        self.name = name
        self.help = help
        self.fn = fn
        self._value: Any = 0

    def set(self, value: Any) -> None:
        self._value = value

    def read(self) -> Any:
        if self.fn is not None:
            return self.fn()
        return self._value


class Histogram:
    """A locked sample buffer with list-ish ergonomics.

    Writers ``observe()`` (``append`` is an alias — the pre-round-14
    call sites read naturally); readers take consistent copies
    (``values()``) or percentile summaries; ``clear()`` drops samples
    atomically. The internal lock is the round-14 fix for the
    ``reset_samples()``-vs-concurrent-``tick()``/stream-thread race:
    every mutation and every percentile read holds it, so a mid-reset
    reader sees either the old buffer or the empty one, never a
    half-cleared list mid-sort.
    """

    __slots__ = ("name", "help", "_samples", "_lock", "_sum", "_count")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._samples: List[float] = []
        self._lock = threading.Lock()
        self._sum = 0.0
        self._count = 0  # lifetime observations (survives clear())

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._samples.append(value)
            self._sum += value
            self._count += 1

    append = observe

    def clear(self) -> None:
        """Drop buffered samples (lifetime count/sum stay — they are
        the monotonic export; the buffer is the percentile window)."""
        with self._lock:
            self._samples.clear()

    def values(self) -> List[float]:
        with self._lock:
            return list(self._samples)

    def tail(self, n: int) -> List[float]:
        with self._lock:
            return self._samples[-n:]

    def percentiles(self, points=(50.0, 95.0, 99.0)):
        with self._lock:
            ordered = list(self._samples)
        return percentiles(ordered, points)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def __iter__(self):
        return iter(self.values())

    def __bool__(self) -> bool:
        return len(self) > 0


class MetricsRegistry:
    """Named instruments + the two export surfaces.

    ``namespace`` prefixes every exported metric name
    (``lens_serve_submitted_total``). Instrument factories are
    idempotent by name — asking twice returns the same instrument, a
    kind clash raises (one name, one meaning).
    """

    def __init__(self, namespace: str = "lens"):
        self.namespace = namespace
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def _claim(self, name: str, kind: Dict[str, Any]) -> None:
        for pool in (self.counters, self.gauges, self.histograms):
            if pool is not kind and name in pool:
                raise ValueError(
                    f"metric {name!r} already registered as a "
                    f"different instrument kind"
                )

    def counter(self, name: str, help: str = "") -> Counter:
        self._claim(name, self.counters)
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name, help)
        return c

    def gauge(
        self, name: str, help: str = "",
        fn: Optional[Callable[[], Any]] = None,
    ) -> Gauge:
        self._claim(name, self.gauges)
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name, help, fn)
        elif fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "") -> Histogram:
        self._claim(name, self.histograms)
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name, help)
        return h

    def sample(self) -> Dict[str, Any]:
        """One time-series point: every counter's value, every gauge
        read NOW, every histogram's count/sum + buffered percentiles.
        The ``metrics.jsonl`` record shape (plus the caller's
        timestamp)."""
        return {
            "counters": {
                name: c.value for name, c in self.counters.items()
            },
            "gauges": {
                name: g.read() for name, g in self.gauges.items()
            },
            "histograms": {
                name: {
                    "count": h.count,
                    "sum": h.sum,
                    "buffered": len(h),
                    **h.percentiles(),
                }
                for name, h in self.histograms.items()
            },
        }

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (version 0.0.4) —
        what a scraper GETs. Counters export as ``_total``, histograms
        as summaries (quantile series + ``_count``/``_sum``). Gauges
        whose read is not a number are skipped (device names, shard
        dicts — those belong to the JSON surfaces)."""
        ns = self.namespace
        lines: List[str] = []
        for name, c in sorted(self.counters.items()):
            full = f"{ns}_{name}_total"
            if c.help:
                lines.append(f"# HELP {full} {c.help}")
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {c.value}")
        for name, g in sorted(self.gauges.items()):
            value = g.read()
            if isinstance(value, bool) or not isinstance(
                value, (int, float)
            ):
                continue
            full = f"{ns}_{name}"
            if g.help:
                lines.append(f"# HELP {full} {g.help}")
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {value}")
        for name, h in sorted(self.histograms.items()):
            full = f"{ns}_{name}"
            if h.help:
                lines.append(f"# HELP {full} {h.help}")
            lines.append(f"# TYPE {full} summary")
            for point, value in h.percentiles().items():
                if value is None:
                    continue
                q = float(point[1:]) / 100.0
                lines.append(f'{full}{{quantile="{q:g}"}} {value}')
            lines.append(f"{full}_count {h.count}")
            lines.append(f"{full}_sum {h.sum}")
        return "\n".join(lines) + "\n"


class MetricsRing:
    """``metrics.jsonl``: one JSON object per line, ring-bounded.

    Append-only on the hot path (one line + flush per sample — the
    sampling CADENCE, not the tick rate, so seconds apart); when the
    file exceeds ``2 * max_records`` lines it is compacted in place to
    the newest ``max_records`` (tmp + rename, so a reader never sees a
    torn file). JSONL over the framed-log format on purpose: metrics
    history is for humans and ``jq``/pandas, not for crash recovery —
    greppability beats CRC framing here.
    """

    def __init__(self, path: str, max_records: int = 4096):
        if max_records < 1:
            raise ValueError(
                f"max_records={max_records} must be >= 1"
            )
        self.path = path
        self.max_records = int(max_records)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._count = 0
        if os.path.exists(path):
            with open(path, "rb") as f:
                self._count = sum(1 for _ in f)
        self._file = open(path, "a")

    def append(self, record: Dict[str, Any]) -> None:
        self._file.write(json.dumps(record, default=float) + "\n")
        self._file.flush()
        self._count += 1
        if self._count > 2 * self.max_records:
            self._compact()

    def _compact(self) -> None:
        self._file.close()
        with open(self.path) as f:
            lines = f.readlines()[-self.max_records:]
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(lines)
        os.replace(tmp, self.path)
        self._count = len(lines)
        self._file = open(self.path, "a")

    def records(self) -> List[Dict[str, Any]]:
        """Read the ring back (skips a torn final line, if the process
        died mid-append)."""
        self._file.flush()
        out: List[Dict[str, Any]] = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail from a kill mid-append
        return out

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
