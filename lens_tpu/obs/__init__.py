"""lens_tpu.obs: tracing + metrics for the serving stack.

Two halves, one goal — turn "the server finished and here is a number"
into "here is what every request did, when, on which device, and here
is every health gauge as history":

- :mod:`lens_tpu.obs.trace` — structured span events on the repo's
  framed-JSON log discipline, emitted by the serve pipeline when
  ``trace_dir`` is set, converted to Chrome/Perfetto trace-event JSON
  by :func:`chrome_trace` / ``python -m lens_tpu trace``.
- :mod:`lens_tpu.obs.metrics` — counter/gauge/histogram instruments
  (:class:`MetricsRegistry`), a ``metrics.jsonl`` time-series ring
  (:class:`MetricsRing`), and Prometheus text exposition.

See docs/observability.md for the span taxonomy, event schema, and the
overhead contract (off = bitwise identical, on = within noise).
"""

from lens_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsRing,
    percentiles,
)
from lens_tpu.obs.trace import (
    REQUEST_TRACK,
    SCHED_TRACK,
    STREAM_TRACK,
    SWEEP_TRACK,
    TRACE_NAME,
    NullTracer,
    Tracer,
    chrome_trace,
    device_track,
    read_trace,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsRing",
    "NullTracer",
    "REQUEST_TRACK",
    "SCHED_TRACK",
    "STREAM_TRACK",
    "SWEEP_TRACK",
    "TRACE_NAME",
    "Tracer",
    "chrome_trace",
    "device_track",
    "percentiles",
    "read_trace",
]
