"""Span tracing for the serve pipeline: what happened, when, where.

The serving stack went production-shaped — pipelined windows, prefix
forking, WAL recovery, mesh failover — with only end-of-run numbers to
show for it: a ``ServerMetrics`` snapshot and bench columns. Those
answer "how fast on average", never "why was THIS request slow" or
"what did the scheduler do while device 1 died". The inference stacks
this repo borrows its serving shape from treat per-request span traces
as the substrate for every scheduling and SLO decision; this module is
that substrate for simulation serving.

A :class:`Tracer` appends small structured events to a framed-JSON log
(the same :class:`~lens_tpu.emit.log.JsonFrameLog` discipline as the
WAL and the sweep ledger — magic + CRC framing, a torn tail is lost
cleanly, replay is just reading). Two event shapes:

- **span**: a named interval ``{ev: "span", name, track, ts, dur,
  args}`` — a window's device compute, a sink flush, an admission
  scatter, a hold spill. ``ts`` is seconds since the tracer's epoch,
  ``dur`` seconds. Spans carrying an ``aid`` (async id) may overlap
  freely on one track (a request's queue wait, a sweep trial); plain
  spans on one track are emitted by one thread and nest.
- **instant**: a named point ``{ev: "instant", name, track, ts,
  args}`` — a retirement, a prefix-cache hit, a device quarantine, an
  injected fault.

Correlation rides ``args``: every serve event carries the request id
(``rid``), scheduler tick (``tick``), and device shard (``shard``)
that apply, so a timeline groks "this request waited 3 windows behind
that one's streamer backpressure on shard 2".

The request-stream CDN (round 18) adds four events: a
``result.replay`` span on the requests track (a submit answered whole
from the durable result cache — its duration is the entire serving
cost of the hit), a ``result.store`` span on the scheduler track (a
completed log filed under its fingerprint), and ``dedup.coalesced`` /
``dedup.detached`` instants (a request attaching to — or re-queueing
off — an identical in-flight leader's lane).

Overhead contract (docs/observability.md): tracing OFF is a
:class:`NullTracer` — falsy, every method a no-op — and the traced
code paths are written to compute nothing extra behind ``if tracer:``
guards, so the untraced server is the round-13 server bit for bit.
Tracing ON costs one dict + one JSON encode + one buffered write per
event, a handful of events per window — pinned ≤2% on ``bench_serve
--trace`` (BENCH_OBS_CPU_r14.json). The trace file is buffered
(no per-event flush/fsync): observability must never tax the serving
hot path for durability it does not need — a crash loses at most the
buffered tail, and the WAL (not the trace) is the recovery record.

Conversion: :func:`chrome_trace` renders a span log as Chrome
trace-event JSON — load it in Perfetto (https://ui.perfetto.dev) or
``chrome://tracing`` to see the depth-2 pipeline, streamer
backpressure, and a kill-one-device drill on a real timeline.
``python -m lens_tpu trace <dir> --out trace.json`` is the CLI wrapper.
"""

from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional

from lens_tpu.emit.log import JsonFrameLog, iter_frames

#: The span log's file name inside a ``--trace-dir``.
TRACE_NAME = "serve.trace"

#: Track names the serve pipeline emits on (docs/observability.md).
#: A track is a horizontal lane on the rendered timeline: one per
#: logical actor, so concurrent actors never visually nest.
SCHED_TRACK = "scheduler"      # the tick loop's own work
STREAM_TRACK = "streamer"      # background sink slicing/appends
REQUEST_TRACK = "requests"     # per-request async spans (queue wait)
SWEEP_TRACK = "sweep"          # per-trial spans (sweep driver)


def device_track(shard: int) -> str:
    """The per-device-shard track (window compute + host copy)."""
    return f"device:{int(shard)}"


class NullTracer:
    """The tracing-off tracer: falsy, every method a no-op.

    Handed out wherever a real :class:`Tracer` could go, so
    instrumented code never branches on ``is None`` — it either calls
    cheap no-ops or guards genuinely extra work behind ``if tracer:``.
    """

    enabled = False

    def __bool__(self) -> bool:
        return False

    @staticmethod
    def now() -> float:
        return 0.0

    def emit_span(self, name: str, t0: float, t1: float, **kw) -> None:
        pass

    def instant(self, name: str, **kw) -> None:
        pass

    @contextmanager
    def span(self, name: str, **kw):
        yield

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class Tracer:
    """Thread-safe span/instant emitter over one framed-JSON file.

    ``path`` is the span log (conventionally ``<trace_dir>/serve.trace``).
    Events are framed + buffered (no per-event flush); ``flush()``
    pushes to the OS, ``close()`` flushes and closes. All timestamps
    are ``time.perf_counter()`` seconds, stored relative to the
    tracer's construction epoch — callers pass absolute perf_counter
    values (the clock the server already stamps everything with) and
    the tracer normalizes.

    Thread safety: the scheduler thread, the stream thread, and the
    log-writer threads may all emit; one lock serializes appends (an
    event is one small frame — contention is negligible next to the
    JSON encode each caller pays outside any lock... the encode happens
    inside ``JsonFrameLog.append``, so it is under the lock; at tens of
    events per window this is nanoseconds against a millisecond
    window).
    """

    enabled = True

    def __init__(self, path: str, extra: Optional[Dict[str, Any]] = None):
        self.path = path
        # write-only + fresh file: a trace describes ONE server run,
        # and a long-lived traced server must not retain (or replay)
        # an unbounded event list in RAM — the on-disk log is the
        # record, read back by read_trace()/the trace CLI
        self._log = JsonFrameLog(
            path, fsync_every=False, buffered=True,
            retain=False, truncate=True,
        )
        self._lock = threading.Lock()
        self.t0 = time.perf_counter()
        self.events_emitted = 0
        # labels stamped into EVERY event's args (cluster workers set
        # {"host": k} so a merged multi-host view stays attributable
        # end to end; empty for single-host servers — zero overhead)
        self.extra: Dict[str, Any] = dict(extra or {})

    def __bool__(self) -> bool:
        return True

    @staticmethod
    def now() -> float:
        return time.perf_counter()

    def _emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            if self._log is None:
                return  # closed: late stream-thread events are dropped
            self._log.append(event)
            self.events_emitted += 1

    def emit_span(
        self,
        name: str,
        t0: float,
        t1: float,
        track: str = SCHED_TRACK,
        aid: Optional[str] = None,
        **args,
    ) -> None:
        """One completed interval. ``t0``/``t1`` are absolute
        perf_counter stamps; ``aid`` marks the span async (it may
        overlap others on its track — rendered as a Chrome async event
        keyed by the id). Extra keyword args become the span's
        correlation payload (rid, tick, shard, lane, ...)."""
        event: Dict[str, Any] = {
            "ev": "span",
            "name": name,
            "track": track,
            "ts": t0 - self.t0,
            "dur": max(t1 - t0, 0.0),
        }
        if aid is not None:
            event["aid"] = str(aid)
        if self.extra:
            args = {**self.extra, **args}
        if args:
            event["args"] = _jsonable(args)
        self._emit(event)

    def instant(
        self, name: str, track: str = SCHED_TRACK, **args
    ) -> None:
        """One point event, stamped now."""
        event: Dict[str, Any] = {
            "ev": "instant",
            "name": name,
            "track": track,
            "ts": time.perf_counter() - self.t0,
        }
        if self.extra:
            args = {**self.extra, **args}
        if args:
            event["args"] = _jsonable(args)
        self._emit(event)

    @contextmanager
    def span(self, name: str, track: str = SCHED_TRACK, **args):
        """Context manager form: times the with-block."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit_span(name, t0, time.perf_counter(), track, **args)

    def flush(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.sync()

    def close(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.close()
                self._log = None


def _jsonable(args: Dict[str, Any]) -> Dict[str, Any]:
    """Span args as plain JSON scalars (numpy ints, tuples, and the
    odd object all flatten to something a reader can grep)."""
    out: Dict[str, Any] = {}
    for k, v in args.items():
        if v is None or isinstance(v, (bool, int, float, str)):
            out[k] = v
        else:
            try:
                out[k] = json.loads(json.dumps(v, default=str))
            except (TypeError, ValueError):
                out[k] = str(v)
    return out


def read_trace(path: str) -> List[Dict[str, Any]]:
    """Replay a span log into its event list (torn tail dropped
    cleanly, same contract as every framed log in the repo)."""
    events: List[Dict[str, Any]] = []
    for payload in iter_frames(path):
        events.append(json.loads(payload.decode()))
    return events


# -- Chrome trace-event conversion ------------------------------------------

#: Synthetic pid for the whole server process in the rendered trace.
_PID = 1


def chrome_trace(
    events: Iterable[Dict[str, Any]], label: str = "lens_tpu serve"
) -> Dict[str, Any]:
    """Render span-log events as a Chrome trace-event JSON object
    (the ``{"traceEvents": [...]}`` object form; load in Perfetto or
    chrome://tracing).

    Mapping:

    - each ``track`` becomes one named thread (tid) under one process;
      tracks are ordered scheduler, devices, streamer, requests, sweep,
      then first-seen;
    - plain spans -> complete events (``ph: "X"``, ``ts``/``dur`` in
      microseconds);
    - ``aid``-carrying spans -> async begin/end pairs (``ph: "b"``/
      ``"e"``) keyed by the id, so overlapping per-request waits render
      as parallel bars instead of bogus nesting;
    - instants -> ``ph: "i"`` with thread scope;
    - ``args`` pass through untouched (rid/tick/shard correlation is
      clickable in the viewer).
    """
    events = list(events)
    order = {SCHED_TRACK: 0, STREAM_TRACK: 100, REQUEST_TRACK: 200,
             SWEEP_TRACK: 300}
    seen: List[str] = []
    for e in events:
        t = str(e.get("track", SCHED_TRACK))
        if t not in seen:
            seen.append(t)

    def track_rank(t: str) -> tuple:
        if t.startswith("device:"):
            try:
                return (10, int(t.split(":", 1)[1]))
            except ValueError:
                return (10, 0)
        return (order.get(t, 400), seen.index(t))

    tids = {t: i + 1 for i, t in enumerate(sorted(seen, key=track_rank))}

    out: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
        "args": {"name": label},
    }]
    for t, tid in tids.items():
        out.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
            "args": {"name": t},
        })
        out.append({
            "name": "thread_sort_index", "ph": "M", "pid": _PID,
            "tid": tid, "args": {"sort_index": tid},
        })
    for e in events:
        tid = tids[str(e.get("track", SCHED_TRACK))]
        ts_us = float(e.get("ts", 0.0)) * 1e6
        base = {
            "name": str(e.get("name", "?")),
            "cat": str(e.get("track", SCHED_TRACK)),
            "pid": _PID,
            "tid": tid,
            "args": dict(e.get("args") or {}),
        }
        if e.get("ev") == "span":
            dur_us = float(e.get("dur", 0.0)) * 1e6
            aid = e.get("aid")
            if aid is not None:
                # async pair: overlapping spans on one track render in
                # parallel, keyed by the id (Perfetto draws one row per
                # concurrent id)
                out.append({**base, "ph": "b", "id": str(aid),
                            "ts": ts_us})
                out.append({**base, "ph": "e", "id": str(aid),
                            "ts": ts_us + dur_us})
            else:
                out.append({**base, "ph": "X", "ts": ts_us,
                            "dur": dur_us})
        else:
            out.append({**base, "ph": "i", "ts": ts_us, "s": "t"})
    out.sort(key=lambda e: (e.get("ts", 0.0), e["ph"] != "b"))
    return {"traceEvents": out, "displayTimeUnit": "ms"}
