"""Replicate ensembles: N independent colonies as ONE device program.

A capability the reference's architecture cannot express: where one
Lens experiment is one cluster of OS processes, here a whole simulation
— any colony form — is a pure function of a state pytree, so N
replicates are just one more leading axis. ``Ensemble`` vmaps
construction, stepping, and emission over that axis:

- **statistics for free**: division times, growth curves, and phase
  transitions are stochastic; an ensemble turns one run into a
  distribution (mean/CI across the replicate axis of every emitted
  leaf) at one compile.
- **chip utilization**: small colonies are latency-bound on TPU (the
  chip idles between tiny kernels — see BENCH_AGENTS_SWEEP records);
  64 replicates of a 1k-agent colony fill the same lanes a single 64k
  colony would, so parameter-free replication is the cheapest way to
  buy back the under-filled regime.

Works with any sim exposing the colony-form protocol:
``initial_state(..., key=...)``, ``step(state, dt)``, and
``emit_state(state)`` — :class:`~lens_tpu.colony.colony.Colony`,
:class:`~lens_tpu.environment.spatial.SpatialColony`, and
:class:`~lens_tpu.environment.multispecies.MultiSpeciesColony` all do.
Replicates are fully independent (separate PRNG streams split from one
seed; no shared fields), and the ensemble trajectory's emitted leaves
gain a replicate axis after time: ``[T, R, ...]``.

Note ``lax.cond``-guarded work (division) runs unconditionally under
``vmap`` (cond becomes select across lanes) — the ensemble trades that
small overhead for R-way parallelism.

Replicates need not be identical twins: ``initial_state`` accepts
``replicate_overrides`` — a nested mapping whose leaves carry a leading
``[R, ...]`` axis — so the same one-compile program doubles as a
**parameter scan** (R initial conditions / parameter values stepped in
lock-step on one chip). The reference lineage runs a scan as R separate
experiment processes (SURVEY.md §3.3: one cluster of OS processes per
experiment); here it is one more ``in_axes`` entry.
"""

from __future__ import annotations

from typing import Any, Mapping, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.core.schedule import scan_schedule
from lens_tpu.utils.dicts import flatten_paths, set_path
from lens_tpu.utils.hostio import copy_tree_to_host_async


class Ensemble:
    """N independent replicates of ``sim`` stepped as one program."""

    def __init__(self, sim: Any, n_replicates: int):
        if n_replicates < 1:
            raise ValueError(f"n_replicates={n_replicates} must be >= 1")
        for attr in ("initial_state", "step", "emit_state"):
            if not callable(getattr(sim, attr, None)):
                raise TypeError(
                    f"{type(sim).__name__} does not expose {attr}(); "
                    f"Ensemble needs the colony-form protocol"
                )
        self.sim = sim
        self.n_replicates = int(n_replicates)

    def initial_state(
        self,
        *args,
        key: jax.Array | None = None,
        keys: jax.Array | None = None,
        replicate_overrides: Mapping | None = None,
        **kwargs,
    ):
        """Stacked initial states: ``sim.initial_state`` vmapped over
        ``n_replicates`` keys split from ``key`` (all other arguments are
        shared and static across replicates).

        ``keys`` replaces the split with EXPLICIT per-replicate PRNG keys
        (shape ``[n_replicates, 2]``) — the hook the sweep subsystem's
        dense-grid backend uses so every trial's key is derived from
        ``(sweep_seed, trial_index)`` independently of which batch the
        trial lands in (``jax.random.split`` would entangle a trial's
        stream with the batch size). Exactly one of ``key``/``keys``.

        ``replicate_overrides`` turns the ensemble into a parameter scan:
        a nested mapping of schema-variable paths to arrays with a leading
        ``[n_replicates, ...]`` axis. Replicate ``r``'s slice is merged
        over the shared ``overrides`` kwarg (per-replicate wins on a path
        collision) and flows through the sim's own override validation —
        a ``[R]`` leaf sets one scalar per replicate (broadcast to every
        agent), a ``[R, capacity, ...]`` leaf sets per-agent values per
        replicate.
        """
        if (key is None) == (keys is None):
            raise ValueError(
                "pass exactly one of key= (split into n_replicates "
                "streams) or keys= (explicit [n_replicates, 2] keys)"
            )
        if keys is None:
            keys = jax.random.split(key, self.n_replicates)
        else:
            keys = jnp.asarray(keys)
            if keys.ndim != 2 or keys.shape[0] != self.n_replicates:
                raise ValueError(
                    f"keys must be [n_replicates={self.n_replicates}, 2] "
                    f"PRNG keys, got shape {keys.shape}"
                )
        if not replicate_overrides:
            return jax.vmap(
                lambda k: self.sim.initial_state(*args, key=k, **kwargs)
            )(keys)
        if len(args) > 1:
            # Colony's 2nd positional is `overrides` but SpatialColony's
            # is `key`, so a positional arg here can't be merged safely —
            # it would either collide with the overrides kwarg below or
            # silently skip the documented per-replicate merge.
            raise ValueError(
                "with replicate_overrides, pass the sim's other "
                "initial_state arguments (overrides, locations, ...) as "
                "keywords, not positionally"
            )
        shared = kwargs.pop("overrides", None) or {}
        rep = {}
        for path, value in flatten_paths(replicate_overrides):
            value = jnp.asarray(value)
            if value.ndim < 1 or value.shape[0] != self.n_replicates:
                raise ValueError(
                    f"replicate override {path} needs a leading "
                    f"[n_replicates={self.n_replicates}] axis, got shape "
                    f"{value.shape}"
                )
            rep[path] = value

        def build(k, rep_slice):
            merged = dict(shared)
            for path, value in rep_slice.items():
                merged = set_path(merged, path, value)
            return self.sim.initial_state(
                *args, key=k, overrides=merged, **kwargs
            )

        return jax.vmap(build)(keys, rep)

    def step(self, states, timestep: float):
        return jax.vmap(lambda s: self.sim.step(s, timestep))(states)

    def step_where(self, states, active: jax.Array, timestep: float):
        """Step only the replicates where ``active`` is True; the rest
        keep their state BITWISE (every leaf, including the PRNG key and
        step counter, is the old value — the replicate-axis analogue of
        the colony's frozen dead rows).

        This is what lets heterogeneous lifetimes share one resident
        program (lens_tpu.serve packs requests with different horizons
        into fixed lanes): the step is computed for every lane — masking
        trades wasted FLOPs on idle lanes for a single compiled shape —
        and a per-leaf ``where`` selects old state for inactive lanes.
        Because the select is elementwise along the replicate axis, an
        active lane's result is independent of what the OTHER lanes hold
        (garbage, frozen remnants of a finished run, anything) — the
        property the serve layer's co-batching determinism contract
        rests on.
        """
        stepped = self.step(states, timestep)

        def sel(new, old):
            mask = active.reshape(active.shape + (1,) * (new.ndim - 1))
            return jnp.where(mask, new, old)

        return jax.tree.map(sel, stepped, states)

    def emit_state(self, states) -> dict:
        return jax.vmap(self.sim.emit_state)(states)

    def run(
        self, states, total_time: float, timestep: float, emit_every: int = 1
    ) -> Tuple[Any, dict]:
        """Scan the vmapped step; emitted leaves are ``[T, R, ...]``."""
        return scan_schedule(
            lambda s: self.step(s, timestep),
            self.emit_state,
            states,
            total_time,
            timestep,
            emit_every,
        )

    def expanded(self, states, factor=2) -> Tuple["Ensemble", Any]:
        """Capacity growth for every replicate (host-side, at a segment
        boundary — same contract as :meth:`Colony.expanded`).

        Replicates advance in lockstep, so each replicate's slice expands
        through the wrapped sim's OWN ``expanded`` with identical
        capacity/lineage-id bookkeeping; the padded slices re-stack into
        the ensemble layout. Returns ``(ensemble_with_grown_sim,
        stacked_states)`` — the pre-expansion trajectory of every
        replicate is bitwise unchanged, exactly as for a single colony.
        """
        if not callable(getattr(self.sim, "expanded", None)):
            raise TypeError(
                f"{type(self.sim).__name__} has no expanded(); capacity "
                f"growth needs a Colony/SpatialColony-form sim"
            )
        # start every leaf's DMA before the blocking fetch (the shared
        # segment-loop policy; see utils.hostio)
        host = jax.device_get(copy_tree_to_host_async(states))
        grown_sim = None
        slices = []
        # Delegating per replicate re-runs the (host-side, cheap)
        # grown-colony construction R times, but keeps ONE source of
        # truth for expansion semantics — a batched pad here would have
        # to mirror Colony.expanded's template/lineage rules forever.
        for r in range(self.n_replicates):
            sim_r, s_r = self.sim.expanded(
                jax.tree.map(lambda x: x[r], host), factor
            )
            grown_sim = grown_sim or sim_r
            slices.append(s_r)
        # np.stack, not jnp: the stacked grown ensemble must NOT
        # materialize on one device (a replicate-mesh caller re-shards
        # it; the transient single-device copy could OOM where both
        # sharded layouts fit).
        stacked = jax.tree.map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *slices
        )
        return Ensemble(grown_sim, self.n_replicates), stacked

    def run_timeline(
        self,
        states,
        timeline,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        start_time: float = 0.0,
    ) -> Tuple[Any, dict]:
        """Timeline-driven run over the replicate axis.

        The media schedule is replicate-independent (event times and
        recipes are static), and ``run_media_timeline``'s segment loop is
        fully traceable — static Python unrolling, jnp field resets, scan
        segments — so vmapping the wrapped sim's whole ``run_timeline``
        gives every replicate the same media history at one compile.
        Needs a sim with fields (spatial / multi-species forms).
        """
        if not callable(getattr(self.sim, "run_timeline", None)):
            raise TypeError(
                f"{type(self.sim).__name__} has no run_timeline(); media "
                f"timelines need a lattice sim (SpatialColony / "
                f"MultiSpeciesColony)"
            )
        final, traj = jax.vmap(
            lambda s: self.sim.run_timeline(
                s, timeline, total_time, timestep, emit_every, start_time
            )
        )(states)
        # vmap stacks the replicate axis FIRST; the ensemble layout is
        # [T, R, ...] (time-leading, matching Ensemble.run and what the
        # emitter/analysis consume)
        return final, jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), traj)
