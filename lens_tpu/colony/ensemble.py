"""Replicate ensembles: N independent colonies as ONE device program.

A capability the reference's architecture cannot express: where one
Lens experiment is one cluster of OS processes, here a whole simulation
— any colony form — is a pure function of a state pytree, so N
replicates are just one more leading axis. ``Ensemble`` vmaps
construction, stepping, and emission over that axis:

- **statistics for free**: division times, growth curves, and phase
  transitions are stochastic; an ensemble turns one run into a
  distribution (mean/CI across the replicate axis of every emitted
  leaf) at one compile.
- **chip utilization**: small colonies are latency-bound on TPU (the
  chip idles between tiny kernels — see BENCH_AGENTS_SWEEP records);
  64 replicates of a 1k-agent colony fill the same lanes a single 64k
  colony would, so parameter-free replication is the cheapest way to
  buy back the under-filled regime.

Works with any sim exposing the colony-form protocol:
``initial_state(..., key=...)``, ``step(state, dt)``, and
``emit_state(state)`` — :class:`~lens_tpu.colony.colony.Colony`,
:class:`~lens_tpu.environment.spatial.SpatialColony`, and
:class:`~lens_tpu.environment.multispecies.MultiSpeciesColony` all do.
Replicates are fully independent (separate PRNG streams split from one
seed; no shared fields), and the ensemble trajectory's emitted leaves
gain a replicate axis after time: ``[T, R, ...]``.

Note ``lax.cond``-guarded work (division) runs unconditionally under
``vmap`` (cond becomes select across lanes) — the ensemble trades that
small overhead for R-way parallelism.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax

from lens_tpu.core.schedule import scan_schedule


class Ensemble:
    """N independent replicates of ``sim`` stepped as one program."""

    def __init__(self, sim: Any, n_replicates: int):
        if n_replicates < 1:
            raise ValueError(f"n_replicates={n_replicates} must be >= 1")
        for attr in ("initial_state", "step", "emit_state"):
            if not callable(getattr(sim, attr, None)):
                raise TypeError(
                    f"{type(sim).__name__} does not expose {attr}(); "
                    f"Ensemble needs the colony-form protocol"
                )
        self.sim = sim
        self.n_replicates = int(n_replicates)

    def initial_state(self, *args, key: jax.Array, **kwargs):
        """Stacked initial states: ``sim.initial_state`` vmapped over
        ``n_replicates`` keys split from ``key`` (all other arguments are
        shared and static across replicates)."""
        keys = jax.random.split(key, self.n_replicates)
        return jax.vmap(
            lambda k: self.sim.initial_state(*args, key=k, **kwargs)
        )(keys)

    def step(self, states, timestep: float):
        return jax.vmap(lambda s: self.sim.step(s, timestep))(states)

    def emit_state(self, states) -> dict:
        return jax.vmap(self.sim.emit_state)(states)

    def run(
        self, states, total_time: float, timestep: float, emit_every: int = 1
    ) -> Tuple[Any, dict]:
        """Scan the vmapped step; emitted leaves are ``[T, R, ...]``."""
        return scan_schedule(
            lambda s: self.step(s, timestep),
            self.emit_state,
            states,
            total_time,
            timestep,
            emit_every,
        )
