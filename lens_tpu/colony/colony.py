"""The colony layer: a whole population of cells as ONE device pytree.

This is the rebuild's replacement for the reference's entire actor runtime.
Where the reference runs one OS process per cell, spawns daughters through
a shepherd supervisor, and synchronizes over Kafka (reconstructed:
``lens/actor/inner.py``, ``shepherd.py``, SURVEY.md §1 L3-L4), the colony
stacks homogeneous agent state along a leading **agent axis** of fixed
``capacity`` and:

- steps every agent with one ``vmap`` of the compartment step
  (BASELINE.json north star: "stacked into a single device pytree and
  each ODE-style Process.next_update vmap'd across all cells");
- tracks liveness with an **alive mask** — "agent death" is clearing a
  bit, never a shape change;
- implements division as **row activation**: the parent row is
  overwritten with daughter A, daughter B is scattered into a free row,
  per the per-variable dividers declared in the schema
  (SURVEY.md §3.3: the reference's spawn-two-processes handshake
  "collapses to activate two rows in the alive-mask").

Everything is fixed-shape and branch-free, so the whole colony step —
biology, division, bookkeeping — jits into a single XLA program that can
later be sharded over the agent axis with ``shard_map``.

Determinism: dead rows are frozen (their state does not evolve), so a
colony trajectory is bitwise-reproducible for a fixed seed regardless of
how many rows are active — the rebuild's answer to the reference's
exchange-window barrier ordering (SURVEY.md §5 "race detection").
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from lens_tpu.core.engine import Compartment
from lens_tpu.core.schedule import scan_schedule
from lens_tpu.core.state import DIVIDERS
from lens_tpu.core.topology import Path, normalize_path
from lens_tpu.utils.dicts import flatten_paths, get_path, set_path


class ColonyState(NamedTuple):
    """The full simulation state of a colony — one pytree, one device.

    agents:  stacked agent state; every leaf has leading dim = capacity.
    alive:   bool[capacity] — which rows are live cells.
    key:     PRNG state consumed by division (and stochastic processes).
    step:    int32 scalar — global step counter (drives emit cadence,
             deterministic per-step randomness).
    """

    agents: dict
    alive: jax.Array
    key: jax.Array
    step: jax.Array


def _bcast(mask: jax.Array, leaf: jax.Array) -> jax.Array:
    """Broadcast a [capacity] mask against a [capacity, ...] leaf."""
    return mask.reshape(mask.shape + (1,) * (leaf.ndim - 1))


class Colony:
    """Fixed-capacity population of one compartment type.

    Parameters
    ----------
    compartment:
        The wired ``Compartment`` describing a single agent's biology.
    capacity:
        Maximum number of rows (preallocated). Division beyond capacity is
        deterministically suppressed: the parent simply does not divide
        that step (and will retry next step while a row is free).
    division_trigger:
        Optional path into the agent state tree holding a boolean/0-1
        variable; rows where it is nonzero (and alive) divide this step.
        ``None`` disables division entirely.
    death_trigger:
        Optional path to a boolean/0-1 variable; rows where it is
        nonzero (and alive) DIE this step — the alive bit clears, the
        row freezes (same dead-row semantics as initial padding), and
        the freed row returns to the division pool for a future
        daughter. Death is the other half of the reference lineage's
        lifecycle (cells burst/starve, and their OS process exits —
        SURVEY.md §3.3); here it is one mask update, and it RECYCLES
        capacity instead of leaking it.
    """

    def __init__(
        self,
        compartment: Compartment,
        capacity: int,
        division_trigger: Optional[Path | str] = None,
        id_offset: int = 0,
        death_trigger: Optional[Path | str] = None,
    ):
        self.compartment = compartment
        self.capacity = int(capacity)
        # Static base added to every minted lineage id. 0 for a fresh
        # colony; capacity expansion (``expanded``) sets it so that ids
        # minted at the NEW capacity start above every id the old colony
        # could have minted (the stride of the minting scheme changes
        # with capacity, so without the shift old and new id ranges
        # would interleave and collide).
        self.id_offset = int(id_offset)
        self.division_trigger = (
            normalize_path(division_trigger) if division_trigger is not None else None
        )
        self.death_trigger = (
            normalize_path(death_trigger) if death_trigger is not None else None
        )
        for role, trig in (
            ("division_trigger", self.division_trigger),
            ("death_trigger", self.death_trigger),
        ):
            if trig is not None and trig not in compartment.updaters:
                raise ValueError(
                    f"{role} {trig} is not a schema variable of the "
                    f"compartment"
                )

    # -- construction --------------------------------------------------------

    def initial_state(
        self,
        n_alive: int,
        overrides: Mapping | None = None,
        key: jax.Array | None = None,
    ) -> ColonyState:
        """Stack the compartment's initial state into ``capacity`` rows,
        with the first ``n_alive`` marked alive.

        ``overrides`` may carry per-agent leading axes (shape
        ``[capacity, ...]``) or scalars (broadcast to all rows).
        """
        if not 0 <= n_alive <= self.capacity:
            raise ValueError(f"n_alive={n_alive} not in [0, {self.capacity}]")
        single = self.compartment.initial_state()
        agents = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (self.capacity,) + x.shape).copy(), single
        )
        if overrides:
            agents = self._override_agents(agents, overrides)
        alive = jnp.arange(self.capacity) < n_alive
        if self.division_trigger is not None:
            # Lineage bookkeeping (framework-level, not schema-declared):
            # founders' cell_id = their row; division assigns BOTH
            # daughters fresh ids and records the parent's id, so offline
            # analysis can reconstruct the full binary lineage tree from
            # any emitted trajectory (the reference's multi-generation
            # traces, SURVEY.md §2 "Analysis"). row_id is the immutable
            # physical row index (globally unique even when the agent
            # axis is sharded — it rides the shard split), used to mint
            # collision-free ids inside per-shard division.
            rows = jnp.arange(self.capacity, dtype=jnp.int32)
            agents = dict(
                agents,
                lineage={
                    "cell_id": rows,
                    "parent_id": jnp.full(self.capacity, -1, jnp.int32),
                    "birth_step": jnp.zeros(self.capacity, jnp.int32),
                    "row_id": rows,
                },
            )
        if key is None:
            key = jax.random.PRNGKey(0)
        return ColonyState(
            agents=agents, alive=alive, key=key, step=jnp.int32(0)
        )

    def _override_agents(self, agents: Mapping, overrides: Mapping):
        """Set schema variables into an agents tree: scalars broadcast
        to every row, per-agent arrays must match the row count. Shared
        by ``initial_state`` (fresh rows) and ``apply_overrides`` (an
        existing state — the serve layer's fork point). Row-count
        polymorphic like ``step_biology``."""
        for path, value in flatten_paths(overrides):
            if path not in self.compartment.updaters:
                raise KeyError(f"override {path} is not a schema variable")
            value = jnp.asarray(value)
            base = get_path(agents, path)
            if value.ndim == base.ndim:  # per-agent array
                if value.shape[0] != base.shape[0]:
                    raise ValueError(
                        f"per-agent override {path} has leading dim "
                        f"{value.shape[0]}, expected capacity={base.shape[0]}"
                    )
                agents = set_path(agents, path, value.astype(base.dtype))
            else:
                agents = set_path(
                    agents, path, jnp.broadcast_to(value, base.shape).astype(base.dtype)
                )
        return agents

    def apply_overrides(
        self, cs: ColonyState, overrides: Mapping | None
    ) -> ColonyState:
        """Set schema variables on an EXISTING colony state — the serve
        layer's fork point (docs/serving.md, "Prefix caching &
        forking"): a snapshot of a shared scenario prefix gets each
        fork's divergent parameters applied before the suffix runs.
        Same validation and scalar→rows broadcast as ``initial_state``'s
        ``overrides=``; everything not named is left exactly as the
        evolved state holds it."""
        if not overrides:
            return cs
        return cs._replace(
            agents=self._override_agents(cs.agents, overrides)
        )

    # -- stepping ------------------------------------------------------------

    def step_biology(self, cs: ColonyState, timestep: float) -> ColonyState:
        """Run every Process on every row (no division, no step counter).

        Shape-polymorphic over the row count (``cs.alive.shape[0]``), not
        pinned to ``self.capacity`` — so the same code steps a per-device
        block inside ``shard_map`` (lens_tpu.parallel.runner).
        """
        if self.compartment.has_stochastic:
            step_key = jax.random.fold_in(cs.key, cs.step)
            agent_keys = jax.random.split(step_key, cs.alive.shape[0])
            stepped = jax.vmap(
                lambda s, k: self.compartment.step(s, timestep, k)
            )(cs.agents, agent_keys)
        else:
            stepped = jax.vmap(
                lambda s: self.compartment.step(s, timestep)
            )(cs.agents)
        # Freeze dead rows: no NaN creep, bitwise determinism independent of
        # how many rows happen to be active.
        agents = jax.tree.map(
            lambda new, old: jnp.where(_bcast(cs.alive, new), new, old),
            stepped,
            cs.agents,
        )
        return cs._replace(agents=agents)

    def step_death(self, cs: ColonyState) -> ColonyState:
        """Clear the alive bit where the death trigger fired (no-op if
        disabled). Purely elementwise — shard-safe with no collectives —
        and freed rows rejoin the division pool immediately."""
        if self.death_trigger is None:
            return cs
        trig = get_path(cs.agents, self.death_trigger)
        return cs._replace(alive=cs.alive & ~(trig > 0))

    def step_division(self, cs: ColonyState) -> ColonyState:
        """Apply the lifecycle phase: deaths per the death trigger, then
        divisions per the division trigger (each a no-op if disabled).
        Death goes first so a row that both triggers name this step dies
        rather than divides, and its row frees up for OTHER parents."""
        cs = self.step_death(cs)
        if self.division_trigger is None:
            return cs
        key, sub = jax.random.split(cs.key)
        agents, alive = self._divide(cs.agents, cs.alive, sub, cs.step)
        return cs._replace(agents=agents, alive=alive, key=key)

    def step(self, cs: ColonyState, timestep: float) -> ColonyState:
        """One exchange-window step for the whole colony. Pure; jittable.

        Spatial wrappers call the two phases separately so exchange fluxes
        can be applied to the environment BETWEEN biology and division —
        otherwise the division dividers (exchange is ``_divider: zero``)
        would discard a window's uptake before the field is debited.
        """
        cs = self.step_biology(cs, timestep)
        cs = self.step_division(cs)
        return cs._replace(step=cs.step + 1)

    def run(
        self, cs: ColonyState, total_time: float, timestep: float, emit_every: int = 1
    ) -> Tuple[ColonyState, dict]:
        """Scan ``step`` over ``total_time``; emit colony slices periodically.

        The emitted trajectory carries ``alive`` alongside the agent slice so
        offline analysis can mask dead rows (SURVEY.md §5 emitter design).
        """
        return scan_schedule(
            lambda c: self.step(c, timestep), self.emit, cs,
            total_time, timestep, emit_every,
        )

    # -- capacity growth -----------------------------------------------------

    def expanded_meta(self, step_now: int, factor: int = 2) -> "Colony":
        """The metadata half of :meth:`expanded`: the grown ``Colony``
        (new capacity + lineage ``id_offset`` watermark), touching no
        arrays. Split out so the sharded expansion path
        (:func:`lens_tpu.parallel.mesh.expand_colony_rows_on_mesh`) can
        grow the state ON DEVICE, per shard, without the host gather
        that :meth:`expanded` implies for a mesh-sharded state.

        ``step_now`` is the colony's current step counter — the only
        piece of state the watermark needs (one scalar, locally
        addressable on every host of a multi-host mesh).
        """
        if factor < 2:
            raise ValueError(f"expansion factor must be >= 2, got {factor}")
        new_cap = self.capacity * int(factor)
        watermark = self.id_offset + (step_now + 1) * 2 * self.capacity
        # Lineage ids are int32 and the minting stride is 2*capacity per
        # step, so every expansion accelerates the march toward overflow.
        # Fail LOUDLY here (host-side, cheap) instead of letting ids wrap
        # negative and silently corrupt offline lineage reconstruction.
        headroom_steps = (2**31 - 1 - watermark) // (2 * new_cap)
        if headroom_steps < 10_000:
            raise ValueError(
                f"capacity expansion to {new_cap} rows leaves only "
                f"{headroom_steps} steps of int32 lineage-id headroom "
                f"(id watermark {watermark}); cap the colony size "
                f"(auto_expand max_capacity) or disable division lineage"
            )
        return Colony(
            self.compartment,
            new_cap,
            division_trigger=self.division_trigger,
            id_offset=watermark - (step_now + 1) * 2 * new_cap,
            death_trigger=self.death_trigger,
        )

    def expanded(
        self, cs: ColonyState, factor: int = 2
    ) -> Tuple["Colony", ColonyState]:
        """Grow the colony to ``factor * capacity`` rows (host-side, at a
        segment boundary) — the rebuild's answer to the reference's
        unbounded process spawning (SURVEY.md §3.3: the shepherd just
        forks more agents; a fixed-shape colony must instead re-allocate).

        Returns ``(bigger_colony, padded_state)``:

        - every agent leaf is padded with fresh template rows (schema
          defaults; a future daughter overwrites every leaf on arrival,
          so the padding never leaks into biology);
        - ``alive``/``step``/``key`` are preserved, so the trajectory up
          to the expansion point is bitwise identical to the unexpanded
          run, and the next step simply sees more free rows;
        - lineage ``row_id``/``cell_id`` padding continues the arange,
          and the new colony's ``id_offset`` is set to the old colony's
          id WATERMARK (the supremum of ids it could have minted through
          ``cs.step``), so ids minted at the new stride can never
          collide with any pre-expansion id.
        """
        grown = self.expanded_meta(int(cs.step), factor)
        new_cap = grown.capacity
        template = grown.initial_state(0).agents
        old_cap = self.capacity

        def pad(old, tmpl):
            return jnp.concatenate(
                [old, tmpl[old_cap:].astype(old.dtype)], axis=0
            )

        agents = jax.tree.map(pad, cs.agents, template)
        alive = jnp.concatenate(
            [cs.alive, jnp.zeros(new_cap - old_cap, bool)]
        )
        return grown, cs._replace(agents=agents, alive=alive)

    # -- division ------------------------------------------------------------

    def _divide(
        self,
        agents: dict,
        alive: jax.Array,
        key: jax.Array,
        step: jax.Array | int = 0,
    ) -> Tuple[dict, jax.Array]:
        """Vectorized division: all triggered rows split at once.

        Fixed-shape algorithm (no data-dependent shapes):
        1. ``triggers`` = alive rows whose trigger variable is nonzero.
        2. Free rows are enumerated with ``nonzero(size=capacity)``; the
           k-th triggering parent claims the k-th free row. Parents ranked
           beyond the number of free rows are suppressed (stay undivided).
        3. Every schema leaf is split by its declared divider into
           (daughter_a, daughter_b) for all rows; daughter A overwrites the
           parent row, daughter B is scattered to the claimed row.

        The whole body sits under ``lax.cond`` on "any row triggered": in
        typical dynamics divisions are rare per step, so most steps pay one
        reduction instead of the nonzero/cumsum/scatter pipeline.

        Shape-polymorphic: ``cap`` is the row count of the arrays passed
        in, so a shard_map block divides within its own rows (per-shard
        free-row pools — see lens_tpu.parallel.runner). ``lax.cond`` under
        shard_map branches per device block, which is exactly the wanted
        semantics (a shard with no divisions skips the work).
        """
        cap = alive.shape[0]
        trig_val = get_path(agents, self.division_trigger)
        triggers = alive & (trig_val > 0)

        def body(operand):
            agents, alive, key = operand
            free_rows = jnp.nonzero(~alive, size=cap, fill_value=cap)[0]
            n_free = jnp.sum(~alive)
            # rank of each triggering parent among triggers (0-based)
            rank = jnp.cumsum(triggers) - 1
            can_divide = triggers & (rank < n_free)
            # daughter slot per row (cap = "no slot"; scatter drops OOB)
            slot = jnp.where(
                can_divide, free_rows[jnp.clip(rank, 0, cap - 1)], cap
            )

            leaves = list(flatten_paths(agents))
            # zeros_like of a real split keeps this agnostic to the key
            # representation (legacy uint32 arrays vs typed jax.random.key)
            dummy = jnp.zeros_like(jax.random.split(key, cap))
            out = agents
            for i, (path, value) in enumerate(leaves):
                if path[0] == "lineage":
                    continue  # handled below, not by schema dividers
                name = self.compartment.dividers.get(path, "split")
                divider = DIVIDERS[name]
                # Key policy is declared on the divider itself (see
                # core.state: `_div_binomial.stochastic = True`); only
                # randomness-consuming dividers cost a threefry batch.
                if getattr(divider, "stochastic", False):
                    row_keys = jax.random.split(
                        jax.random.fold_in(key, i), cap
                    )
                else:
                    row_keys = dummy  # deterministic divider: key unused
                # vmap the scalar divider across the agent axis
                a, b = jax.vmap(divider)(value, row_keys)
                new_val = jnp.where(_bcast(can_divide, value), a, value)
                # scatter daughter B into claimed slots; 'drop' ignores
                # slot==cap (only can_divide rows have slot < cap, so
                # nothing else lands)
                new_val = new_val.at[slot].set(b, mode="drop")
                out = set_path(out, path, new_val)

            lin = agents.get("lineage")
            if lin is not None:
                # Both daughters are NEW cells: fresh ids minted from the
                # immutable global row_id so ids never collide across
                # steps or shards. Daughter A (parent's row) gets
                # base + row_id, daughter B (claimed slot) gets
                # base + capacity + row_id[slot]; bases advance by
                # 2*capacity per step, so id ranges are disjoint from the
                # founders' [0, capacity) and from every other step.
                # (int32: overflows after ~2^31/(2*capacity) steps —
                # ~20k steps at 50k capacity; ``expanded`` re-checks the
                # headroom on every capacity growth and fails loudly.)
                step32 = jnp.asarray(step, jnp.int32)
                base = jnp.int32(self.id_offset) + (step32 + 1) * jnp.int32(
                    2 * self.capacity
                )
                row_id = lin["row_id"]
                old_id = lin["cell_id"]
                slot_row = row_id[jnp.clip(slot, 0, cap - 1)]
                cell_id = jnp.where(can_divide, base + row_id, old_id)
                cell_id = cell_id.at[slot].set(
                    base + jnp.int32(self.capacity) + slot_row, mode="drop"
                )
                parent_id = jnp.where(can_divide, old_id, lin["parent_id"])
                parent_id = parent_id.at[slot].set(old_id, mode="drop")
                birth = jnp.where(can_divide, step32, lin["birth_step"])
                birth = birth.at[slot].set(step32, mode="drop")
                out = dict(
                    out,
                    lineage=dict(
                        lin,
                        cell_id=cell_id,
                        parent_id=parent_id,
                        birth_step=birth,
                    ),
                )

            return out, alive.at[slot].set(True, mode="drop")

        return lax.cond(
            jnp.any(triggers),
            body,
            lambda operand: (operand[0], operand[1]),
            (agents, alive, key),
        )

    # -- emission ------------------------------------------------------------

    def emit(self, cs_or_agents, alive: jax.Array | None = None) -> dict:
        """Colony emit slice: schema ``_emit`` paths + the alive mask."""
        if isinstance(cs_or_agents, ColonyState):
            agents, alive = cs_or_agents.agents, cs_or_agents.alive
        else:
            agents = cs_or_agents
            if alive is None:
                raise ValueError(
                    "emit(agents_dict) needs the alive mask explicitly"
                )
        out = self.compartment.emit(agents)
        out["alive"] = alive
        if "lineage" in agents:
            # cell/parent ids + birth step: the offline lineage-tree key
            # (analysis.lineage_table reconstructs generations from these)
            out["lineage"] = dict(agents["lineage"])
        if self.division_trigger is not None:
            # Saturation telemetry: rows still triggered after step_division
            # are parents whose division was suppressed (no free row). On a
            # sharded colony the per-shard free pools mean backlog can be
            # nonzero while other shards have free rows — this counter is
            # how that divergence from unsharded biology becomes visible.
            trig = get_path(agents, self.division_trigger)
            out["division_backlog"] = jnp.sum(alive & (trig > 0))
            out["free_rows"] = jnp.sum(~alive)
        return out

    #: Uniform emit-slice name across colony forms (SpatialColony and
    #: MultiSpeciesColony define emit_state too) — what Ensemble vmaps.
    emit_state = emit

    def n_alive(self, cs: ColonyState) -> jax.Array:
        return jnp.sum(cs.alive)
