from lens_tpu.colony.colony import Colony, ColonyState
from lens_tpu.colony.ensemble import Ensemble

__all__ = ["Colony", "ColonyState", "Ensemble"]
