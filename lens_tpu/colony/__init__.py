from lens_tpu.colony.colony import Colony, ColonyState

__all__ = ["Colony", "ColonyState"]
