"""Surrogate cells: trivial CellSimulations for plumbing tests.

The reference ships "surrogate" cell sims — near-trivial implementations
of the CellSimulation interface — so the actor/lattice machinery can be
exercised without real biology (reconstructed: ``lens/surrogates/``,
SURVEY.md §2, §4). The rebuild's equivalents plug into
``lens_tpu.bridge.HostExchangeLoop`` and serve the same role for the host
path (the device path is exercised by real Processes, which are cheap
there).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np


class ConstantUptakeSurrogate:
    """Consumes a fixed amount of one molecule per window. No dynamics."""

    def __init__(self, molecule: str = "glucose", uptake_per_s: float = 0.1):
        self.molecule = molecule
        self.uptake_per_s = float(uptake_per_s)
        self.local = 0.0
        self.time = 0.0
        self._consumed = 0.0

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        self.local = float(update.get(self.molecule, 0.0))

    def run_incremental(self, run_until: float) -> None:
        dt = run_until - self.time
        # cannot take more than is locally available
        self._consumed += min(self.uptake_per_s * dt, self.local)
        self.time = run_until

    def generate_inner_update(self) -> Dict[str, Any]:
        update = {"exchange": {self.molecule: -self._consumed}, "divide": False}
        self._consumed = 0.0
        return update

    def divide(self) -> Tuple["ConstantUptakeSurrogate", "ConstantUptakeSurrogate"]:
        raise NotImplementedError("this surrogate never divides")

    def finalize(self) -> None:
        pass


class GrowDivideSurrogate:
    """Doubles a volume counter at a fixed rate; divides at threshold.

    Exercises the host loop's division handshake (SURVEY.md §3.3) with
    zero biochemical content.
    """

    def __init__(self, volume: float = 1.0, rate: float = 0.02, threshold: float = 2.0):
        self.volume = float(volume)
        self.rate = float(rate)
        self.threshold = float(threshold)
        self.time = 0.0
        self.finalized = False

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        pass

    def run_incremental(self, run_until: float) -> None:
        dt = run_until - self.time
        self.volume *= float(np.exp(self.rate * dt))
        self.time = run_until

    def generate_inner_update(self) -> Dict[str, Any]:
        return {
            "exchange": {},
            "volume": self.volume,
            "divide": self.volume >= self.threshold,
        }

    def divide(self) -> Tuple["GrowDivideSurrogate", "GrowDivideSurrogate"]:
        half = self.volume / 2.0
        mk = lambda: GrowDivideSurrogate(  # noqa: E731
            half, self.rate, self.threshold
        )
        a, b = mk(), mk()
        a.time = b.time = self.time
        return a, b

    def finalize(self) -> None:
        self.finalized = True


__all__ = ["ConstantUptakeSurrogate", "GrowDivideSurrogate"]
