"""Surrogate cells: trivial CellSimulations for plumbing tests.

The reference ships "surrogate" cell sims — near-trivial implementations
of the CellSimulation interface — so the actor/lattice machinery can be
exercised without real biology (reconstructed: ``lens/surrogates/``,
SURVEY.md §2, §4). The rebuild's equivalents plug into
``lens_tpu.bridge.HostExchangeLoop`` and serve the same role for the host
path (the device path is exercised by real Processes, which are cheap
there).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np


class ConstantUptakeSurrogate:
    """Consumes a fixed amount of one molecule per window. No dynamics."""

    def __init__(self, molecule: str = "glucose", uptake_per_s: float = 0.1):
        self.molecule = molecule
        self.uptake_per_s = float(uptake_per_s)
        self.local = 0.0
        self.time = 0.0
        self._consumed = 0.0

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        self.local = float(update.get(self.molecule, 0.0))

    def run_incremental(self, run_until: float) -> None:
        dt = run_until - self.time
        # cannot take more than is locally available
        self._consumed += min(self.uptake_per_s * dt, self.local)
        self.time = run_until

    def generate_inner_update(self) -> Dict[str, Any]:
        update = {"exchange": {self.molecule: -self._consumed}, "divide": False}
        self._consumed = 0.0
        return update

    def divide(self) -> Tuple["ConstantUptakeSurrogate", "ConstantUptakeSurrogate"]:
        raise NotImplementedError("this surrogate never divides")

    def finalize(self) -> None:
        pass


class GrowDivideSurrogate:
    """Doubles a volume counter at a fixed rate; divides at threshold.

    Exercises the host loop's division handshake (SURVEY.md §3.3) with
    zero biochemical content.
    """

    def __init__(self, volume: float = 1.0, rate: float = 0.02, threshold: float = 2.0):
        self.volume = float(volume)
        self.rate = float(rate)
        self.threshold = float(threshold)
        self.time = 0.0
        self.finalized = False

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        pass

    def run_incremental(self, run_until: float) -> None:
        dt = run_until - self.time
        self.volume *= float(np.exp(self.rate * dt))
        self.time = run_until

    def generate_inner_update(self) -> Dict[str, Any]:
        return {
            "exchange": {},
            "volume": self.volume,
            "divide": self.volume >= self.threshold,
        }

    def divide(self) -> Tuple["GrowDivideSurrogate", "GrowDivideSurrogate"]:
        half = self.volume / 2.0
        mk = lambda: GrowDivideSurrogate(  # noqa: E731
            half, self.rate, self.threshold
        )
        a, b = mk(), mk()
        a.time = b.time = self.time
        return a, b

    def finalize(self) -> None:
        self.finalized = True


class ChemotaxisSurrogate:
    """Run/tumble motility chasing an attractant gradient — the
    reference's chemotaxis surrogate, host-path edition.

    Temporal sensing like the real machinery's logic, minus all
    biochemistry: keep heading while the local attractant concentration
    rises (run), draw a fresh random heading when it falls (tumble).
    Reports its new ``location`` each window (the host loop applies and
    clips it).
    """

    def __init__(
        self,
        location,
        molecule: str = "glucose",
        speed: float = 1.0,
        seed: int = 0,
        domain=None,
    ):
        self.location = np.asarray(location, np.float64)
        self.molecule = molecule
        self.speed = float(speed)
        # Physical domain (h, w) in um: the sim clips its OWN location so
        # its internal position never desyncs from the loop-clipped agent
        # (otherwise a wall-pinned cell keeps integrating outward and its
        # temporal sensing compares concentrations against motion it
        # never made).
        self.domain = (
            np.asarray(domain, np.float64) if domain is not None else None
        )
        self._rng = np.random.default_rng(seed)
        theta = self._rng.uniform(0.0, 2.0 * np.pi)
        self._heading = np.asarray([np.cos(theta), np.sin(theta)])
        self._last = None
        self._local = 0.0
        self.time = 0.0

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        self._local = float(update.get(self.molecule, 0.0))

    def run_incremental(self, run_until: float) -> None:
        dt = run_until - self.time
        if self._last is not None and self._local < self._last:
            theta = self._rng.uniform(0.0, 2.0 * np.pi)  # tumble
            self._heading = np.asarray([np.cos(theta), np.sin(theta)])
        self._last = self._local
        self.location = self.location + self.speed * dt * self._heading
        if self.domain is not None:
            self.location = np.clip(
                self.location, 0.0, self.domain - 1e-3
            )
        self.time = run_until

    def generate_inner_update(self) -> Dict[str, Any]:
        return {
            "exchange": {},
            "location": self.location.copy(),
            "divide": False,
        }

    def divide(self):
        raise NotImplementedError("this surrogate never divides")

    def finalize(self) -> None:
        pass


__all__ = [
    "ConstantUptakeSurrogate",
    "GrowDivideSurrogate",
    "ChemotaxisSurrogate",
]
