"""lens_tpu.serve: continuous-batching scenario serving.

One resident jitted multi-lane window program per (composite, shape)
bucket; a host scheduler packs many small user scenarios — each with its
own seed, parameter overrides, horizon, and emit spec — into fixed
vmapped lanes, with bounded-queue backpressure, deadlines, cancellation,
and counters. See docs/serving.md for the architecture and the
determinism contract.
"""

from lens_tpu.serve.batcher import (
    BATCH,
    CANCELLED,
    DONE,
    FAILED,
    INTERACTIVE,
    MIGRATED,
    PRIORITIES,
    QUEUED,
    QueueFull,
    RequestValidationError,
    RUNNING,
    SimulationDiverged,
    TIMEOUT,
    ScenarioRequest,
)
from lens_tpu.serve.faults import FaultPlan
from lens_tpu.serve.lanes import LanePool
from lens_tpu.serve.metrics import ServerMetrics, write_server_meta
from lens_tpu.serve.server import SimServer
from lens_tpu.serve.snapshots import SnapshotStore, snapshot_key
from lens_tpu.serve.streamer import Streamer, WatchdogTimeout
from lens_tpu.serve.tiers import TieredSnapshotStore
from lens_tpu.serve.wal import ServeWal

__all__ = [
    "BATCH",
    "CANCELLED",
    "DONE",
    "FAILED",
    "INTERACTIVE",
    "MIGRATED",
    "PRIORITIES",
    "QUEUED",
    "FaultPlan",
    "QueueFull",
    "RequestValidationError",
    "RUNNING",
    "TIMEOUT",
    "LanePool",
    "ScenarioRequest",
    "ServeWal",
    "ServerMetrics",
    "SimServer",
    "SimulationDiverged",
    "SnapshotStore",
    "Streamer",
    "TieredSnapshotStore",
    "WatchdogTimeout",
    "snapshot_key",
    "write_server_meta",
]
