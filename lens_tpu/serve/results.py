"""Durable content-addressed RESULT cache: a CDN for simulations.

The serving determinism contract (pinned since round 8, re-pinned at
every layer since: solo == co-batched == pipelined == mesh-placed,
bit for bit) means a request's ``.lens`` log is a pure function of
``(bucket config, seed, overrides, n_agents, horizon, emit, prefix)``.
That makes whole RESULTS cacheable the same way round 16 made prefix
STATES cacheable: a completed request's log, filed under the request's
content address, can serve every later identical submission with zero
device windows and zero lanes — the submit short-circuits admission
entirely and clients replay the bytes.

Three pieces live here:

- :func:`request_fingerprint` — the content address: sha256 over the
  bytes-relevant coordinates of a request's canonical WAL-JSON form
  (``serve.server._request_to_json``). ``deadline``/``tenant``/
  ``priority``/``hold_state`` never touch the emitted bytes and are
  excluded, so requests differing only in those hit the same entry.
  Spelling-level aliases (``seed: 3.0`` vs ``3``, override dict
  ordering, ``emit: {"every": 1}`` vs no emit block) are folded by
  ``ScenarioRequest.from_mapping``'s canonicalization BEFORE the
  request reaches serialization — one spelling in, one key out.
- :class:`ResultCache` — the disk store, the exact protocol of the
  snapshot disk tier (``serve/tiers.py``): payload written to a
  per-pid tmp name then ``os.replace``'d (readers see whole entries
  or nothing), a ``.meta.json`` sidecar written after the payload (a
  sidecar attests a complete entry; a kill between the two leaves a
  harmless orphan the scan ignores), a construction-time scan that
  re-adopts every complete entry (restart-warm, like
  ``BENCH_TIER_CPU_r16.json``'s 0-miss restart row), and a bucket
  fingerprint guard (``result_meta.json``) refusing entries recorded
  under a bits-relevant different bucket config. Its byte budget and
  LRU eviction are its own — result bytes never compete with snapshot
  tiers for budget.
- :meth:`ResultCache.serve` — the replay: the cached log's bytes are
  copied to the hitting request's own ``<rid>.lens`` with ONE frame
  rewritten — the header, which embeds the experiment id (= the
  donor's rid) and so must be re-minted for the hitting rid. Every
  frame after the header is rid-free (SEGMENT records carry only
  trajectory + times), so the spliced copy is byte-identical to what
  the hitting request's own solo run would have written
  (``tests/test_results.py`` pins it), and ``tail_frames`` replay /
  the front door's SSE stream serve it unchanged.

See docs/serving.md, "Suffix dedup & result cache".
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from lens_tpu.emit.log import (
    SEP,
    encode_record,
    frame,
    iter_frames,
    make_header,
)
from lens_tpu.utils import flatten_paths

#: The cache directory's identity file — the same guard as the snapshot
#: tier dir's ``tier_meta.json``: a result's content address includes
#: the bucket NAME, not its bits-relevant config, so the directory
#: itself carries the bucket fingerprint and a mismatch is refused.
RESULT_META = "result_meta.json"

_META_SUFFIX = ".meta.json"
_ENTRY_PREFIX = "res_"
_ENTRY_SUFFIX = ".lens"

#: Request keys that shape the emitted bytes. Everything else
#: (deadline, tenant, priority, hold_state) is scheduling/billing
#: policy: two requests differing only there stream identical records,
#: so they SHARE a cache entry and an in-flight dedup lane.
_BYTES_RELEVANT = (
    "composite", "seed", "horizon", "overrides", "n_agents", "emit",
    "prefix",
)


def request_fingerprint(payload: Mapping[str, Any]) -> str:
    """The request's result content address: sha256 hex over the
    bytes-relevant keys of its canonical WAL-JSON form
    (``_request_to_json`` output — the same mapping ``submit``
    accepts). ``json.dumps(sort_keys=True)`` canonicalizes mapping
    order recursively, so override trees hash identically however
    their dicts were built; value-level aliases are already folded by
    ``ScenarioRequest.from_mapping``."""
    core = {
        k: payload[k]
        for k in _BYTES_RELEVANT
        if payload.get(k) is not None
    }
    blob = json.dumps(
        core, sort_keys=True, separators=(",", ":")
    ).encode()
    return hashlib.sha256(blob).hexdigest()


def log_config(request) -> Dict[str, Any]:
    """The ``.lens`` header config for one request — the ONE encoding
    of a request into its log's self-description, shared by the live
    sink (``SimServer._make_sink``) and cache replay's header splice
    (:meth:`ResultCache.serve`), so a cache hit's header is byte-equal
    to the one the hitting request's own run would have written."""
    req = request
    return {
        "composite": req.composite,
        "seed": int(req.seed),
        "horizon": float(req.horizon),
        "n_agents": req.n_agents,
        "overrides": {
            SEP.join(map(str, p)): np.asarray(v).tolist()
            for p, v in flatten_paths(req.overrides or {})
        },
        "emit": dict(req.emit or {}),
        # a forked run's rows are SUFFIX-only with divergent
        # overrides applied at the fork point — without the prefix
        # declaration the file would misdescribe itself as a full
        # t=0 run
        "prefix": (
            {
                "horizon": float(req.prefix["horizon"]),
                "overrides": {
                    SEP.join(map(str, p)): np.asarray(v).tolist()
                    for p, v in flatten_paths(
                        req.prefix.get("overrides") or {}
                    )
                },
            }
            if req.prefix
            else None
        ),
    }


@dataclass
class _Entry:
    fingerprint: str
    nbytes: int
    used: float  # last-use wall stamp (LRU order; survives restarts)
    hits: int = 0
    created: float = 0.0
    request: Optional[Dict[str, Any]] = field(default=None)


class ResultCache:
    """Content-addressed ``.lens`` result store over one directory.

    Single-writer-per-entry by content address (identical fingerprints
    write identical bytes, so concurrent writers racing one entry are
    harmless — last rename wins with the same content); multi-process
    tolerant the same way the shared snapshot tier dir is: per-pid tmp
    names, ``os.replace`` publication, and every read path treating a
    vanished file (a peer's eviction) as a plain miss.

    Parameters
    ----------
    dir:
        The cache directory (created if missing). One
        ``res_<digest>.lens`` payload + ``.meta.json`` sidecar per
        entry, plus the ``result_meta.json`` fingerprint guard.
    budget_bytes:
        Byte budget over payload sizes (None = unbounded). Past it,
        least-recently-USED entries are deleted — results have no
        lower tier to demote to.
    fingerprint:
        The server's bits-relevant bucket fingerprint
        (``serve.wal.buckets_fingerprint``); verified against (or
        pinned into) ``result_meta.json``. ``None`` skips the check —
        the inspection CLI's mode, which never serves hits.
    """

    def __init__(
        self,
        dir: str,
        budget_bytes: Optional[int] = None,
        fingerprint: Optional[str] = None,
    ):
        if budget_bytes is not None and budget_bytes <= 0:
            raise ValueError(
                f"budget_bytes={budget_bytes} must be > 0 (or None "
                f"for unbounded)"
            )
        self.dir = os.path.abspath(dir)
        self.budget_bytes = budget_bytes
        os.makedirs(self.dir, exist_ok=True)
        if fingerprint is not None:
            self._check_fingerprint(fingerprint)
        self._entries: Dict[str, _Entry] = {}
        # lifetime tallies (delta-synced into the server's metrics
        # registry at gauge refresh, like the snapshot store's)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stored = 0
        # fault seams (tests): set by the owning server so a FaultPlan
        # kill can land between the tmp write and the rename
        self.faults: Any = None
        self._scan()

    # -- directory protocol (the tiers.py idioms) ----------------------------

    def _check_fingerprint(self, fingerprint: str) -> None:
        path = os.path.join(self.dir, RESULT_META)
        if os.path.exists(path):
            with open(path) as f:
                have = json.load(f).get("fingerprint")
            if have != fingerprint:
                raise ValueError(
                    f"{self.dir} holds results for a server with "
                    f"bucket fingerprint {have!r}, not "
                    f"{fingerprint!r} — the bucket configuration "
                    f"changed in a bits-relevant way, so its cached "
                    f"results would replay a different simulation. "
                    f"Use a fresh results dir (or restore the "
                    f"original buckets)."
                )
            return
        # per-pid tmp: cluster workers and the router construct their
        # caches over ONE shared dir concurrently at bring-up; a
        # shared tmp name would let one replace consume another's file
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"fingerprint": fingerprint}, f)
        os.replace(tmp, path)

    def _name(self, fp: str) -> str:
        return f"{_ENTRY_PREFIX}{fp[:32]}{_ENTRY_SUFFIX}"

    def _path(self, fp: str) -> str:
        return os.path.join(self.dir, self._name(fp))

    def _write_sidecar(self, fp: str, entry: _Entry) -> None:
        path = self._path(fp) + _META_SUFFIX
        tmp = f"{path}.tmp-{os.getpid()}"
        payload = {
            "fingerprint": fp,
            "nbytes": int(entry.nbytes),
            "created": entry.created,
            "used": entry.used,
            "hits": int(entry.hits),
        }
        if entry.request is not None:
            payload["request"] = entry.request
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    def _scan(self) -> None:
        """Adopt every complete entry the directory already holds —
        the restart-warm path. Torn entries (payload without its
        sidecar: a kill between the payload rename and the sidecar
        write) are skipped; the rename protocol guarantees a present
        payload whose sidecar exists was completely WRITTEN, and the
        size check guards the unsynced-page-cache case (``put`` does
        not fsync): a payload truncated by a host crash disagrees
        with the byte count its sidecar recorded and is demoted to a
        miss."""
        for meta in sorted(glob.glob(os.path.join(
            self.dir, f"{_ENTRY_PREFIX}*{_ENTRY_SUFFIX}{_META_SUFFIX}"
        ))):
            try:
                with open(meta) as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # torn sidecar: the entry never happened
            fp = data.get("fingerprint")
            if not fp or fp in self._entries:
                continue
            payload = meta[: -len(_META_SUFFIX)]
            try:
                if os.path.getsize(payload) != int(
                    data.get("nbytes", -1)
                ):
                    continue  # truncated by a host crash: a miss
            except OSError:
                continue  # sidecar outlived its payload
            self._entries[fp] = _Entry(
                fingerprint=fp,
                nbytes=int(data.get("nbytes", 0)),
                used=float(data.get("used", 0.0)),
                hits=int(data.get("hits", 0)),
                created=float(data.get("created", 0.0)),
                request=data.get("request"),
            )

    def refresh(self, fp: str) -> bool:
        """Adopt ONE fingerprint published by a peer process since our
        scan (cluster workers and the router share a results dir; the
        rename protocol makes a complete entry visible atomically).
        Returns True if ``fp`` is now resident. Cheap enough for the
        miss path: one stat pair on a miss, nothing on a hit."""
        if fp in self._entries:
            return True
        meta = self._path(fp) + _META_SUFFIX
        try:
            with open(meta) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False
        if data.get("fingerprint") != fp:
            return False
        try:
            if os.path.getsize(self._path(fp)) != int(
                data.get("nbytes", -1)
            ):
                return False  # truncated by a host crash (see _scan)
        except OSError:
            return False
        self._entries[fp] = _Entry(
            fingerprint=fp,
            nbytes=int(data.get("nbytes", 0)),
            used=float(data.get("used", 0.0)),
            hits=int(data.get("hits", 0)),
            created=float(data.get("created", 0.0)),
            request=data.get("request"),
        )
        return True

    # -- size / inspection ---------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, fp: str) -> bool:
        return fp in self._entries

    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def entries(self) -> List[Dict[str, Any]]:
        """One inspection row per entry (the ``cache`` CLI's table),
        LRU-first — the order eviction would take them."""
        now = time.time()
        out = []
        for fp, e in sorted(
            self._entries.items(), key=lambda kv: kv[1].used
        ):
            req = e.request or {}
            out.append({
                "fingerprint": fp,
                "name": self._name(fp),
                "nbytes": e.nbytes,
                "hits": e.hits,
                "age_s": max(now - e.created, 0.0) if e.created else None,
                "idle_s": max(now - e.used, 0.0) if e.used else None,
                "composite": req.get("composite"),
                "horizon": req.get("horizon"),
            })
        return out

    # -- writes --------------------------------------------------------------

    def put(
        self,
        fp: str,
        src_path: str,
        request: Optional[Mapping[str, Any]] = None,
    ) -> bool:
        """File one completed request's log under its fingerprint:
        copy to a per-pid tmp name, rename, THEN write the sidecar —
        a kill anywhere in between leaves either nothing or an orphan
        payload the scan ignores, never a half-entry that could
        serve. No fsync on purpose: this runs on the scheduler thread
        between ticks, an fsync per completed request measurably taxes
        the all-miss path (bench_serve --cdn pins it <=2%), and the
        cache is a rebuildable optimization, not the recovery record
        — against process death the rename ordering alone holds, and
        a HOST crash that tears page cache can at worst truncate a
        payload, which the scan demotes to a miss by checking it
        against the sidecar's byte count. Idempotent per fingerprint
        (the content address guarantees a present entry's bytes
        match). Returns whether a new entry was filed."""
        if fp in self._entries:
            return False
        dst = self._path(fp)
        tmp = f"{dst}.tmp-{os.getpid()}"
        try:
            nbytes = os.path.getsize(src_path)
            shutil.copyfile(src_path, tmp)
            if self.faults is not None:
                # seam for the SIGKILL-mid-write drill: the payload
                # exists only under its tmp name here — a scan must
                # see no entry
                self.faults.kill("result.tmp_written")
            os.replace(tmp, dst)
            if self.faults is not None:
                # payload renamed, sidecar not yet written: an orphan
                # payload the scan skips (and a rerun re-files over)
                self.faults.kill("result.renamed")
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        now = time.time()
        entry = _Entry(
            fingerprint=fp,
            nbytes=int(nbytes),
            used=now,
            created=now,
            request=dict(request) if request is not None else None,
        )
        self._write_sidecar(fp, entry)
        self._entries[fp] = entry
        self.stored += 1
        self._evict_to_budget()
        return True

    def _evict_to_budget(self) -> None:
        if self.budget_bytes is None:
            return
        self._shrink_to(self.budget_bytes)

    def _shrink_to(self, max_bytes: int) -> List[str]:
        """Delete least-recently-used entries until total payload
        bytes fit ``max_bytes``; returns the evicted fingerprints.
        Deletion order is payload first, then sidecar — the reverse of
        publication, so a kill mid-evict leaves a sidecar-without-
        payload the scan already skips."""
        evicted: List[str] = []
        by_lru = sorted(
            self._entries.items(), key=lambda kv: kv[1].used
        )
        total = self.total_bytes()
        for fp, e in by_lru:
            if total <= max_bytes:
                break
            path = self._path(fp)
            for victim in (path, path + _META_SUFFIX):
                try:
                    os.remove(victim)
                except OSError:
                    pass  # a peer already evicted it
            del self._entries[fp]
            total -= e.nbytes
            self.evictions += 1
            evicted.append(fp)
        return evicted

    def gc(self, max_bytes: int) -> List[str]:
        """Explicit LRU eviction down to ``max_bytes`` (the ``cache``
        CLI's ``--max-mb``); returns the evicted fingerprints."""
        return self._shrink_to(max(int(max_bytes), 0))

    # -- reads ---------------------------------------------------------------

    def serve(
        self, fp: str, rid: str, config: Mapping[str, Any], dst: str
    ) -> bool:
        """Replay one cached result as ``rid``'s own log at ``dst``:
        every frame copied verbatim except the first — the header,
        re-minted for the hitting rid via :func:`log_config`'s shared
        encoding (so the spliced file is byte-equal to the rid's own
        solo run). Written tmp+rename like every other artifact, so a
        kill mid-replay leaves no torn ``<rid>.lens`` for recovery to
        trust. Any failure (entry vanished under a peer's eviction, a
        torn donor) degrades to a MISS — the caller falls through to
        the normal admission path."""
        entry = self._entries.get(fp)
        if entry is None:
            self.misses += 1
            return False
        src = self._path(fp)
        tmp = f"{dst}.tmp-{os.getpid()}"
        # a restart-warm server may hit before any cold run created
        # its out dir (real sinks make it lazily)
        parent = os.path.dirname(dst)
        if parent:
            os.makedirs(parent, exist_ok=True)
        try:
            frames = iter_frames(src, with_offsets=True)
            try:
                _, first_end = next(frames)
            finally:
                frames.close()
            with open(tmp, "wb") as out:
                out.write(frame(encode_record(
                    make_header(rid, config)
                )))
                with open(src, "rb") as inp:
                    inp.seek(first_end)
                    shutil.copyfileobj(inp, out)
                out.flush()
                os.fsync(out.fileno())
            os.replace(tmp, dst)
        except (OSError, ValueError, StopIteration):
            # vanished/torn donor: forget it so later submits miss
            # cleanly and recompute (the prewarm torn-spill repair)
            try:
                os.remove(tmp)
            except OSError:
                pass
            self._entries.pop(fp, None)
            self.misses += 1
            return False
        entry.used = time.time()
        entry.hits += 1
        self.hits += 1
        try:
            # best-effort: the sidecar's hit/used stamps feed the CLI
            # table and cross-restart LRU; losing one update is fine
            self._write_sidecar(fp, entry)
        except OSError:
            pass
        return True
