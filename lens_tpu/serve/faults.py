"""Deterministic fault injection for the serve stack.

A production server meets three failure classes the happy path never
exercises: physics that diverges to NaN/Inf inside a lane, I/O that
fails or hangs under the scheduler (a full disk, a wedged sink), and
the process dying outright (OOM killer, preemption, deploy). Testing
the recovery machinery against them requires the faults to be
REPRODUCIBLE — a chaos test that only fails sometimes is worse than no
test — so this module is a declarative, seeded fault schedule threaded
through the server's named seams, not a monkeypatching grab-bag.

A :class:`FaultPlan` holds a list of faults; each names the seam it
arms, an optional request filter, and an occurrence index (the N-th
time the seam fires with a matching context), so a given plan replays
identically against a given request schedule. The optional ``p``
(with the plan seed) makes probabilistic chaos runs replayable too:
same seed, same call sequence, same faults.

Fault kinds and their seams:

- ``nan`` (seam ``lane.state``): poison the matched request's lane
  with a NaN before the next window dispatch
  (``LanePool.poison_lane``) — the divergence injector the
  ``check_finite`` quarantine is pinned against.
- ``io_error`` (seam ``sink.append``): raise ``OSError`` from the
  matched request's sink append on the stream path — exercises
  stream-error propagation and close-on-exception.
- ``stall`` (seam ``stream.window``): sleep ``seconds`` inside the
  stream thread's window processing — exercises backpressure and the
  scheduler watchdog.
- ``device_down`` (seam ``shard.window``): declare a whole device
  dead at the N-th window dispatched on it (optionally filtered to
  one ``shard``) — the mesh server quarantines the device, drains it
  from scheduling, and re-queues its requests onto survivors
  (docs/serving.md, "Mesh serving & device failover"); the
  kill-one-device drill injector.
- ``host_down`` (seam ``cluster.host``): declare a whole HOST dead at
  the N-th cluster-router health pass over it (optionally filtered to
  one ``host``) — the router SIGKILLs a spawned worker process (or
  marks an in-process simulated host dead), drains it from routing,
  and fails its WAL-known work over to the surviving hosts
  (docs/serving.md, "Cluster serving"); the kill-one-host drill
  injector.
- ``kill`` (any seam in :data:`KILL_SEAMS`): ``SIGKILL`` the process
  at a named scheduler/WAL seam — the crash-recovery pins
  (tests/test_recovery.py) SIGKILL at every one of these and require
  the recovered results bitwise equal to an uninterrupted run's.

See docs/serving.md, "Fault tolerance & recovery".
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence

#: Seams at which a ``kill`` fault may SIGKILL the process. Each sits
#: just AFTER a durability step, so the recovery contract is tested at
#: the exact boundaries where a real crash is most informative.
KILL_SEAMS = (
    "submit.walled",     # submit WAL event written, rid about to return
    "resubmit.walled",   # continuation WAL event written
    "admitted",          # request scattered into a lane
    "window.dispatched",  # device window program enqueued
    "hold.spilled",      # held snapshot spilled + WAL hold event written
    "retired.walled",    # terminal status WAL event written
    "streamed.walled",   # stream-completion WAL event written (stream thread)
    # result-cache publication protocol (serve/results.py) — these fire
    # only when the server runs with result_cache_mb set:
    "result.tmp_written",  # payload copied to tmp name, not yet renamed
    "result.renamed",      # payload renamed, sidecar not yet written
    "result.cached",       # sidecar written: the entry is complete
)

#: Default seam per fault kind (a fault may override ``at`` only for
#: ``kill``, which must name one of KILL_SEAMS).
_KIND_SEAMS = {
    "nan": "lane.state",
    "io_error": "sink.append",
    "stall": "stream.window",
    "device_down": "shard.window",
    "host_down": "cluster.host",
}

_FAULT_KEYS = {
    "kind", "at", "request", "after_steps", "occurrence", "seconds",
    "p", "shard", "host",
}


@dataclass
class Fault:
    """One armed fault. ``occurrence`` is 1-based over matching seam
    firings (0 = every matching firing); ``after_steps`` (``nan`` only)
    defers matching until the request's sim-step counter reaches it;
    ``p`` arms the fault probabilistically per matching firing, drawn
    from the plan's seeded stream."""

    kind: str
    at: str
    request: Optional[str] = None
    after_steps: int = 0
    occurrence: int = 1
    seconds: float = 0.0
    p: Optional[float] = None
    shard: Optional[int] = None  # device_down: which device (None=any)
    _count: int = field(default=0, repr=False)
    _done: bool = field(default=False, repr=False)


class FaultPlan:
    """A deterministic schedule of injected faults.

    Construct from a list of fault dicts (see module docstring) plus a
    seed for the probabilistic stream, or :meth:`from_spec` for the
    CLI/JSON form ``{"seed": 0, "faults": [...]}`` (a bare list is
    accepted too). An empty plan is falsy and every hook is a no-op,
    so production servers carry ``FaultPlan(None)`` at zero cost.
    """

    def __init__(
        self,
        faults: Optional[Sequence[Mapping[str, Any]]] = None,
        seed: int = 0,
    ):
        import numpy as np

        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self._lock = threading.Lock()
        # a span tracer (lens_tpu.obs) the owning server installs:
        # every FIRED fault becomes an instant on the timeline, so a
        # chaos run's injections line up visually with the quarantines
        # and requeues they caused. None / NullTracer = no emission.
        self.trace: Any = None
        self.faults: List[Fault] = []
        for i, f in enumerate(faults or []):
            f = dict(f)
            unknown = set(f) - _FAULT_KEYS
            if unknown:
                raise ValueError(
                    f"fault {i}: unknown keys {sorted(unknown)}; known: "
                    f"{sorted(_FAULT_KEYS)}"
                )
            kind = f.get("kind")
            if kind == "kill":
                at = f.get("at")
                if at not in KILL_SEAMS:
                    raise ValueError(
                        f"fault {i}: kill fault needs 'at' naming a "
                        f"kill seam; known: {list(KILL_SEAMS)}"
                    )
                if f.get("request") is not None:
                    # kill seams fire with no request context, so a
                    # request filter would silently never match — the
                    # exact no-op chaos this harness exists to prevent
                    raise ValueError(
                        f"fault {i}: kill faults cannot filter by "
                        f"request (kill seams are scheduler-wide; "
                        f"use 'occurrence' to target the N-th firing)"
                    )
            elif kind in _KIND_SEAMS:
                at = f.get("at", _KIND_SEAMS[kind])
                if at != _KIND_SEAMS[kind]:
                    raise ValueError(
                        f"fault {i}: kind {kind!r} fires at seam "
                        f"{_KIND_SEAMS[kind]!r}, not {at!r}"
                    )
            else:
                raise ValueError(
                    f"fault {i}: unknown kind {kind!r}; known: "
                    f"{sorted([*_KIND_SEAMS, 'kill'])}"
                )
            p = f.get("p")
            if p is not None and not 0.0 < float(p) <= 1.0:
                raise ValueError(f"fault {i}: p={p} must be in (0, 1]")
            shard = f.get("shard")
            if shard is not None:
                if kind != "device_down":
                    raise ValueError(
                        f"fault {i}: 'shard' only applies to "
                        f"device_down faults (kind {kind!r} has no "
                        f"device context)"
                    )
                if int(shard) < 0:
                    raise ValueError(
                        f"fault {i}: shard={shard} must be >= 0"
                    )
            host = f.get("host")
            if host is not None:
                if kind != "host_down":
                    raise ValueError(
                        f"fault {i}: 'host' only applies to "
                        f"host_down faults (kind {kind!r} has no "
                        f"host context)"
                    )
                if int(host) < 0:
                    raise ValueError(
                        f"fault {i}: host={host} must be >= 0"
                    )
                # the generic matcher's shard slot doubles as the host
                # index (both are "which failure domain" filters)
                shard = host
            if kind in ("device_down", "host_down") \
                    and f.get("request") is not None:
                raise ValueError(
                    f"fault {i}: {kind} faults target a failure "
                    f"domain, not a request (use "
                    f"'{'host' if kind == 'host_down' else 'shard'}'"
                    f"/'occurrence')"
                )
            self.faults.append(Fault(
                kind=str(kind),
                at=str(at),
                request=f.get("request"),
                after_steps=int(f.get("after_steps", 0)),
                occurrence=int(f.get("occurrence", 1)),
                seconds=float(f.get("seconds", 0.0)),
                p=None if p is None else float(p),
                shard=None if shard is None else int(shard),
            ))

    @classmethod
    def from_spec(cls, spec: Any) -> "FaultPlan":
        """Build from the JSON form: a list of fault dicts, or
        ``{"seed": s, "faults": [...]}``, or a path to a JSON file
        holding either. ``None`` yields an empty (no-op) plan."""
        if spec is None:
            return cls(None)
        if isinstance(spec, str):
            with open(spec) as f:
                spec = json.load(f)
        if isinstance(spec, Mapping):
            unknown = set(spec) - {"seed", "faults"}
            if unknown:
                raise ValueError(
                    f"unknown fault-plan keys {sorted(unknown)}; known: "
                    f"seed, faults"
                )
            return cls(spec.get("faults"), seed=spec.get("seed", 0))
        return cls(spec)

    def __bool__(self) -> bool:
        return bool(self.faults)

    # -- the generic matcher -------------------------------------------------

    def fire(
        self,
        seam: str,
        request_id: Optional[str] = None,
        steps: Optional[int] = None,
        shard: Optional[int] = None,
    ) -> List[Fault]:
        """Faults firing NOW at ``seam`` for this context. Occurrence
        counters advance on every MATCH (seam + request + after_steps
        + shard), fired-or-not, so a plan's N-th-occurrence semantics
        are a pure function of the call sequence — deterministic and
        replayable."""
        if not self.faults:
            return []
        out: List[Fault] = []
        with self._lock:
            for f in self.faults:
                if f._done or f.at != seam:
                    continue
                if f.request is not None and request_id != f.request:
                    continue
                if f.shard is not None and shard != f.shard:
                    continue
                if f.after_steps and (
                    steps is None or steps < f.after_steps
                ):
                    continue
                f._count += 1
                if f.occurrence and f._count != f.occurrence:
                    continue
                if f.p is not None and self._rng.random() >= f.p:
                    continue
                if f.occurrence:
                    f._done = True
                out.append(f)
        if out and self.trace:
            # outside the lock: the tracer serializes internally, and
            # a kill fault's instant may be lost with the buffered
            # tail — the injection is visible via its WAL/quarantine
            # consequences either way
            for f in out:
                self.trace.instant(
                    "fault.injected", kind=f.kind, seam=seam,
                    rid=request_id, shard=shard,
                )
        return out

    # -- seam helpers (what the server/streamer actually call) ---------------

    def kill(self, seam: str) -> None:
        """SIGKILL the process if a kill fault fires at ``seam`` — the
        real signal, not an exception: no handler, no cleanup, no
        atexit, exactly what the recovery machinery must survive."""
        if self.fire(seam):
            os.kill(os.getpid(), signal.SIGKILL)

    def stall(self, seam: str) -> None:
        """Sleep out any stall faults firing at ``seam``."""
        for f in self.fire(seam):
            time.sleep(f.seconds)

    def io_error(self, seam: str, request_id: Optional[str]) -> None:
        """Raise an injected OSError if an io_error fault fires."""
        if self.fire(seam, request_id=request_id):
            raise OSError(
                f"injected sink I/O failure ({seam}, "
                f"request {request_id})"
            )

    def poison(self, request_id: str, steps: int) -> bool:
        """True when a nan fault fires for this request at this step
        count (the server then poisons the lane before the next window
        dispatch)."""
        return bool(self.fire("lane.state", request_id, steps))

    def device_down(self, shard: int) -> bool:
        """True when a device_down fault fires for this shard at this
        window dispatch (the server then quarantines the whole device
        — drains it from scheduling and fails its work over to the
        surviving shards). The seam fires once per window-dispatch
        attempt per shard, so ``occurrence`` counts that shard's
        windows."""
        return bool(self.fire("shard.window", shard=shard))

    def host_down(self, host: int) -> bool:
        """True when a host_down fault fires for this host at this
        cluster-router health pass (the router then kills/drains the
        host and fails its WAL-known work over to the survivors —
        docs/serving.md, "Cluster serving"). The seam fires once per
        router tick per live host, so ``occurrence`` counts that
        host's health passes."""
        return bool(self.fire("cluster.host", shard=host))
