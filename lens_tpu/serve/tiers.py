"""Tiered snapshot store: device -> host RAM -> disk paging for
scenario prefixes, with durable disk entries that survive restarts.

Round 11's :class:`~lens_tpu.serve.snapshots.SnapshotStore` treats
device RAM as the only home a snapshot can have: the byte budget
EVICTS warm state outright, and every entry dies with the process —
a restarted server recomputes every popular prefix from t=0. This
module is the paged-KV-cache shape (an LLM server demotes cold KV
blocks to host memory and pages them back on a hit) applied to the
simulation-state cache, built from two pieces the repo already had:

- **host tier** — demotion is one ``jax.device_get`` (started async
  via the shared :func:`~lens_tpu.utils.hostio.copy_tree_to_host_async`
  hint), promotion one ``jax.device_put`` onto the admitting shard's
  device. Bits are placement-independent, so a demote/promote
  round-trip is bitwise free — pinned by tests/test_tiers.py.
- **disk tier** — the round-12 held-snapshot spill protocol
  (:func:`lens_tpu.checkpoint.save_tree`, tmp+rename) promoted from a
  recovery side-channel to a first-class storage tier. A WAL hold
  spill and a budget demotion now produce the SAME on-disk object
  (``snap_<digest>/`` under the spill dir) plus a ``.meta.json``
  sidecar recording the content address, so a fresh server over the
  same directory re-adopts every content-addressed entry at
  construction and serves repeat traffic with warm disk hits — no WAL
  required, and recovery re-pins held spills INTO the tier instead of
  eagerly rehydrating them to device RAM (recovery memory stays
  bounded by what actually gets scattered).

Eviction becomes demotion: past the device byte budget, LRU entries
move device->host (unpinned first; pinned entries may demote too —
demotion never loses bits, so a held state parked on disk is still a
held state); past the host budget they move host->disk; only an entry
with nowhere lower to go is dropped (and only unpinned ones may be).
A hit on a lower tier promotes back to the device tier at admission
(:meth:`TieredSnapshotStore.fetch` — the server passes the admitting
shard's device, so mesh placement rules ride along unchanged).

Tiers off == round 15: the server only constructs this class when a
host budget, a tier dir, or a recover dir is given; and with
``demote_to_disk=False`` + ``host_budget_bytes=0`` (the plain
``recover_dir`` shape) demotion degenerates to the base store's
evict-unpinned/keep-pinned behavior exactly, with the disk tier used
only for explicit hold spills (:meth:`persist`) and recovery adoption
(:meth:`adopt`).

See docs/serving.md, "Tiered snapshots & speculative warming".
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax

from lens_tpu.serve.snapshots import (
    DEVICE,
    DISK,
    HOST,
    SnapshotKey,
    SnapshotStore,
    _Entry,
    tree_nbytes,
)
from lens_tpu.serve.wal import key_from_json, key_to_json, spill_name
from lens_tpu.utils.hostio import copy_tree_to_host_async

#: The tier directory's identity file: recovering prefixes into a
#: server whose buckets would compute DIFFERENT bits must be refused,
#: exactly like the WAL's begin-fingerprint check (a disk entry's
#: content address includes the bucket NAME, not its bits-relevant
#: config, so the directory itself carries the fingerprint).
TIER_META = "tier_meta.json"

_META_SUFFIX = ".meta.json"


class TieredSnapshotStore(SnapshotStore):
    """Device -> host -> disk snapshot paging over the base store.

    Parameters
    ----------
    budget_bytes:
        Device-tier byte budget (None = unbounded, like the base
        store). Past it, LRU entries DEMOTE instead of evicting.
    host_budget_bytes:
        Host-RAM tier byte budget. ``0`` (default) disables the host
        tier — device demotions go straight to disk (or evict, when
        there is no disk tier either).
    dir:
        Disk-tier directory: spill dirs (``snap_<digest>/``, the
        checkpoint rename protocol) plus one ``.meta.json`` sidecar
        per entry. ``None`` = no disk tier.
    demote_to_disk:
        Whether BUDGET pressure may write to disk. ``False`` is the
        plain-``recover_dir`` compatibility mode: the disk tier only
        holds explicit spills (``persist``/``adopt``), ordinary
        eviction behaves exactly like the round-15 store, and the
        construction-time sidecar scan is skipped.
    fingerprint:
        The server's bits-relevant bucket fingerprint
        (:func:`lens_tpu.serve.wal.buckets_fingerprint`), pinned into
        (or verified against) ``<dir>/tier_meta.json``. A mismatch is
        refused at construction — stale snapshots from a different
        simulation must not serve hits under new keys.
    """

    def __init__(
        self,
        budget_bytes: Optional[int] = None,
        host_budget_bytes: int = 0,
        dir: Optional[str] = None,
        demote_to_disk: bool = True,
        fingerprint: Optional[str] = None,
    ):
        super().__init__(budget_bytes=budget_bytes)
        if host_budget_bytes < 0:
            raise ValueError(
                f"host_budget_bytes={host_budget_bytes} must be >= 0"
            )
        self.host_budget_bytes = int(host_budget_bytes)
        self.dir = os.path.abspath(dir) if dir else None
        self.demote_to_disk = bool(demote_to_disk) and self.dir is not None
        if self.dir is not None:
            os.makedirs(self.dir, exist_ok=True)
            if fingerprint is not None:
                self._check_fingerprint(fingerprint)
        if self.demote_to_disk:
            self._scan_dir()

    @property
    def tiers_armed(self) -> bool:
        """Whether paging is actually in play (a host budget or disk
        demotion) — what gates the per-tier metrics export. False in
        the plain-``recover_dir`` compatibility shape, whose disk use
        (hold spills only) keeps the round-15 export surface."""
        return self.demote_to_disk or self.host_budget_bytes > 0

    # -- disk-tier plumbing --------------------------------------------------

    def _check_fingerprint(self, fingerprint: str) -> None:
        path = os.path.join(self.dir, TIER_META)
        if os.path.exists(path):
            with open(path) as f:
                have = json.load(f).get("fingerprint")
            if have != fingerprint:
                raise ValueError(
                    f"{self.dir} holds snapshots for a server with "
                    f"bucket fingerprint {have!r}, not "
                    f"{fingerprint!r} — the bucket configuration "
                    f"changed in a bits-relevant way, so its cached "
                    f"prefixes would serve a different simulation. "
                    f"Use a fresh tier dir (or restore the original "
                    f"buckets)."
                )
            return
        # unique tmp name: cluster workers construct their stores over
        # ONE shared tier dir concurrently at bring-up, and a shared
        # ".tmp" name lets worker A's os.replace consume the file
        # worker B just wrote (B's replace then ENOENTs). Same
        # fingerprint either way — last rename wins harmlessly.
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"fingerprint": fingerprint}, f)
        os.replace(tmp, path)

    def _spill_path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def _write_sidecar(self, name: str, key: SnapshotKey,
                       nbytes: int) -> None:
        path = self._spill_path(name) + _META_SUFFIX
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "key": key_to_json(key),
                "nbytes": int(nbytes),
                # only CONTENT-ADDRESSED entries (the 5-coordinate
                # snapshot_key form) may be re-adopted by a fresh
                # server's scan: a per-request ("held", rid) key is
                # only meaningful to the WAL that recorded the rid —
                # a new server's id space would collide with it
                "content_addressed": len(key) == 5,
            }, f)
        os.replace(tmp, path)

    def _scan_dir(self) -> None:
        """Adopt every content-addressed spill the directory already
        holds (unpinned disk-tier entries) — the restart-warm path: a
        rebooted server serves repeat prefixes from disk without
        recomputing them. Torn spills (sidecar without its data dir,
        or vice versa) are skipped; the rename protocol guarantees a
        present data dir is complete."""
        for meta in sorted(
            glob.glob(os.path.join(self.dir, f"snap_*{_META_SUFFIX}"))
        ):
            try:
                with open(meta) as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # torn sidecar: the entry never happened
            if not data.get("content_addressed"):
                continue
            name = os.path.basename(meta)[: -len(_META_SUFFIX)]
            if not os.path.isdir(self._spill_path(name)):
                continue  # sidecar outlived its spill
            key = key_from_json(data.get("key"))
            if key in self._entries:
                continue
            self._clock += 1
            self._entries[key] = _Entry(
                state=None,
                nbytes=int(data.get("nbytes", 0)),
                used=self._clock,
                tier=DISK,
                disk_name=name,
            )

    def persist(self, key: SnapshotKey) -> str:
        """Ensure a durable disk copy of one entry (the unified spill:
        WAL hold spills and budget demotions write the same object);
        returns the spill-directory name. Idempotent — an entry whose
        ``disk_name`` is already set is already durable (the content
        address guarantees the bytes match). The entry's RESIDENCY is
        untouched: a device-tier entry stays device-resident with a
        disk copy behind it."""
        entry = self._entries[key]
        if entry.disk_name is not None:
            return entry.disk_name
        if self.dir is None:
            raise RuntimeError(
                f"cannot persist snapshot {key!r}: the store has no "
                f"disk tier (no dir configured)"
            )
        from lens_tpu.checkpoint import save_tree

        name = spill_name(key)
        save_tree(self._spill_path(name), entry.state)
        self._write_sidecar(name, key, entry.nbytes)
        entry.disk_name = name
        return name

    def adopt(
        self,
        key: SnapshotKey,
        name: str,
        pin: bool = False,
        warmed: bool = False,
    ) -> None:
        """Register an EXISTING spill as a disk-tier entry without
        restoring it (WAL recovery's re-pin path: the held state is
        promoted lazily, at the admission that actually scatters it,
        so recovery memory is bounded by what runs — not by what was
        ever held). Idempotent across multiple continuations of one
        parent: a present entry just absorbs the pin."""
        entry = self._entries.get(key)
        if entry is not None:
            if entry.disk_name is None:
                entry.disk_name = str(name)
            if pin:
                entry.refs += 1
            self._clock += 1
            entry.used = self._clock
            return
        path = self._spill_path(str(name))
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"held snapshot spill {path} is missing — a hold is "
                f"recorded for snapshot {key!r} but its spill "
                f"directory is gone; the held state cannot be rebuilt"
            )
        nbytes = 0
        try:
            with open(path + _META_SUFFIX) as f:
                nbytes = int(json.load(f).get("nbytes", 0))
        except (OSError, ValueError, json.JSONDecodeError):
            pass  # pre-round-16 spill: no sidecar; sized at promotion
        self._clock += 1
        self._entries[key] = _Entry(
            state=None,
            nbytes=nbytes,
            refs=1 if pin else 0,
            used=self._clock,
            tier=DISK,
            disk_name=str(name),
            warmed=warmed,
        )

    # -- tier-aware reads ----------------------------------------------------

    def _tier_bytes(self, tier: str) -> int:
        return sum(
            e.nbytes for e in self._entries.values() if e.tier == tier
        )

    def resident_bytes(self, shard: Optional[int] = None) -> int:
        """RAM actually held (device + host tiers; disk entries cost no
        memory). With ``shard``, the device-tier bytes on that shard —
        what the per-shard mesh gauges report."""
        if shard is not None:
            return sum(
                e.nbytes
                for e in self._entries.values()
                if e.tier == DEVICE and e.shard == shard
            )
        return sum(
            e.nbytes
            for e in self._entries.values()
            if e.tier in (DEVICE, HOST)
        )

    def shard_of(self, key: SnapshotKey) -> Optional[int]:
        """The device shard owning an entry's buffers — only
        meaningful while the entry is device-resident (a host/disk
        entry can promote onto ANY shard, so admission placement is
        free to balance)."""
        entry = self._entries.get(key)
        if entry is None or entry.tier != DEVICE:
            return None
        return entry.shard

    def keys_on_shard(self, shard: int) -> List[SnapshotKey]:
        return [
            k
            for k, e in self._entries.items()
            if e.tier == DEVICE and e.shard == shard
        ]

    def state(self, key: SnapshotKey) -> Any:
        """The cached state as a DEVICE tree (promoting from a lower
        tier onto the DEFAULT device if needed, recorded as shard 0 —
        the residency bookkeeping must name where the buffers actually
        land, and the pre-demotion shard index is stale by now) — kept
        for callers that predate placement-aware :meth:`fetch`; the
        server's admission path always fetches with an explicit
        shard/device."""
        return self.fetch(key, shard=0)

    def fetch(
        self,
        key: SnapshotKey,
        shard: int = 0,
        device: Any = None,
    ) -> Any:
        """The entry's state as a device tree on ``device``, PROMOTING
        a host/disk-resident entry back to the device tier (host: one
        ``device_put``; disk: ``restore_tree`` straight onto the
        target). The promotion is counted against the SOURCE tier and
        may itself demote colder device entries to stay under the
        device budget — paging, not growth."""
        entry = self._entries[key]
        self._clock += 1
        entry.used = self._clock
        if entry.tier == DEVICE:
            return entry.state
        src = entry.tier
        if src == HOST:
            state = jax.device_put(entry.state, device)
        else:
            from lens_tpu.checkpoint import restore_tree

            state = restore_tree(
                self._spill_path(entry.disk_name), device=device
            )
        entry.state = state
        entry.tier = DEVICE
        entry.shard = int(shard)
        entry.nbytes = tree_nbytes(state)
        self.promotions[src] += 1
        if self.trace:
            self.trace.instant(
                "snapshot.promote", tier=src, shard=int(shard),
                bytes=entry.nbytes,
            )
        self._evict_to_budget()
        return state

    # -- writes --------------------------------------------------------------

    def put(
        self,
        key: SnapshotKey,
        state: Any,
        pin: bool = False,
        shard: int = 0,
    ) -> int:
        """Base-store semantics, plus: inserting a key that is
        currently host/disk-resident upgrades its residency in place —
        the caller just recomputed (or captured) the same bits on
        device, so the store takes the free promotion instead of
        keeping the colder copy authoritative."""
        entry = self._entries.get(key)
        if entry is not None and entry.tier != DEVICE:
            entry.state = state
            entry.tier = DEVICE
            entry.shard = int(shard)
            entry.nbytes = tree_nbytes(state)
            self._clock += 1
            entry.used = self._clock
            if pin:
                entry.refs += 1
            return self._evict_to_budget()
        return super().put(key, state, pin=pin, shard=shard)

    def device_lost(self, shard: int) -> List[Tuple[SnapshotKey, int]]:
        """A device died. Entries whose only bytes lived there but
        have a durable disk copy DEMOTE to the disk tier (same key,
        same refs — a queued continuation's pin keeps working and the
        admission that scatters it restores onto a survivor); entries
        without one are lost, returned as ``(key, orphaned_refs)`` for
        the server to repair. Host/disk-resident entries are
        untouched — they never depended on the dead device."""
        lost: List[Tuple[SnapshotKey, int]] = []
        for key in self.keys_on_shard(shard):
            entry = self._entries[key]
            if entry.disk_name is not None and os.path.isdir(
                self._spill_path(entry.disk_name)
            ):  # trust a spill only if it still exists on disk
                entry.state = None
                entry.tier = DISK
                self.demotions[DEVICE] += 1
                if self.trace:
                    self.trace.instant(
                        "snapshot.demote", tier=DEVICE, to=DISK,
                        bytes=entry.nbytes, reason="device_lost",
                    )
            else:
                lost.append((key, entry.refs))
                del self._entries[key]
        return lost

    # -- demotion (the budget enforcer) --------------------------------------

    def _evict_to_budget(self) -> int:
        """Enforce both RAM budgets, coldest-first: device excess
        demotes to host (or straight to disk when the host tier is
        disabled), then host excess demotes to disk. Only entries with
        nowhere lower to go are dropped — unpinned ones count in the
        returned eviction total (the ``snapshot_evictions`` feed);
        pinned undemotable entries stay and overshoot, exactly like
        the base store."""
        evicted = self._shrink_tier(DEVICE, self.budget_bytes)
        evicted += self._shrink_tier(HOST, self.host_budget_bytes)
        if evicted and self.trace:
            self.trace.instant("snapshot.evicted", count=evicted)
        return evicted

    def _shrink_tier(self, tier: str, budget: Optional[int]) -> int:
        if budget is None:
            return 0
        excess = self._tier_bytes(tier) - budget
        if excess <= 0:
            return 0
        # unpinned LRU first (they cost nothing to lose), pinned LRU
        # after (demotable only — demotion preserves their bits)
        victims = sorted(
            (e.refs > 0, e.used, k)
            for k, e in self._entries.items()
            if e.tier == tier
        )
        if tier == DEVICE:
            # start every prospective victim's device->host DMA before
            # the first blocking device_get — the copies overlap
            remaining = excess
            for _, _, key in victims:
                if remaining <= 0:
                    break
                e = self._entries[key]
                copy_tree_to_host_async(e.state)
                remaining -= e.nbytes
        evicted = 0
        for pinned, _, key in victims:
            if excess <= 0:
                break
            entry = self._entries[key]
            nbytes = entry.nbytes
            if self._demote(key, entry):
                excess -= nbytes
            elif not pinned:
                del self._entries[key]
                evicted += 1
                excess -= nbytes
            # pinned with nowhere to go: stays, budget overshoots
        return evicted

    def _demote(self, key: SnapshotKey, entry: _Entry) -> bool:
        """Move one entry a tier down; False when no lower tier will
        take it (then eviction rules apply)."""
        src = entry.tier
        if src == DEVICE and self.host_budget_bytes > 0:
            target = HOST
        elif self.demote_to_disk:
            # an already-durable entry (a spilled hold) just drops its
            # RAM copy; others persist first — but only when disk
            # PAGING is armed: the plain-recover_dir compatibility
            # shape keeps round-15 residency behavior exactly (pinned
            # entries overshoot the budget and stay device-resident;
            # device LOSS still falls back to a hold's spill, that
            # path does not come through here)
            target = DISK
        else:
            return False
        if target == HOST:
            entry.state = jax.device_get(entry.state)
            entry.tier = HOST
        else:
            if entry.disk_name is None:
                self.persist(key)
            entry.state = None
            entry.tier = DISK
        self.demotions[src] += 1
        if self.trace:
            self.trace.instant(
                "snapshot.demote", tier=src, to=target,
                bytes=entry.nbytes,
            )
        return True
