"""Content-addressed snapshot store: run shared scenario prefixes once.

Sweeps, ASHA rungs, counterfactual "what-if-at-t" queries, and
branching ensembles all share a scenario *prefix* — same composite,
seed, warmup overrides, and warmup horizon — and before round 11 each
request re-simulated that prefix from t=0. This module is LLM-server
prefix caching applied to simulation *time*: a device-resident state
tree captured at a known sim-time is addressed by the CONTENT that
deterministically produced it, so any later request declaring the same
prefix can fork from the cached bits and run only its suffix.

The address (:func:`snapshot_key`) is the serving determinism contract
turned into a cache key: a lane's state at step ``s`` is a pure
function of (bucket program, seed, initial-state overrides, n_agents,
``s``) — pinned bitwise by ``tests/test_serve.py`` — so two requests
agreeing on those five coordinates would compute identical prefixes,
and the store lets the second one not compute it at all.

The store itself is deliberately dumb and single-threaded (only the
scheduler thread touches it; the stream thread never does):

- **refcounting** — an entry is *pinned* while anyone still needs its
  exact buffers: a queued fork that will scatter it, or a ``hold_state``
  parent whose client may extend it again. Pinned entries are never
  evicted; ``release`` below zero raises (a double-free is a scheduler
  bug, never silently absorbed).
- **byte budget + LRU** — unpinned entries are evicted
  least-recently-used when ``put`` would exceed ``budget_bytes``. An
  unpinned entry that cannot fit even after evicting everything
  evictable is simply not retained (the caller already holds the state
  tree in hand for its waiters — the cache misses later, it never
  blocks). Pinned inserts always land: an explicit hold is the
  client's promise to ``release`` it, so the budget governs the
  *cache*, not the client's working set.
- **request coalescing** lives in the server, not here: the store only
  answers "cached or not"; ``SimServer`` keeps the in-flight-prefix
  ticket map so concurrent submitters of one prefix never duplicate
  work.

See docs/serving.md, "Prefix caching & forking".
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax
import numpy as np

from lens_tpu.emit.log import SEP
from lens_tpu.utils.dicts import flatten_paths

#: A snapshot address: (bucket, seed, n_agents fp, overrides fp, steps).
#: ``steps`` is LAST so a continuation's key is its parent's key with
#: the step coordinate advanced (``key[:-1] + (steps,)``).
SnapshotKey = Tuple[Any, ...]


def overrides_fingerprint(overrides: Mapping | None) -> str:
    """Content digest of an override tree: every leaf's path, dtype,
    shape, and exact bytes, in sorted path order. Two trees that build
    the same initial state hash the same; any value/shape/dtype change
    hashes differently."""
    h = hashlib.sha256()
    leaves = sorted(
        (SEP.join(map(str, path)), np.asarray(value))
        for path, value in flatten_paths(overrides or {})
    )
    for path, value in leaves:
        h.update(path.encode())
        h.update(str(value.dtype).encode())
        h.update(repr(value.shape).encode())
        h.update(np.ascontiguousarray(value).tobytes())
    return h.hexdigest()


def agents_fingerprint(n_agents: Any) -> Any:
    """A hashable form of the (possibly per-species) n_agents value."""
    if isinstance(n_agents, Mapping):
        return tuple(sorted((str(k), int(v)) for k, v in n_agents.items()))
    return int(n_agents) if n_agents is not None else None


def snapshot_key(
    bucket: str,
    seed: int,
    n_agents: Any,
    overrides: Mapping | None,
    steps: int,
) -> SnapshotKey:
    """The content address of "bucket ``bucket``'s state after running
    ``steps`` steps from ``initial_state(n_agents, PRNGKey(seed),
    overrides)``". The bucket name pins composite, config, capacity,
    timestep, and emit cadence (one bucket = one resident program)."""
    return (
        str(bucket),
        int(seed),
        agents_fingerprint(n_agents),
        overrides_fingerprint(overrides),
        int(steps),
    )


def tree_nbytes(tree: Any) -> int:
    """Total leaf bytes of a state tree (device or host arrays — both
    expose ``nbytes`` without forcing a transfer)."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        total += int(nb) if nb is not None else np.asarray(leaf).nbytes
    return total


#: Storage-tier names, hot to cold. The base store is DEVICE-only; the
#: tiered subclass (lens_tpu.serve.tiers) adds host RAM and disk, but
#: both speak the same per-tier stats vocabulary so the metrics surface
#: is uniform.
DEVICE = "device"
HOST = "host"
DISK = "disk"
TIERS = (DEVICE, HOST, DISK)


@dataclass
class _Entry:
    state: Any
    nbytes: int
    refs: int = 0
    used: int = 0  # LRU stamp (monotonic per store)
    shard: int = 0  # device shard whose memory holds the state tree
    tier: str = DEVICE  # which tier holds `state` (base store: device)
    disk_name: Optional[str] = None  # durable spill dir (tiered store)
    warmed: bool = False  # produced/prefetched by speculative warming


class SnapshotStore:
    """Refcounted, byte-budgeted, LRU content-addressed snapshot cache.

    ``budget_bytes=None`` means unbounded (in-process tests, small
    servers); a budget makes ``put`` — and ``release``, when a pin
    drops to zero — evict unpinned entries LRU-first and report how
    many were evicted, so the server's metrics can count them. All
    methods are O(entries log entries) at worst and touch no device
    program — the store only holds references to already-materialized
    state trees.
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is not None and budget_bytes < 0:
            raise ValueError(f"budget_bytes={budget_bytes} must be >= 0")
        self.budget_bytes = budget_bytes
        self._entries: Dict[SnapshotKey, _Entry] = {}
        self._clock = 0
        # observability counters (monotonic over the store's lifetime):
        # `rejected` — puts whose entry was NOT retained (an unpinned
        # tree too big for the budget; before round 16 this was a
        # silent drop); `hits`/`promotions`/`demotions` — per-tier
        # traffic, counted at acquire/fetch/demote time (the base
        # store only ever hits its device tier; the tiered subclass
        # moves entries between all three).
        self.rejected = 0
        self.hits: Dict[str, int] = {t: 0 for t in TIERS}
        self.promotions: Dict[str, int] = {t: 0 for t in TIERS}
        self.demotions: Dict[str, int] = {t: 0 for t in TIERS}
        # a span tracer (lens_tpu.obs) the owning server installs:
        # inserts and budget evictions become timeline instants (a
        # thrashing store is a scheduling story, not just a counter).
        # None / NullTracer = no emission.
        self.trace: Any = None

    # -- reads ---------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: SnapshotKey) -> bool:
        return key in self._entries

    def resident_bytes(self, shard: Optional[int] = None) -> int:
        return sum(
            e.nbytes
            for e in self._entries.values()
            if shard is None or e.shard == shard
        )

    def shard_of(self, key: SnapshotKey) -> Optional[int]:
        """The device shard owning an entry's buffers (None if
        absent) — mesh admission places a fork on the shard that
        already holds its cached prefix, so the scatter never crosses
        devices on the happy path."""
        entry = self._entries.get(key)
        return entry.shard if entry is not None else None

    def keys_on_shard(self, shard: int) -> List[SnapshotKey]:
        """Every entry whose buffers live in one shard's device memory
        — the set a device quarantine must rehydrate (from spills) or
        declare lost."""
        return [
            k for k, e in self._entries.items() if e.shard == shard
        ]

    def refs_total(self) -> int:
        """Outstanding pins across all entries — 0 when every acquire
        has been released (the no-leak invariant ``SimServer.close``
        restores and tests assert)."""
        return sum(e.refs for e in self._entries.values())

    def state(self, key: SnapshotKey) -> Any:
        """The cached state tree (LRU touch). KeyError if absent —
        callers holding a ref can never see that (pinned entries are
        not evictable)."""
        entry = self._entries[key]
        self._clock += 1
        entry.used = self._clock
        return entry.state

    # -- refcounting ---------------------------------------------------------

    def acquire(self, key: SnapshotKey) -> Any:
        """Pin an entry (evicting it becomes impossible) and return its
        state. Every ``acquire`` must be paired with exactly one
        ``release``. Counts a hit against the tier the entry currently
        lives in — acquire is the moment a consumer committed to these
        bits (warming success is counted server-side, per prefix
        submit, where the policy lives)."""
        entry = self._entries[key]
        entry.refs += 1
        self._clock += 1
        entry.used = self._clock
        self.hits[entry.tier] += 1
        return entry.state

    def release(self, key: SnapshotKey) -> int:
        """Drop one pin. The entry STAYS cached (evictable once refs
        hit zero) — release means "I no longer need these exact
        buffers", not "forget the snapshot". A pin dropping to zero
        re-enforces the byte budget (pinned inserts may legitimately
        overshoot it; the overshoot must not outlive the pins), so
        like ``put`` this returns how many entries were evicted.
        Releasing an absent or unpinned entry raises: a double-free is
        a bug upstream."""
        entry = self._entries.get(key)
        if entry is None:
            raise KeyError(f"release of unknown snapshot {key!r}")
        if entry.refs <= 0:
            raise RuntimeError(
                f"double release of snapshot {key!r} (refs already 0)"
            )
        entry.refs -= 1
        return self._evict_to_budget() if entry.refs == 0 else 0

    def refs(self, key: SnapshotKey) -> int:
        """Outstanding pins on one entry (0 for an absent key)."""
        entry = self._entries.get(key)
        return entry.refs if entry is not None else 0

    def fetch(
        self,
        key: SnapshotKey,
        shard: int = 0,
        device: Any = None,
    ) -> Any:
        """The entry's state as a DEVICE tree ready to scatter into a
        lane on ``shard``. In the base store every entry already is one
        (``device``/``shard`` are advisory — ``admit_state`` migrates
        across devices itself, a byte copy); the tiered subclass
        PROMOTES host/disk-resident entries onto the given device
        here. KeyError if absent, like :meth:`state`."""
        return self.state(key)

    def tier_of(self, key: SnapshotKey) -> Optional[str]:
        """Which tier holds an entry's resident bytes (None if
        absent)."""
        entry = self._entries.get(key)
        return entry.tier if entry is not None else None

    def mark_warmed(self, key: SnapshotKey) -> None:
        """Tag an entry as produced (or prefetched) by speculative
        warming, so later hits on it count as speculative successes.
        No-op for an absent key (an oversized warm snapshot may have
        been rejected by the budget)."""
        entry = self._entries.get(key)
        if entry is not None:
            entry.warmed = True

    def is_warmed(self, key: SnapshotKey) -> bool:
        entry = self._entries.get(key)
        return entry.warmed if entry is not None else False

    def device_lost(self, shard: int) -> List[Tuple[SnapshotKey, int]]:
        """A device died: every entry whose resident bytes lived in its
        memory is gone. Returns ``[(key, orphaned_refs), ...]`` for the
        entries LOST outright — the caller must repair every ticket
        that held a ref (the tiered subclass saves entries with a
        host/disk copy by demoting them instead of losing them)."""
        lost = []
        for key in self.keys_on_shard(shard):
            refs = self._entries.pop(key).refs
            lost.append((key, refs))
        return lost

    def tier_stats(self) -> Dict[str, Any]:
        """The per-tier observability dict the server's metrics embed:
        resident entries/bytes plus lifetime hit/promotion/demotion
        counts per tier, and the store-wide rejected count. Uniform
        across base and tiered stores (the base store simply never
        populates host/disk)."""
        resident: Dict[str, List[int]] = {t: [0, 0] for t in TIERS}
        for e in self._entries.values():
            resident[e.tier][0] += 1
            resident[e.tier][1] += e.nbytes
        return {
            "rejected": self.rejected,
            "tiers": {
                t: {
                    "entries": resident[t][0],
                    "bytes": resident[t][1],
                    "hits": self.hits[t],
                    "promotions": self.promotions[t],
                    "demotions": self.demotions[t],
                }
                for t in TIERS
            },
        }

    # -- writes --------------------------------------------------------------

    def put(
        self,
        key: SnapshotKey,
        state: Any,
        pin: bool = False,
        shard: int = 0,
    ) -> int:
        """Insert (or re-touch) a snapshot; returns how many entries
        were evicted to make room. ``pin=True`` adds one ref (the
        ``hold_state`` path — the caller promises a ``release``).
        ``shard`` records which device shard's memory holds the tree
        (0 on a single-device server).

        Inserting an existing key never replaces the state: by the
        content-address contract the bits are identical, so the
        incumbent (possibly pinned, possibly older-LRU) entry simply
        absorbs the pin/touch.
        """
        self._clock += 1
        entry = self._entries.get(key)
        if entry is not None:
            entry.used = self._clock
            if pin:
                entry.refs += 1
            return 0
        entry = _Entry(
            state=state,
            nbytes=tree_nbytes(state),
            refs=1 if pin else 0,
            used=self._clock,
            shard=int(shard),
        )
        self._entries[key] = entry
        if self.trace:
            self.trace.instant(
                "snapshot.put", bytes=entry.nbytes, pinned=bool(pin),
                shard=int(shard),
            )
        # LRU eviction may consume the new entry itself (it is the
        # newest, so only after every older evictable is gone): an
        # unpinned snapshot that cannot fit is simply not retained —
        # the caller still holds the tree for its immediate consumers.
        # Counted (`rejected`, additive to the eviction count the
        # return value always carried) rather than silently dropped: a
        # store whose budget rejects every insert serves zero hits
        # while looking healthy on the hit counters alone.
        evicted = self._evict_to_budget()
        if key not in self._entries:
            self.rejected += 1
            if self.trace:
                self.trace.instant(
                    "snapshot.rejected", bytes=entry.nbytes,
                )
        return evicted

    def drop(self, key: SnapshotKey) -> None:
        """Forget an unpinned entry now (explicit invalidation)."""
        entry = self._entries.get(key)
        if entry is None:
            return
        if entry.refs > 0:
            raise RuntimeError(
                f"drop of pinned snapshot {key!r} (refs={entry.refs})"
            )
        del self._entries[key]

    def _evict_to_budget(self) -> int:
        if self.budget_bytes is None:
            return 0
        excess = self.resident_bytes() - self.budget_bytes
        if excess <= 0:
            return 0
        victims: List[Tuple[int, SnapshotKey]] = sorted(
            (e.used, k)
            for k, e in self._entries.items()
            if e.refs == 0
        )
        evicted = 0
        for _, key in victims:  # LRU-first until the budget holds
            if excess <= 0:
                break
            excess -= self._entries[key].nbytes
            del self._entries[key]
            evicted += 1
        # excess > 0 here means everything left is pinned: the budget
        # cannot bind (pinned inserts always land)
        if evicted and self.trace:
            self.trace.instant("snapshot.evicted", count=evicted)
        return evicted

    def clear(self) -> None:
        """Drop every entry regardless of pins (server close: the
        tickets' pins are being torn down with the server)."""
        self._entries.clear()
