"""Background window streaming: the host half of the serve pipeline.

``BENCH_SERVE_CPU_r08`` measured the scheduler at 0.83-0.89 of the bare
``Ensemble`` ceiling at full occupancy, and the gap was all host time
spent while the device idled: every tick blocked on ``jax.device_get``
of the window trajectory, then did per-lane slicing, emit filtering,
and sink appends inline before dispatching the next window. Podracer's
Sebulba (PAPERS.md) names the fix: keep the device loop hot and move
host-side data handling off the critical path.

This module is that off-path half. The scheduler dispatches window
``k+1`` immediately after bookkeeping window ``k`` (retire/admit read
only the host-mirrored counters — no readback) and hands window ``k``'s
already-async-copying trajectory to a :class:`Streamer` — ONE daemon
thread per server draining a bounded queue in FIFO order, so every
request's records land in order while the device computes ahead.

Contracts:

- **Backpressure.** At most ``max_inflight`` windows may be queued or
  in processing; ``submit`` blocks the scheduler past that (returned
  stall seconds feed the metrics). The device can therefore run at most
  ``max_inflight`` windows ahead of the slowest sink — bounded memory,
  bounded staleness for tailing readers.
- **Ordering.** One thread, one FIFO: a request's appends happen in
  window order, and its sink ``close`` (a :class:`LaneSlice` with
  ``close_after`` or a bare close item) happens after its last append.
- **Exception propagation.** A failure on the stream thread (sink I/O,
  a poisoned device buffer surfacing in ``device_get``) parks the
  error and stops the thread; the next scheduler call into the
  streamer (``check`` at tick start, ``submit``, ``drain``) raises it.
- **Bits.** Everything here is host-side numpy projection of what the
  device emitted — reordering WHEN it happens cannot change a record's
  bytes, which is why the solo==co-batched determinism pins hold with
  the pipeline on (tests/test_streamer.py pins pipelined==sync too).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from lens_tpu.emit.log import SEP
from lens_tpu.obs.trace import STREAM_TRACK, device_track
from lens_tpu.utils.dicts import flatten_paths, set_path


class WatchdogTimeout(RuntimeError):
    """The serve watchdog expired: a device window / streamer handoff
    stalled longer than ``watchdog_s``. Raised into the scheduler
    (``tick``/``drain``/``result``) instead of wedging it forever — a
    hung sink, a disk that stopped accepting writes, or a device
    program that never completes all surface here with a bounded
    detection time. The server is NOT automatically healthy afterwards
    (whatever wedged is still wedged); the caller decides whether to
    close, shed load, or page a human."""


def filter_paths(tree: Any, prefixes: List[str]) -> Dict:
    """Keep leaves whose ``/``-joined path starts with any prefix
    (component-aligned: prefix ``cell`` matches ``cell/volume``, not
    ``cells``). Host-side, post-device — a pure projection of the
    emitted bits, so it can never perturb them."""
    out: Dict = {}
    for path, value in flatten_paths(tree):
        joined = SEP.join(str(p) for p in path)
        if any(
            joined == p or joined.startswith(p + SEP) for p in prefixes
        ):
            out = set_path(out, path, value)
    return out


def subsample_rows(first_emit: int, n_valid: int, every: int) -> np.ndarray:
    """Window-local indices of the rows a request's ``every``-k emit
    spec keeps, given ``first_emit`` rows already emitted before this
    window. Vectorized arange/modulo — the per-row Python loop it
    replaces ran O(rows) interpreted work per lane per window on the
    hot streaming path. ``every < 1`` is a caller bug (``submit``
    validates requests) — raise rather than silently keeping all."""
    if every < 1:
        raise ValueError(f"every={every} must be >= 1")
    idx = np.arange(n_valid)
    if every > 1:
        idx = idx[(first_emit + idx + 1) % every == 0]
    return idx


@dataclass
class LaneSlice:
    """One lane's share of a window: which rows to keep, where they go.

    ``idx is None`` marks a close-only slice (a retiring lane whose
    final window kept no rows, or a cancelled/expired request whose
    sink must close AFTER its already-queued appends).
    """

    request_id: str
    sink: Any
    lane: int = 0
    idx: Optional[np.ndarray] = None      # window-local rows to keep
    times: Optional[np.ndarray] = None    # sim times for those rows
    paths: Optional[List[str]] = None     # emit path-prefix filter
    close_after: bool = False             # final slice: close the sink
    on_close: Optional[Any] = None        # callback after the close
    # (the scheduler hangs request-completion bookkeeping here so a
    # pipelined request's latency is measured when its records are
    # actually available, not when bookkeeping ran ahead)
    on_error: Optional[Any] = None        # sink failure scoped to THIS
    # request (the server's sink_errors="request" policy): called with
    # the exception instead of poisoning the whole stream pipe; the
    # slice's close/on_close are skipped (the handler owns cleanup)


@dataclass
class WindowItem:
    """One dispatched window handed to the stream thread: the device
    trajectory (async host copy already started) plus every occupied
    lane's slice. ``traj is None`` for pure control items (closes).
    ``shard``/``tick`` are correlation context for the span tracer
    (which device ran the window, which scheduler tick dispatched
    it)."""

    traj: Any
    slices: List[LaneSlice] = field(default_factory=list)
    dispatched_at: float = 0.0
    shard: int = 0
    tick: int = 0


def process_window(
    host: Any, slices: List[LaneSlice], faults: Any = None
) -> None:
    """Apply every slice of one window to its sink, in order. Shared by
    the stream thread and the ``pipeline="off"`` synchronous path, so
    both produce byte-identical sink contents. ``faults`` (a
    ``FaultPlan``) arms the ``sink.append`` io_error seam on both
    paths."""
    for s in slices:
        try:
            if s.idx is not None:
                if faults:
                    faults.io_error("sink.append", s.request_id)
                source = host
                if s.paths:
                    source = filter_paths(host, s.paths)
                if source:
                    tree = jax.tree.map(
                        lambda leaf: np.asarray(leaf)[s.idx, s.lane],
                        source,
                    )
                    s.sink.append(tree, s.times)
            if s.close_after:
                s.sink.close()
            if s.on_close is not None:
                s.on_close()
        except Exception as e:
            if s.on_error is None:
                raise  # sink_errors="fatal": park on the stream pipe
            # sink_errors="request": the failure is THIS request's
            # alone — hand it to the server's per-request handler and
            # keep streaming the co-batched slices (close/on_close are
            # skipped; the handler owns the sink's cleanup)
            s.on_error(e)


class Streamer:
    """Bounded-queue background consumer of :class:`WindowItem`\\ s.

    ``max_inflight`` bounds queued + currently-processing REAL windows
    (close-only control items ride free — they hold no device memory
    and must never deadlock a shutdown). ``metrics`` (a
    ``ServerMetrics``) receives per-window stream samples.

    ``watchdog_s`` arms the handoff watchdog: any blocking wait on the
    stream pipe (``submit`` backpressure, ``drain``, ``close``'s join)
    that makes no progress for that long raises
    :class:`WatchdogTimeout` instead of wedging the scheduler — the
    bounded-detection-time answer to a hung sink or a device window
    that never lands. ``faults`` (a ``FaultPlan``) arms the
    ``stream.window`` stall seam and the ``sink.append`` io_error seam
    on the stream thread.
    """

    def __init__(
        self,
        max_inflight: int = 2,
        metrics: Any = None,
        watchdog_s: Optional[float] = None,
        faults: Any = None,
        trace: Any = None,
    ):
        if max_inflight < 1:
            raise ValueError(
                f"max_inflight={max_inflight} must be >= 1"
            )
        if watchdog_s is not None and watchdog_s <= 0:
            raise ValueError(f"watchdog_s={watchdog_s} must be > 0")
        self.max_inflight = int(max_inflight)
        self.watchdog_s = watchdog_s
        self._faults = faults
        self._metrics = metrics
        self._trace = trace  # a Tracer/NullTracer (None = no tracing)
        self._queue: List[WindowItem] = []
        self._cond = threading.Condition()
        self._inflight = 0  # real windows queued or being processed
        self._busy = False  # an item popped but not yet finished
        self._busy_rids: List[str] = []  # requests in the busy item
        self._prev_done = None  # previous window's streamed_at
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- scheduler-side surface ---------------------------------------------

    def check(self) -> None:
        """Raise a stream-thread failure into the caller (the scheduler
        calls this at every tick)."""
        with self._cond:
            if self._error is not None:
                raise self._error

    def progress_token(self):
        """An opaque snapshot of pipe state; two equal tokens a
        watchdog period apart mean NO item completed in between — the
        no-progress test the watchdog waits (``drain``, the server's
        ``result``) key off, so a slow-but-moving pipe never trips
        them."""
        with self._cond:
            return (len(self._queue), self._inflight, self._busy)

    def submit(self, item: WindowItem) -> float:
        """Enqueue a window; BLOCKS while ``max_inflight`` windows are
        already queued/processing (the pipeline's backpressure: the
        scheduler — and therefore the device — stalls instead of racing
        ahead of the slowest sink). Returns seconds stalled."""
        stalled = 0.0
        with self._cond:
            if self._error is not None:
                raise self._error
            if self._stop:
                # fail fast: the thread is (being) joined, so a queued
                # item would never drain — blocking here is a silent
                # deadlock for a caller ticking a closed server
                raise RuntimeError(
                    "streamer is closed; the server was shut down"
                )
            real = item.traj is not None
            if real and self._inflight >= self.max_inflight:
                t0 = time.perf_counter()
                done = self._cond.wait_for(
                    lambda: self._inflight < self.max_inflight
                    or self._error is not None
                    or self._stop,
                    timeout=self.watchdog_s,
                )
                if not done:
                    # the watchdog: the pipe made no progress for a
                    # whole watchdog period — a hung sink or a device
                    # window that never landed. Raise instead of
                    # wedging tick() forever.
                    raise WatchdogTimeout(
                        f"stream handoff stalled > {self.watchdog_s}s "
                        f"with {self._inflight}/{self.max_inflight} "
                        f"windows in flight — a sink append or the "
                        f"device window fetch is hung"
                        f"{self._stuck_note()}"
                    )
                stalled = time.perf_counter() - t0
                if self._error is not None:
                    raise self._error
                if self._stop:
                    # close() raced the stall: enqueueing now would
                    # silently drop the item (nothing will process it)
                    raise RuntimeError(
                        "streamer is closed; the server was shut down"
                    )
            if real:
                self._inflight += 1
            self._queue.append(item)
            self._cond.notify_all()
        return stalled

    def submit_close(
        self, sink: Any, on_close: Any = None, on_error: Any = None
    ) -> None:
        """Queue a sink close behind everything already queued (a
        cancelled/expired request's ordered shutdown). ``on_close``
        runs after the close — completion signalling; ``on_error``
        scopes a close failure to the owning request (the server's
        ``sink_errors="request"`` policy)."""
        self.submit(
            WindowItem(
                traj=None,
                slices=[LaneSlice(
                    "", sink, close_after=True, on_close=on_close,
                    on_error=on_error,
                )],
            )
        )

    def drain(self) -> None:
        """Block until every queued item is fully processed; raise any
        stream-thread failure. The barrier ``result()``,
        ``run_until_idle()``, and ``close()`` sit behind. With the
        watchdog armed, a drain that makes no progress for a whole
        watchdog period raises :class:`WatchdogTimeout`."""
        with self._cond:
            while True:
                pending = (len(self._queue), self._inflight, self._busy)
                done = self._cond.wait_for(
                    lambda: (not self._queue and self._inflight == 0
                             and not self._busy)
                    or self._error is not None,
                    timeout=self.watchdog_s,
                )
                if self._error is not None:
                    raise self._error
                if done:
                    return
                if (
                    len(self._queue), self._inflight, self._busy
                ) == pending:
                    raise WatchdogTimeout(
                        f"stream drain stalled > {self.watchdog_s}s "
                        f"({pending[0]} queued, {pending[1]} in "
                        f"flight) — a sink append or the device "
                        f"window fetch is hung"
                        f"{self._stuck_note()}"
                    )
                # progress happened (slower than the watchdog period
                # per item is fine) — keep waiting

    def close(self) -> None:
        """Drain, stop, and join the stream thread. Raises a parked
        stream error after the thread is down (cleanup first). With
        the watchdog armed, a join the stream thread never completes
        (hung mid-item) raises :class:`WatchdogTimeout` — the daemon
        thread is abandoned, not waited on forever."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=self.watchdog_s)
        if self._thread.is_alive():
            raise WatchdogTimeout(
                f"stream thread did not stop within "
                f"{self.watchdog_s}s of close — abandoned (daemon)"
            )
        self.check()

    def _stuck_note(self) -> str:
        """Name the requests whose window the stream thread is stuck
        on (caller holds ``_cond``) — a bounded-time failure should
        say where progress stopped, not just that it did."""
        rids = [r for r in self._busy_rids if r]
        if not rids:
            return ""
        return f"; currently streaming window for request(s) {rids}"

    # -- stream thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                self._cond.wait_for(lambda: self._queue or self._stop)
                if not self._queue:
                    return  # stopped and drained
                item = self._queue.pop(0)
                self._busy = True
                self._busy_rids = [
                    s.request_id for s in item.slices
                ]
            try:
                self._process(item)
            except BaseException as e:
                with self._cond:
                    # Park the error and stop: appending LATER windows
                    # after a dropped one would tear request streams.
                    self._error = e
                    self._queue.clear()
                    self._inflight = 0
                    self._busy = False
                    self._busy_rids = []
                    self._cond.notify_all()
                return
            with self._cond:
                if item.traj is not None:
                    self._inflight -= 1
                self._busy = False
                self._busy_rids = []
                self._cond.notify_all()

    def _process(self, item: WindowItem) -> None:
        if self._faults and item.traj is not None:
            # injected window stall: models a hung device fetch / slow
            # sink without needing either to actually misbehave
            self._faults.stall("stream.window")
        host = None
        if item.traj is not None:
            # waits for compute + the async copy started at dispatch
            host = jax.device_get(item.traj)
        ready = time.perf_counter()
        process_window(host, item.slices, faults=self._faults)
        if self._trace and item.traj is not None:
            # the two pipelined halves of one window on the timeline:
            # device compute + async copy (dispatch -> host-side), then
            # the streamer's slicing/filtering/sink appends
            done_t = time.perf_counter()
            self._trace.emit_span(
                "window.device", item.dispatched_at, ready,
                track=device_track(item.shard),
                shard=item.shard, tick=item.tick,
            )
            self._trace.emit_span(
                "window.stream", ready, done_t, track=STREAM_TRACK,
                shard=item.shard, tick=item.tick,
                requests=len(item.slices),
            )
        if item.traj is not None:
            done = time.perf_counter()
            if self._metrics is not None:
                self._metrics.observe_stream(
                    item.dispatched_at, ready, done
                )
                # keep avg_window_seconds (the retry-after pacing unit)
                # meaningful under the pipeline: the incremental wall
                # per window through the WHOLE pipe in steady state —
                # max(device, host) per window — which is exactly the
                # rate the backlog drains at. (dispatch -> ready alone
                # would double-count queue wait behind earlier
                # windows' host work when the streamer is backlogged.)
                start = item.dispatched_at
                if self._prev_done is not None:
                    start = max(start, self._prev_done)
                self._metrics.observe_window(done - start)
            self._prev_done = done
