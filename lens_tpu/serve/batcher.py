"""Request admission: bounded queue, buckets, deadlines, backpressure.

Host-side scheduling policy, kept apart from the device mechanics
(lanes.py) on purpose: everything in this module is plain Python over
plain data, so the queueing behavior is unit-testable without ever
compiling a program.

Shape discipline is the organizing idea, borrowed from inference-stack
continuous batching: a resident program serves exactly one (composite,
config, capacity, lane-count, window) BUCKET, requests are routed to
their bucket by composite name, and anything per-request must be DATA
(seed, initial-state overrides, horizon, emit spec) — never shape. A
request that would need a different shape belongs in a different bucket.

Backpressure is reject-with-retry-after, not unbounded buffering: the
queue is bounded, a full queue refuses the submit, and the hint quotes
how long the present backlog would take to drain at the measured window
rate — the client's cue to back off (the serving analogue of HTTP 429 +
Retry-After).
"""

from __future__ import annotations

import itertools
import numbers
import time
from dataclasses import dataclass, field, fields
from typing import Any, Dict, Iterator, List, Mapping, Optional

#: Request lifecycle states. Terminal: DONE, TIMEOUT, CANCELLED, FAILED,
#: MIGRATED (this server handed the queued request to another host —
#: cluster work-stealing; the request lives on under its original id on
#: the host that adopted it, so MIGRATED is terminal only locally).
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
TIMEOUT = "timeout"
CANCELLED = "cancelled"
FAILED = "failed"
MIGRATED = "migrated"

#: Admission priority classes, highest first. ``interactive`` requests
#: are admitted ahead of ``batch`` ones whenever both wait for a lane
#: (FIFO within a class) — the front door's latency tier. The default
#: is ``batch``: a request stream that never names a priority is the
#: plain FIFO the server always had, bit for bit.
INTERACTIVE = "interactive"
BATCH = "batch"
PRIORITIES = (INTERACTIVE, BATCH)


class RequestValidationError(ValueError):
    """A malformed request, with a MACHINE-READABLE field path.

    ``path`` names the offending field in dotted form (``"emit.every"``,
    ``"prefix.horizon"``, ``"overrides"``) — what the front door's HTTP
    400 body carries so a client can repair programmatically instead of
    parsing prose. A ``ValueError`` subclass, so every existing
    ``except ValueError`` call site keeps working unchanged.
    """

    def __init__(self, message: str, path: Optional[str] = None):
        self.path = path
        super().__init__(message)


class QueueFull(Exception):
    """Bounded-queue backpressure: resubmit after ``retry_after`` seconds.

    Deliberately an exception, not a None return: a dropped request must
    be impossible to ignore silently at the call site.
    """

    def __init__(self, retry_after: float, depth: int):
        self.retry_after = float(retry_after)
        self.depth = int(depth)
        super().__init__(
            f"request queue full ({depth} deep); retry in "
            f"~{self.retry_after:.2f}s"
        )


def validate_emit_block(emit: Any) -> None:
    """Structural validation of a request's ``emit`` block (the checks
    that need no bucket/schema context), shared by
    :meth:`ScenarioRequest.from_mapping` and ``SimServer`` submit-time
    validation. Raises :class:`RequestValidationError` with the
    offending field's dotted path."""
    if emit is None:
        return
    if not isinstance(emit, Mapping):
        raise RequestValidationError(
            f"emit must be a mapping, got {type(emit).__name__}",
            path="emit",
        )
    unknown = set(emit) - {"paths", "every"}
    if unknown:
        raise RequestValidationError(
            f"unknown emit keys {sorted(unknown)}; known: every, paths",
            path=f"emit.{sorted(unknown)[0]}",
        )
    every = emit.get("every", 1)
    # integral-valued floats pass (the pre-round-15 server coerced
    # with int(), so a request file carrying 2.0 keeps working)
    if isinstance(every, bool) or not (
        isinstance(every, numbers.Integral)
        or (isinstance(every, numbers.Real)
            and float(every).is_integer())
    ):
        raise RequestValidationError(
            f"emit every must be an integer, got {every!r}",
            path="emit.every",
        )
    if every < 1:
        raise RequestValidationError(
            f"emit every={every} must be >= 1", path="emit.every"
        )
    paths = emit.get("paths")
    if paths is not None and (
        isinstance(paths, (str, bytes))
        or not isinstance(paths, (list, tuple))
        or not all(isinstance(p, str) for p in paths)
    ):
        raise RequestValidationError(
            "emit paths must be a list of path-prefix strings",
            path="emit.paths",
        )


def validate_prefix_block(prefix: Any) -> None:
    """Structural validation of a request's ``prefix`` block (shape
    only — horizon-grid and override-path checks need the bucket and
    stay server-side). Raises :class:`RequestValidationError` with the
    offending field's dotted path."""
    if prefix is None:
        return
    if not isinstance(prefix, Mapping):
        raise RequestValidationError(
            f"prefix must be a mapping, got {type(prefix).__name__}",
            path="prefix",
        )
    unknown = set(prefix) - {"horizon", "overrides"}
    if unknown:
        raise RequestValidationError(
            f"unknown prefix keys {sorted(unknown)}; known: "
            f"horizon, overrides",
            path=f"prefix.{sorted(unknown)[0]}",
        )
    if "horizon" not in prefix:
        raise RequestValidationError(
            "prefix needs a 'horizon'", path="prefix.horizon"
        )
    if isinstance(prefix["horizon"], bool) or not isinstance(
        prefix["horizon"], numbers.Real
    ):
        raise RequestValidationError(
            f"prefix horizon must be a number, got "
            f"{prefix['horizon']!r}",
            path="prefix.horizon",
        )
    overrides = prefix.get("overrides")
    if overrides is not None and not isinstance(overrides, Mapping):
        raise RequestValidationError(
            f"prefix overrides must be a mapping, got "
            f"{type(overrides).__name__}",
            path="prefix.overrides",
        )


def _sorted_tree(tree: Mapping[str, Any]) -> Dict[str, Any]:
    """Rebuild an override tree with mapping keys sorted at every
    level (leaves untouched). Dict order is semantically inert for the
    simulation but NOT for the bytes of a ``.lens`` header
    (``emit.log.make_header`` serializes config JSON in insertion
    order) or for the result-cache / dedup fingerprint — one ordering
    in, one ordering out."""
    return {
        k: _sorted_tree(v) if isinstance(v, Mapping) else v
        for k, v in sorted(tree.items(), key=lambda kv: str(kv[0]))
    }


def canonicalize_request(request: Mapping[str, Any]) -> Dict[str, Any]:
    """Fold spelling-level aliases out of a VALIDATED request mapping
    so equivalent submissions construct equal requests — the round-18
    result-cache / suffix-dedup key contract (one meaning, one
    fingerprint; docs/serving.md, "Suffix dedup & result cache") and
    the header-bytes contract above. Folds:

    - ``seed`` -> int; ``horizon`` / ``deadline`` -> float
    - override trees (top-level and ``prefix``) key-sorted recursively
    - ``n_agents``: integral -> int; per-species mapping key-sorted
      with int counts
    - ``emit``: ``every`` -> int with the default ``every=1`` elided,
      ``paths`` -> list of str with an empty list elided, and a
      fully-default block -> None
    - ``prefix``: ``horizon`` -> float, empty ``overrides`` elided

    Value aliases inside override LEAVES (``1`` vs ``1.0``) are
    deliberately NOT folded: leaf dtype can change the simulated bits,
    so those stay distinct requests — and distinct cache keys (the
    safe direction: a spurious miss, never a wrong hit).
    """
    req = dict(request)
    if "seed" in req:
        req["seed"] = int(req["seed"])
    for key in ("horizon", "deadline"):
        if req.get(key) is not None:
            req[key] = float(req[key])
    if isinstance(req.get("overrides"), Mapping):
        req["overrides"] = _sorted_tree(req["overrides"])
    n_agents = req.get("n_agents")
    if isinstance(n_agents, Mapping):
        req["n_agents"] = {
            k: int(v)
            for k, v in sorted(
                n_agents.items(), key=lambda kv: str(kv[0])
            )
        }
    elif n_agents is not None:
        req["n_agents"] = int(n_agents)
    if req.get("emit") is not None:
        emit = req["emit"]
        canon: Dict[str, Any] = {}
        if int(emit.get("every", 1)) != 1:
            canon["every"] = int(emit["every"])
        if emit.get("paths"):
            canon["paths"] = [str(p) for p in emit["paths"]]
        req["emit"] = canon or None
    if req.get("prefix") is not None:
        prefix: Dict[str, Any] = {
            "horizon": float(req["prefix"]["horizon"])
        }
        if req["prefix"].get("overrides"):
            prefix["overrides"] = _sorted_tree(
                req["prefix"]["overrides"]
            )
        req["prefix"] = prefix
    return req


class SimulationDiverged(Exception):
    """A request's lane produced non-finite state (NaN/Inf).

    Raised by ``SimServer.result`` for a request the per-window finite
    check (``check_finite="window"``) quarantined: its physics
    diverged, the request retired FAILED, its lane was reclaimed, and
    co-resident lanes are bitwise untouched (the serve path has no
    cross-lane coupling). Records streamed before detection — up to
    one window of which may be post-divergence garbage — stay in the
    request's sink/log; this error is what keeps a caller from
    mistaking them for a completed result.
    """


@dataclass
class ScenarioRequest:
    """One serving request: WHICH resident program (composite -> bucket)
    plus the per-request data that rides the lane.

    horizon:
        Sim seconds to run (must be a positive multiple of the bucket's
        timestep, and its step count a multiple of the bucket's
        emit_every — same divisibility contract as ``scan_schedule``).
    overrides:
        Initial-state overrides (schema-variable paths -> values), the
        same surface as a one-shot run's ``overrides`` config. Data
        only — shapes are the bucket's.
    n_agents:
        Initially-alive rows (int, or per-species mapping for
        multi-species buckets); None -> the bucket default.
    emit:
        Optional host-side emit spec: ``{"paths": [...]}`` keeps only
        leaves whose joined path starts with one of the prefixes;
        ``{"every": k}`` keeps every k-th emitted record (relative to
        the request's own start). Both filter AFTER the device emits at
        the bucket cadence, so they never change compiled shapes (or
        the bits of what is kept).
    deadline:
        Wall-clock seconds from submit; expired requests (queued OR
        mid-run) retire as TIMEOUT at the next tick, keeping whatever
        records they already streamed.
    hold_state:
        Retain the lane's final simulation state when the request
        retires DONE — registered (pinned) in the server's
        content-addressed ``SnapshotStore`` — so ``SimServer.resubmit``
        can EXTEND the scenario past its horizon later, as many times
        as the client likes: each continuation is admitted from the
        held bits and is bitwise what a longer original horizon would
        have produced. Costs one on-device lane-slice at retirement
        plus device memory until ``release_state`` drops the hold. The
        sweep driver's successive-halving rungs are the intended
        client (survivors extend, losers never rerun).
    prefix:
        Declare that the request's first ``prefix["horizon"]`` sim
        seconds are a SHARED prefix: the scenario built from
        ``(seed, prefix["overrides"])`` and run for that horizon, with
        this request's own ``overrides`` applied only afterwards, at
        the fork point. The server runs each distinct prefix ONCE
        (content-addressed snapshot store + request coalescing) and
        forks the cached device-resident state into every requester's
        lane; only suffix rows are emitted (times continue from the
        prefix horizon). Must be shorter than ``horizon`` and on the
        bucket's step/emit grid. See docs/serving.md, "Prefix caching
        & forking".
    tenant:
        The tenant this request belongs to (multi-tenant serving via
        the front door — docs/serving.md, "Front door"). The server
        keeps per-tenant counters (admitted/rejected/...) under this
        label; ``None`` (default) is untenanted traffic and counts
        nowhere extra.
    priority:
        Admission class: ``"interactive"`` requests are admitted ahead
        of ``"batch"`` (default) ones whenever both are queued; FIFO
        within a class. An all-default stream is the plain FIFO the
        server always had.
    """

    composite: str
    seed: int = 0
    horizon: float = 10.0
    overrides: Mapping[str, Any] = field(default_factory=dict)
    n_agents: Any = None
    emit: Optional[Mapping[str, Any]] = None
    deadline: Optional[float] = None
    hold_state: bool = False
    prefix: Optional[Mapping[str, Any]] = None
    tenant: Optional[str] = None
    priority: str = BATCH

    def prefix_spec(self) -> Optional[Dict[str, Any]]:
        """The ``SimServer.prewarm`` mapping for this request's shared
        prefix (None without one) — the ONE place the prefix's
        content-address-relevant field set is encoded for the warm
        drivers (front door, serve CLI), so a future prefix field
        cannot silently diverge between what clients submit and what
        warming precomputes."""
        if not self.prefix:
            return None
        prefix = dict(self.prefix)
        return {
            "composite": self.composite,
            "seed": int(self.seed),
            "horizon": float(prefix["horizon"]),
            "overrides": prefix.get("overrides") or {},
            "n_agents": self.n_agents,
        }

    @classmethod
    def from_mapping(
        cls, request: Mapping[str, Any]
    ) -> "ScenarioRequest":
        """Build from a JSON-shaped dict, validating every block's
        SHAPE eagerly with a descriptive error carrying a
        machine-readable field path (:class:`RequestValidationError`
        — the front door's 400 body quotes ``.path``). Schema-aware
        checks (override paths, horizon grid, n_agents vs capacity)
        still live server-side, where the bucket is known. The CLI and
        ``SimServer.submit`` both route mapping submissions through
        here."""
        known = {f.name for f in fields(cls)}
        unknown = set(request) - known
        if unknown:
            raise RequestValidationError(
                f"unknown request keys {sorted(unknown)}; known: "
                f"{sorted(known)}",
                path=sorted(unknown)[0],
            )
        def _bad(name: str, want: str, path: Optional[str] = None):
            return RequestValidationError(
                f"{name} must be {want}, got {request[name]!r}",
                path=path or name,
            )

        if "composite" in request and not isinstance(
            request["composite"], str
        ):
            raise _bad("composite", "a string")
        if "seed" in request and (
            isinstance(request["seed"], bool)
            or not isinstance(request["seed"], numbers.Integral)
        ):
            raise _bad("seed", "an integer")
        for key in ("horizon", "deadline"):
            if key in request and request[key] is not None and (
                isinstance(request[key], bool)
                or not isinstance(request[key], numbers.Real)
            ):
                raise _bad(key, "a number")
        if "overrides" in request and not isinstance(
            request["overrides"], Mapping
        ):
            raise _bad("overrides", "a mapping of state paths")
        if "n_agents" in request and request["n_agents"] is not None \
                and (
                    isinstance(request["n_agents"], bool)
                    or not isinstance(
                        request["n_agents"],
                        (numbers.Integral, Mapping),
                    )
                ):
            raise _bad(
                "n_agents", "an integer or per-species mapping"
            )
        if "hold_state" in request and not isinstance(
            request["hold_state"], bool
        ):
            raise _bad("hold_state", "a boolean")
        if "tenant" in request and request["tenant"] is not None \
                and not isinstance(request["tenant"], str):
            raise _bad("tenant", "a string")
        if "priority" in request and request["priority"] not in PRIORITIES:
            raise RequestValidationError(
                f"unknown priority {request['priority']!r}; known: "
                f"{', '.join(PRIORITIES)}",
                path="priority",
            )
        validate_emit_block(request.get("emit"))
        validate_prefix_block(request.get("prefix"))
        # alias folding happens HERE, at the one mapping->request
        # gate, so every downstream identity (cache fingerprint,
        # dedup key, header bytes) sees one spelling per meaning
        return cls(**canonicalize_request(request))


@dataclass
class Ticket:
    """Scheduler-side bookkeeping for one submitted request."""

    request_id: str
    request: ScenarioRequest
    status: str = QUEUED
    error: Optional[str] = None
    horizon_steps: int = 0
    steps_done: int = 0
    # steps already accounted for BEFORE this ticket ever runs (the
    # shared prefix's steps, or the parent chain's for a resubmit
    # continuation) — what steps_done/emit_count reset to when a
    # device quarantine re-queues the ticket for a clean re-run
    steps_base: int = 0
    lane: Optional[int] = None
    shard: Optional[int] = None  # device shard the lane lives on
    submitted_at: float = field(default_factory=time.perf_counter)
    admitted_at: Optional[float] = None
    finished_at: Optional[float] = None
    cancel_requested: bool = False
    emit_count: int = 0  # emitted records streamed so far (pre-filter)
    result_path: Optional[str] = None
    # -- observability marks (round 14, docs/observability.md) --
    # first_window_at / streamed_at: lifecycle wall stamps feeding the
    # server_meta.json per-request timing table (queued/admitted come
    # from submitted_at/admitted_at above, retired from finished_at).
    # stage / stage_tick: the request's last COMPLETED pipeline stage
    # and the scheduler tick it completed on — what WatchdogTimeout /
    # SimulationDiverged messages quote so a bounded-time failure
    # names where progress stopped.
    first_window_at: Optional[float] = None
    streamed_at: Optional[float] = None
    stage: str = "created"
    stage_tick: int = 0
    stage_info: Optional[tuple] = None
    # device-failover re-queue marks: the queue.wait span of a
    # re-admission must start at the requeue, not the original submit
    # (the time in between was spent RUNNING on the dead device), and
    # each admission attempt needs its own async-span id
    requeued_at: Optional[float] = None
    requeues: int = 0
    # -- continuation / fork plumbing (hold_state, resubmit, prefix) --
    # carry_state: a state pytree to scatter at admission instead of
    # building one from seed+overrides (set when a coalesced prefix
    # lands for a waiting fork; cleared once scattered). carry_key: a
    # SnapshotStore address this ticket holds ONE acquired ref on —
    # its scatter source (prefix hits, resubmit continuations);
    # released at scatter or on any terminal path. prefix_key: the
    # snapshot address a prefix-declaring request forks from.
    # content_key: this request's own content address (set when its
    # final state is a pure function of (seed, overrides, horizon) —
    # what hold_state pins and prefix runs publish). held_key: the
    # store entry this DONE ticket pins for resubmit (released by
    # release_state/close). waiting: queued but not yet admissible
    # (its prefix is still being computed). internal: a
    # server-generated prefix ticket (no client, no sink, no result).
    # parent: the request id this ticket continues, for provenance.
    carry_state: Any = None
    carry_shard: Optional[int] = None  # shard holding carry_state
    carry_key: Any = None
    prefix_key: Any = None
    content_key: Any = None
    held_key: Any = None
    waiting: bool = False
    internal: bool = False
    # a speculative prefix-warming run (SimServer.prewarm): internal,
    # admitted only into lanes no client ticket wants, preemptible —
    # its product is a warmed snapshot, never a result
    warm: bool = False
    parent: Optional[str] = None
    # quarantine (check_finite): the per-window finite check flagged
    # this ticket's lane; result() raises SimulationDiverged
    diverged: bool = False
    # sink_errors="request": this ticket's sink already failed and was
    # closed by the stream-side error handler — terminal paths must
    # not close (or stream to) it again
    sink_closed: bool = False
    # -- result cache / suffix dedup (round 18) --
    # fingerprint: the request's bytes-relevant content address
    # (serve.results.request_fingerprint), set at submit when either
    # knob is armed. leader: the request id of the in-flight identical
    # request this ticket COALESCED onto — a follower never queues,
    # never owns a lane; it rides the leader's stream with its own
    # sink and retires when the leader does.
    fingerprint: Optional[str] = None
    leader: Optional[str] = None

    def expired(self, now: float) -> bool:
        return (
            self.request.deadline is not None
            and now - self.submitted_at > self.request.deadline
        )

    def mark_stage(self, stage: str, tick: int, info=None) -> None:
        """Record the last completed pipeline stage (and the scheduler
        tick it completed on) — the breadcrumb failure messages quote.
        ``info`` carries the stage's raw detail fields; formatting is
        deferred to :meth:`stage_note` so the per-window hot path
        stores a tuple, not an f-string."""
        self.stage = stage
        self.stage_tick = int(tick)
        self.stage_info = info

    def stage_note(self) -> str:
        """The human form of the breadcrumb, for error messages."""
        stage = self.stage
        if stage == "window dispatched" and self.stage_info is not None:
            step, total, shard = self.stage_info
            stage = (
                f"window dispatched (through step {step} of {total}, "
                f"shard {shard})"
            )
        return (
            f"last completed stage: {stage!r} "
            f"(tick {self.stage_tick})"
        )


class RequestQueue:
    """Bounded FIFO of tickets awaiting a lane.

    ``take(bucket_of, free_lanes)`` pops admissible tickets in FIFO
    order, skipping (not blocking on) tickets whose bucket has no free
    lane — one saturated bucket must not head-of-line-block the others.
    """

    def __init__(self, max_depth: int = 64):
        if max_depth < 1:
            raise ValueError(f"max_depth={max_depth} must be >= 1")
        self.max_depth = int(max_depth)
        self._queue: List[Ticket] = []
        self._ids = itertools.count()

    def __len__(self) -> int:
        return len(self._queue)

    def __iter__(self) -> Iterator[Ticket]:
        """Queued tickets in FIFO order (read-only: the occupancy-
        derived ``retry_after`` hint sums the backlog's remaining
        windows)."""
        return iter(list(self._queue))

    def skip_ids(self, first: int) -> None:
        """Advance the id mint so the next id is ``req-<first>`` — WAL
        recovery reserves every id the previous incarnation handed out
        (re-queued tickets keep their original ids; fresh submissions
        must never collide with them)."""
        self._ids = itertools.count(int(first))

    def push(
        self, ticket: Ticket, retry_after: float, force: bool = False
    ) -> None:
        """``force=True`` bypasses the depth bound — reserved for
        server-GENERATED tickets (coalesced prefix runs), which are
        bounded by the distinct prefixes of already-admitted client
        tickets, not by client behavior; rejecting one would deadlock
        the forks already queued behind it."""
        if not force and len(self._queue) >= self.max_depth:
            raise QueueFull(retry_after, len(self._queue))
        self._queue.append(ticket)

    def next_id(self) -> str:
        return f"req-{next(self._ids):06d}"

    def drop(self, ticket: Ticket) -> bool:
        """Remove a specific queued ticket (cancel/expiry)."""
        try:
            self._queue.remove(ticket)
            return True
        except ValueError:
            return False

    def expire(self, now: float) -> List[Ticket]:
        """Pop every queued ticket whose deadline has passed."""
        expired = [t for t in self._queue if t.expired(now)]
        for t in expired:
            self._queue.remove(t)
        return expired

    def take(
        self, bucket_of, free_lanes: Dict[str, int], ready=None
    ) -> List[Ticket]:
        """Priority-then-FIFO admission pass: tickets whose bucket
        still has a free lane, decrementing ``free_lanes`` as it goes,
        considering every ``interactive`` ticket before any ``batch``
        one (stable within a class, so an all-default queue is the
        plain FIFO pass this always was — bit for bit). ``bucket_of``
        maps a ticket to its bucket name. ``ready`` (optional
        predicate) skips tickets that cannot be admitted yet — forks
        waiting on an in-flight prefix — without losing their queue
        position, the same non-blocking discipline as the per-bucket
        skip."""
        taken: List[Ticket] = []
        # stable sort on the class rank only: FIFO within interactive,
        # FIFO within batch, interactive first
        for t in sorted(
            self._queue,
            key=lambda t: 0 if t.request.priority == INTERACTIVE else 1,
        ):
            b = bucket_of(t)
            if (ready is None or ready(t)) and free_lanes.get(b, 0) > 0:
                free_lanes[b] -= 1
                taken.append(t)
        if taken:
            picked = {id(t) for t in taken}
            self._queue = [
                t for t in self._queue if id(t) not in picked
            ]
        return taken
