"""SimServer: a resident, continuously-batched scenario server.

The ROADMAP north star is "serves heavy traffic" — but every
``python -m lens_tpu run`` pays interpreter boot + trace + compile per
invocation, which caps the request rate at compiles per second, not
agent-steps per second. The fix is the inference-stack shape (one
resident program, many logical sequences packed into fixed slots,
host scheduler feeding it — Podracer's Sebulba, TF-Agents' batched
environments, LLM continuous batching):

- each configured BUCKET compiles one multi-lane window program at
  startup (lanes.LanePool over the existing Ensemble machinery) and
  keeps it hot for the server's lifetime;
- a host scheduler loop (``tick``) admits queued requests into free
  lanes, dispatches one window, streams each lane's freshly-produced
  records out through the framed emit-log format, and retires lanes
  whose horizon elapsed — requests with wildly different horizons
  share every dispatch;
- a bounded queue rejects with a retry-after hint when full
  (batcher.QueueFull), per-request wall-clock deadlines expire queued
  AND running work, and counters (metrics.ServerMetrics) plus a
  ``server_meta.json`` sidecar make the whole thing observable;
- the serve path is a depth-2 pipeline (round 10): the tick dispatches
  window k+1 while a background streamer thread (streamer.Streamer)
  slices/filters/appends window k — bookkeeping reads only host
  mirrors, hold_state snapshots stay on-device, and ``pipeline="off"``
  preserves the synchronous path (bitwise-identical results);
- shared scenario prefixes run ONCE (round 11): a request may declare
  a ``prefix`` (warmup horizon + shared overrides); a content-addressed
  snapshot store (snapshots.SnapshotStore — refcounted, byte-budgeted,
  LRU) caches the device-resident state at the fork point, concurrent
  submitters of one prefix coalesce onto a single in-flight prefix
  run, and each fork's lane is seeded by scattering the cached tree
  with its divergent overrides applied — N what-if branches cost one
  prefix plus N suffixes. ``hold_state`` final states live in the same
  store (pinned), so ``resubmit`` extends/forks a parent any number of
  times.

- the server is fault-tolerant (round 12, docs/serving.md "Fault
  tolerance & recovery"): an opt-in per-window finite check
  (``check_finite="window"``) quarantines a lane whose physics went
  NaN/Inf — that request alone fails with ``SimulationDiverged``, its
  lane is reclaimed, co-batched lanes are bitwise untouched; a
  watchdog (``watchdog_s``) expires hung window/streamer handoffs
  instead of wedging ``tick()``; and ``recover_dir`` arms a write-
  ahead log + held-snapshot spills making the server crash-
  recoverable — a SIGKILL'd server restarted over the same directory
  reproduces an uninterrupted run's results byte for byte. A
  deterministic ``FaultPlan`` (serve/faults.py) injects all three
  failure classes at named seams for tests/CI.

- the whole pipeline is observable (round 14, docs/observability.md):
  ``trace_dir`` arms a span tracer (lens_tpu.obs) that timestamps
  every stage of every request's life onto a framed span log —
  convertible to a Chrome/Perfetto timeline — and
  ``metrics_interval_s`` samples the metrics registry into a
  ``metrics.jsonl`` time-series ring, with Prometheus text exposition
  via :meth:`SimServer.prometheus_metrics`. Both off by default: the
  untraced server is the round-13 serve path bit for bit.

Determinism contract (pinned in tests/test_serve.py): a request's
emitted trajectory is BITWISE identical served solo or co-batched with
arbitrary other requests, across admission orders — per-request PRNG
keys, elementwise lane masking, and no cross-lane reduction anywhere in
the serve path.

Use in-process (tests, bench_serve.py)::

    server = SimServer.single_bucket("toggle_colony", lanes=8)
    rid = server.submit(ScenarioRequest(composite="toggle_colony",
                                        seed=7, horizon=50.0))
    server.run_until_idle()
    ts = server.result(rid)          # {"__times__": [T], leaves [T, ...]}

or from the CLI: ``python -m lens_tpu serve --requests reqs.json``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import replace as dc_replace
from typing import Any, Dict, List, Mapping, Optional

import jax
import numpy as np

from lens_tpu.emit import LogEmitter
from lens_tpu.emit.log import SEP
from lens_tpu.serve.batcher import (
    BATCH,
    CANCELLED,
    DONE,
    FAILED,
    MIGRATED,
    PRIORITIES,
    QUEUED,
    QueueFull,
    RUNNING,
    RequestValidationError,
    SimulationDiverged,
    TIMEOUT,
    RequestQueue,
    ScenarioRequest,
    Ticket,
    validate_emit_block,
    validate_prefix_block,
)
from lens_tpu.obs.metrics import MetricsRing
from lens_tpu.obs.trace import (
    REQUEST_TRACK,
    SCHED_TRACK,
    STREAM_TRACK,
    TRACE_NAME,
    NullTracer,
    Tracer,
    device_track,
)
from lens_tpu.serve.faults import FaultPlan
from lens_tpu.serve.lanes import LanePool
from lens_tpu.serve.metrics import (
    ServerMetrics,
    request_timing_row,
    write_server_meta,
)
from lens_tpu.serve.results import (
    ResultCache,
    log_config,
    request_fingerprint,
)
from lens_tpu.serve.snapshots import (
    DEVICE,
    SnapshotStore,
    snapshot_key,
)
from lens_tpu.serve.tiers import TieredSnapshotStore
from lens_tpu.serve.streamer import (
    LaneSlice,
    Streamer,
    WatchdogTimeout,
    WindowItem,
    process_window,
    subsample_rows,
)
from lens_tpu.parallel.mesh import serve_devices
from lens_tpu.serve.wal import (
    BEGIN,
    COALESCE,
    HOLD,
    QUARANTINE,
    RELEASE,
    RESUBMIT,
    RETIRE,
    SPILL_DIR,
    STREAMED,
    SUBMIT,
    WAL_NAME,
    ServeWal,
    buckets_fingerprint,
    classify_events,
    key_from_json,
    key_to_json,
)
from lens_tpu.utils.dicts import flatten_paths, get_path, set_path
from lens_tpu.utils.hostio import copy_tree_to_host_async

#: Per-bucket knobs and their defaults; see ``SimServer`` docstring.
BUCKET_DEFAULTS: Dict[str, Any] = {
    "composite": None,      # registry name (None: the bucket's own key)
    "config": {},           # composite factory config (shared per bucket)
    "capacity": None,       # colony rows (bare compartments; None: default)
    "n_agents": 1,          # default initially-alive rows per request
    "division": True,       # watch ('global','divide') for bare compartments
    "lanes": 4,             # resident lane count L
    "window": 32,           # steps per scheduler tick
    "timestep": 1.0,        # sim seconds per step
    "emit_every": 1,        # device emit cadence within the window
}


def _strip_seq(event: Mapping[str, Any]) -> Dict[str, Any]:
    """A WAL event without its source log's ``seq`` stamp — events
    copied across hosts during failover adoption are re-stamped by the
    destination WAL's own sequence."""
    return {k: v for k, v in event.items() if k != "seq"}


def _tree_to_json(tree: Mapping) -> Dict[str, Any]:
    """A nested override tree with array leaves as plain JSON (lists /
    scalars). The WAL's request serialization: lossless for the bits a
    request admits with, because the admission build casts override
    values to the schema leaf's dtype anyway (exact for every int and
    for float32 values round-tripped through Python floats)."""
    out: Dict[str, Any] = {}
    for path, value in flatten_paths(tree or {}):
        out = set_path(out, path, np.asarray(value).tolist())
    return out


def _request_to_json(request: ScenarioRequest) -> Dict[str, Any]:
    """A ``ScenarioRequest`` as the JSON the WAL records — exactly the
    mapping form ``submit`` accepts, so recovery re-queues with
    ``ScenarioRequest.from_mapping`` and the re-run is the same
    request."""
    out: Dict[str, Any] = {
        "composite": request.composite,
        "seed": int(request.seed),
        "horizon": float(request.horizon),
    }
    if request.overrides:
        out["overrides"] = _tree_to_json(request.overrides)
    if request.n_agents is not None:
        out["n_agents"] = (
            {str(k): int(v) for k, v in request.n_agents.items()}
            if isinstance(request.n_agents, Mapping)
            else int(request.n_agents)
        )
    if request.emit is not None:
        emit = dict(request.emit)
        if emit.get("paths"):
            emit["paths"] = [str(p) for p in emit["paths"]]
        out["emit"] = emit
    if request.deadline is not None:
        out["deadline"] = float(request.deadline)
    if request.hold_state:
        out["hold_state"] = True
    if request.prefix is not None:
        prefix = dict(request.prefix)
        block: Dict[str, Any] = {"horizon": float(prefix["horizon"])}
        if prefix.get("overrides"):
            block["overrides"] = _tree_to_json(prefix["overrides"])
        out["prefix"] = block
    # tenancy/priority (round 15): recorded only when set, so a WAL
    # written by untenanted traffic is byte-compatible with round 14
    if request.tenant is not None:
        out["tenant"] = str(request.tenant)
    if request.priority != BATCH:
        out["priority"] = str(request.priority)
    return out


class _RamResult:
    """In-process result sink: per-window segments, stacked on read."""

    def __init__(self) -> None:
        self._times: List[np.ndarray] = []
        self._segments: List[Dict] = []

    def append(self, tree: Mapping, times: np.ndarray) -> None:
        self._segments.append(dict(tree))
        self._times.append(np.asarray(times))

    def close(self) -> None:
        pass

    def timeseries(self) -> Dict[str, Any]:
        if not self._segments:
            return {"__times__": np.zeros(0)}
        out: Dict[str, Any] = {}
        for path, _ in flatten_paths(self._segments[0]):
            leaves = [
                np.asarray(get_path(seg, path)) for seg in self._segments
            ]
            out = set_path(out, path, np.concatenate(leaves))
        out["__times__"] = np.concatenate(self._times)
        return out


class _LogResult:
    """Disk result sink: one framed ``.lens`` log per request (header +
    one SEGMENT record per window). ``flush_every=k`` makes records
    visible to tailing readers (``emit.log.tail_records``) every ``k``
    windows — the batched flush policy; ``None`` defers visibility to
    close."""

    def __init__(self, path: str, request_id: str, config: Mapping,
                 flush_every: Optional[int] = 1):
        self.path = path
        # A request wholly owns its log. LogEmitter APPENDS (the run
        # path's resume semantics) — but serve request ids restart at
        # req-000000 per server, so a reused out_dir would silently
        # interleave a stale run's records into this request's stream
        # (and poison tailing readers). Truncate instead.
        if os.path.exists(path):
            os.remove(path)
        self._emitter = LogEmitter(
            experiment_id=request_id, config=config, path=path,
            flush_every=flush_every,
        )

    def append(self, tree: Mapping, times: np.ndarray) -> None:
        self._emitter.emit_trajectory(tree, times=times)

    def close(self) -> None:
        self._emitter.close()

    def timeseries(self) -> str:
        return self.path


class _Shard:
    """One device's slice of a bucket: a resident :class:`LanePool`
    committed to that device plus the per-device scheduler
    bookkeeping. The mesh server's failure domain — quarantine flips
    ``quarantined`` and everything here is written off together."""

    def __init__(self, index: int, device: Any, pool: LanePool):
        self.index = index
        self.device = device
        self.pool = pool
        self.assignments: Dict[int, Ticket] = {}
        # quarantine bookkeeping (check_finite="window"): the previous
        # window's device finite flags plus the {lane: (ticket,
        # step-after-window)} map frozen at dispatch — consumed at the
        # next tick's sweep
        self.pending_check = None
        self.quarantined = False
        # device watchdog arm: (dispatch wall time, THAT dispatch's
        # output handle). The handle is captured per window — newer
        # dispatches replace pool.remaining, so polling the pool's
        # current array would time window k's deadline against window
        # k+n's readiness and falsely quarantine a busy-but-healthy
        # device; the captured array stays pollable forever. None =
        # nothing being timed (watchdog off, or the last timed window
        # completed).
        self.watch: Optional[tuple] = None
        # per-shard accumulators behind the shard gauges
        self.windows = 0
        self.lane_windows_busy = 0
        self.lane_windows_total = 0
        self.diverged = 0

    def free_lanes(self) -> int:
        if self.quarantined:
            return 0
        return self.pool.n_lanes - len(self.assignments)

    def next_free_lane(self) -> int:
        return next(
            i for i in range(self.pool.n_lanes)
            if i not in self.assignments
        )


class _Bucket:
    """One composite's resident programs: a lane pool PER DEVICE SHARD
    (all identically shaped — one logical bucket, N failure domains)."""

    def __init__(
        self, name: str, cfg: Dict[str, Any], devices: List[Any]
    ):
        from lens_tpu.experiment import build_model
        from lens_tpu.utils.dicts import deep_merge

        self.name = name
        self.cfg = cfg = deep_merge(BUCKET_DEFAULTS, cfg or {})
        composite = cfg["composite"] or name
        built = build_model(
            composite,
            cfg["config"],
            capacity=cfg["capacity"],
            n_agents=cfg["n_agents"],
            division=cfg["division"],
        )
        self.shards = [
            _Shard(
                k,
                dev,
                LanePool(
                    built.sim,
                    n_lanes=int(cfg["lanes"]),
                    window_steps=int(cfg["window"]),
                    timestep=float(cfg["timestep"]),
                    emit_every=int(cfg["emit_every"]),
                    device=dev,
                ),
            )
            for k, dev in enumerate(devices)
        ]
        # normalize the bucket's n_agents default to the sim form once
        # (an int fans out per species on multi-species buckets)
        cfg["n_agents"] = self.pool.default_agents(cfg["n_agents"])

    @property
    def pool(self) -> LanePool:
        """The bucket's shape/validation surface (identical across
        shards — one bucket, one compiled shape family); shard 0's
        pool by convention. Device work must go through a specific
        shard's pool, never this."""
        return self.shards[0].pool

    def active_shards(self) -> List[_Shard]:
        return [s for s in self.shards if not s.quarantined]

    def free_lanes(self) -> int:
        return sum(s.free_lanes() for s in self.shards)

    def lanes_total(self) -> int:
        """Schedulable lanes (quarantined devices excluded — a
        half-dead mesh must not advertise capacity it cannot run)."""
        return sum(
            s.pool.n_lanes for s in self.shards if not s.quarantined
        )

    def busy(self) -> int:
        return sum(len(s.assignments) for s in self.shards)

    def place(self, prefer: Optional[int] = None) -> _Shard:
        """Choose the shard a ticket admits into: the preferred shard
        (the one owning its cached snapshot — the scatter stays
        device-local) when it has a free lane, else the active shard
        with the most free lanes (deterministic tie-break: lowest
        index). Callers guarantee at least one free lane exists."""
        if prefer is not None and 0 <= prefer < len(self.shards):
            s = self.shards[prefer]
            if s.free_lanes() > 0:
                return s
        return max(
            self.active_shards(),
            key=lambda s: (s.free_lanes(), -s.index),
        )


class SimServer:
    """Continuous-batching scenario server over vmapped simulation lanes.

    Parameters
    ----------
    buckets:
        ``{bucket_name: bucket_config}`` — each entry compiles one
        resident multi-lane program (knobs: ``BUCKET_DEFAULTS``).
        Requests route to the bucket whose name matches their
        ``composite`` field.
    queue_depth:
        Bound on requests waiting for a lane, across all buckets. A
        full queue rejects (``QueueFull`` with a retry-after hint).
    out_dir / sink:
        ``sink="ram"`` keeps results in process (tests, bench);
        ``sink="log"`` streams each request to
        ``<out_dir>/<request_id>.lens`` — readable while still being
        written via :func:`lens_tpu.emit.log.tail_records`.
    stream_flush:
        With the log sink, flush so concurrent readers see records
        promptly (off = records visible only at close). The cadence is
        ``flush_every``.
    flush_every:
        Batched flush policy for the log sink: flush each request's
        log after every k-th window append (1 = per window, the
        tightest tailing-reader staleness; larger batches the flush
        syscalls). Ignored when ``stream_flush`` is off.
    pipeline:
        ``"on"`` (default): depth-2 pipeline — the scheduler
        dispatches window k+1 while a background streamer thread
        slices/filters/appends window k (docs/serving.md, "Pipelining
        & backpressure"). ``"off"``: the synchronous r08 path (every
        tick blocks on the window's host transfer and sink appends) —
        the debugging baseline; both produce bitwise-identical
        results.
    stream_queue:
        Pipeline depth bound: at most this many windows may be queued
        or in processing on the streamer; the scheduler stalls past it
        (backpressure — bounded memory, bounded reader staleness).
    snapshot_budget_mb:
        Byte budget (MiB) for the content-addressed snapshot store
        backing prefix caching and ``hold_state`` (docs/serving.md,
        "Prefix caching & forking"). Unpinned prefix snapshots are
        evicted LRU-first past the budget; pinned held states are the
        client's working set and always land. ``None`` = unbounded.
        With the TIERED store armed (below), the budget bounds the
        DEVICE tier and eviction becomes demotion.
    host_budget_mb:
        Arm the host-RAM snapshot tier (docs/serving.md, "Tiered
        snapshots & speculative warming"): snapshots past the device
        budget demote device->host (one async ``device_get``) instead
        of evicting, and a hit on a host-resident entry promotes it
        back onto the admitting shard's device. ``None`` (default):
        no host tier — the round-15 store, bit for bit.
    tier_dir:
        Arm the DISK snapshot tier: host-tier overflow (or device
        overflow, with no host tier) demotes to disk via the
        checkpoint rename protocol, and the directory SURVIVES
        RESTARTS — a fresh server over the same ``tier_dir`` re-adopts
        every content-addressed snapshot at construction, so repeat
        traffic after a reboot forks from warm disk entries instead
        of recomputing prefixes. Defaults to ``<recover_dir>/snapshots``
        when ``recover_dir`` is set AND a host budget armed the tiers;
        a plain ``recover_dir`` (no tier knobs) keeps round-15
        eviction semantics while still unifying hold spills with the
        tier's on-disk object format.
    check_finite:
        ``"window"`` arms the lane quarantine: after every window a
        jitted per-lane finite check rides the trajectory's
        device->host copy, and the NEXT tick fails any occupied lane
        whose state went NaN/Inf — that request alone retires FAILED
        (``result()`` raises ``SimulationDiverged``), its lane is
        reclaimed, co-resident lanes are bitwise untouched. ``"off"``
        (default) dispatches nothing extra — the round-11 path,
        bitwise. See docs/serving.md, "Fault tolerance & recovery".
    watchdog_s:
        Arm the handoff watchdog: a scheduler wait on the stream pipe
        (backpressure stall, drain, result) that makes no progress for
        this many seconds raises ``WatchdogTimeout`` instead of
        wedging ``tick()`` behind a hung sink or device window
        forever. ``None`` (default) = wait indefinitely.
    sink_errors:
        What a failed SINK APPEND (one request's result log raising —
        disk quota, injected io_error) does. ``"fatal"`` (default, the
        round-14 contract): the error parks on the stream pipe and
        raises at the next scheduler call — correct for a
        single-operator batch server where a torn stream means the
        run is over. ``"request"``: the failure is scoped to the ONE
        request whose sink raised — it retires FAILED with the cause,
        its lane is reclaimed, every co-batched request keeps
        streaming — the multi-tenant front-door policy (one tenant's
        full disk must not take the server down). Errors not
        attributable to a single sink (the device fetch itself) stay
        fatal either way.
    recover_dir:
        Directory for the serve write-ahead log (``serve.wal``) and
        held-snapshot spills (``snapshots/``). When given, every
        client submit/resubmit/terminal is WAL'd (group-commit fsync
        per tick), ``hold_state`` snapshots spill via the checkpoint
        rename protocol — and if the directory already holds a WAL,
        the constructor RECOVERS: finished requests materialize as
        terminal tickets over their existing result logs, held
        snapshots re-pin from their spills, and every unfinished
        request is re-queued under its original id, producing results
        bitwise equal to an uninterrupted run. Requires ``sink="log"``
        (results must live on disk to survive a restart).
    faults:
        A :class:`~lens_tpu.serve.faults.FaultPlan` (tests/bench/CI
        chaos only): deterministic injection of NaN lanes, sink I/O
        errors, stream stalls, device-down declarations, and SIGKILL
        kill-points at the named seams. ``None`` = no seams armed.
    mesh:
        Shard the server across devices (docs/serving.md, "Mesh
        serving & device failover"): each bucket holds one resident
        lane pool PER DEVICE, admission scatter and ``hold_state``
        capture stay device-local, and this one host scheduler ticks
        all shards. Accepts a device count (the first N of
        ``jax.devices()``), an explicit device list, or a
        ``jax.sharding.Mesh`` (its devices in flat order). ``None``
        (default): one uncommitted pool on the default device — the
        single-device server, bit for bit. Per-request bits are
        placement-independent (each lane is an independent scenario),
        so results are bitwise identical at any mesh size.
    device_watchdog_s:
        Whole-device hang detection: a shard whose dispatched window
        has not completed (output buffers still not ready) after this
        many wall seconds is QUARANTINED — drained from scheduling,
        its requests re-queued onto surviving devices (``None`` =
        off). The fail-stop companion to ``FaultPlan`` ``device_down``
        declarations and operator :meth:`quarantine_device` calls.
    trace_dir:
        Arm span tracing (docs/observability.md): every stage of every
        request's life — queue wait, admission scatter, window
        dispatch, device compute, streamer flush, retirement, prefix
        resolution, hold spills, recovery replay, device quarantine
        and requeues, injected faults — is appended as a structured
        span/instant event to ``<trace_dir>/serve.trace`` (framed
        JSON, buffered — observability never taxes the hot path for
        durability). Convert to a Chrome/Perfetto timeline with
        ``python -m lens_tpu trace <trace_dir> --out trace.json``.
        ``None`` (default): a no-op NullTracer — the round-13 serve
        path bit for bit.
    metrics_interval_s:
        Sample the metrics registry (counters, gauges, latency/stream
        histograms, per-shard health) into a ``metrics.jsonl`` ring on
        this wall-clock cadence — occupancy and queue depth as
        HISTORY, not just a close-time number. The ring lives in
        ``trace_dir`` (falling back to ``out_dir``); ``0`` samples
        every tick (tests). ``None`` (default): no sampling. Pull-style
        exposition is always available via :meth:`prometheus_metrics`.
    """

    def __init__(
        self,
        buckets: Mapping[str, Mapping[str, Any]],
        queue_depth: int = 64,
        out_dir: Optional[str] = None,
        sink: str = "ram",
        stream_flush: bool = True,
        flush_every: int = 1,
        pipeline: str = "on",
        stream_queue: int = 2,
        snapshot_budget_mb: Optional[float] = None,
        host_budget_mb: Optional[float] = None,
        tier_dir: Optional[str] = None,
        check_finite: str = "off",
        watchdog_s: Optional[float] = None,
        sink_errors: str = "fatal",
        recover_dir: Optional[str] = None,
        faults: Optional[FaultPlan] = None,
        mesh: Any = None,
        device_watchdog_s: Optional[float] = None,
        trace_dir: Optional[str] = None,
        metrics_interval_s: Optional[float] = None,
        result_cache_mb: Optional[float] = None,
        dedup: str = "off",
    ):
        if not buckets:
            raise ValueError("SimServer needs at least one bucket")
        if sink not in ("ram", "log"):
            raise ValueError(f"unknown sink {sink!r}; known: ram, log")
        if sink == "log" and not out_dir:
            raise ValueError("sink='log' needs out_dir")
        if pipeline not in ("on", "off"):
            raise ValueError(
                f"unknown pipeline {pipeline!r}; known: on, off"
            )
        if flush_every < 1:
            raise ValueError(f"flush_every={flush_every} must be >= 1")
        if check_finite not in ("off", "window"):
            raise ValueError(
                f"unknown check_finite {check_finite!r}; known: "
                f"off, window"
            )
        if sink_errors not in ("fatal", "request"):
            raise ValueError(
                f"unknown sink_errors {sink_errors!r}; known: "
                f"fatal, request"
            )
        if recover_dir and sink != "log":
            raise ValueError(
                "recover_dir needs sink='log': recovery can only hand "
                "back results that live on disk"
            )
        if device_watchdog_s is not None and device_watchdog_s <= 0:
            raise ValueError(
                f"device_watchdog_s={device_watchdog_s} must be > 0"
            )
        if host_budget_mb is not None and host_budget_mb < 0:
            raise ValueError(
                f"host_budget_mb={host_budget_mb} must be >= 0"
            )
        if dedup not in ("on", "off"):
            raise ValueError(
                f"unknown dedup {dedup!r}; known: on, off"
            )
        if result_cache_mb is not None:
            if result_cache_mb <= 0:
                raise ValueError(
                    f"result_cache_mb={result_cache_mb} must be > 0"
                )
            if sink != "log":
                raise ValueError(
                    "result_cache_mb needs sink='log': the cache "
                    "stores and replays whole .lens result logs"
                )
            if not (tier_dir or recover_dir):
                raise ValueError(
                    "result_cache_mb needs tier_dir or recover_dir "
                    "(a durable directory for the cached results to "
                    "live in)"
                )
        if metrics_interval_s is not None:
            if metrics_interval_s < 0:
                raise ValueError(
                    f"metrics_interval_s={metrics_interval_s} must "
                    f"be >= 0"
                )
            if not (trace_dir or out_dir):
                raise ValueError(
                    "metrics_interval_s needs trace_dir or out_dir "
                    "(somewhere for metrics.jsonl to live)"
                )
        # tracing first: buckets/pools/streamer/store all hang spans
        # off it. NullTracer when off — falsy, every call a no-op, the
        # round-13 code path bit for bit.
        self.trace_dir = trace_dir
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)
            self.trace: Any = Tracer(os.path.join(trace_dir, TRACE_NAME))
        else:
            self.trace = NullTracer()
        self.metrics_interval_s = metrics_interval_s
        self._metrics_ring: Optional[MetricsRing] = None
        self._next_sample = 0.0
        if metrics_interval_s is not None:
            self._metrics_ring = MetricsRing(
                os.path.join(trace_dir or out_dir, "metrics.jsonl")
            )
        self.devices = serve_devices(mesh)
        self.n_shards = len(self.devices)
        self.device_watchdog_s = device_watchdog_s
        self._quarantined: set = set()  # downed device shard indices
        self.buckets = {
            name: _Bucket(name, dict(cfg or {}), self.devices)
            for name, cfg in buckets.items()
        }
        if self.trace:
            for b in self.buckets.values():
                for s in b.shards:
                    s.pool.trace = self.trace
        self.queue = RequestQueue(queue_depth)
        self._metrics = ServerMetrics()
        self._metrics.lanes_total = sum(
            b.lanes_total() for b in self.buckets.values()
        )
        self.out_dir = out_dir
        # where server_meta.json lands at close (defaults to out_dir;
        # cluster workers share one out_dir for result logs but must
        # not clobber each other's meta, so each points this at its
        # own per-host directory)
        self.meta_dir = out_dir
        self.sink = sink
        self.stream_flush = stream_flush
        self.flush_every = int(flush_every)
        self.pipeline = pipeline
        self.check_finite = check_finite
        self.watchdog_s = watchdog_s
        self.sink_errors = sink_errors
        # sink_errors="request": failures the stream thread scoped to
        # one request's sink, consumed (and turned into FAILED
        # retirements) at the next tick's sweep
        self._sink_failures: Dict[str, BaseException] = {}
        self._sink_fail_lock = threading.Lock()
        self.faults = faults if faults is not None else FaultPlan(None)
        if self.trace:
            self.faults.trace = self.trace
        self._streamer: Optional[Streamer] = (
            Streamer(max_inflight=int(stream_queue),
                     metrics=self._metrics,
                     watchdog_s=watchdog_s,
                     faults=self.faults,
                     trace=self.trace)
            if pipeline == "on"
            else None
        )
        # -- snapshot store: flat (round 15) or tiered (round 16) --
        # The tiered store arms when any tier knob is given OR a
        # recover_dir exists (hold spills and the disk tier share one
        # on-disk object, so recovery adopts spills INTO the store);
        # with no tier knobs demote_to_disk stays off and the store
        # behaves exactly like the round-15 flat one.
        self._fingerprint = buckets_fingerprint(
            {n: b.cfg for n, b in self.buckets.items()}
        )
        budget_bytes = (
            None
            if snapshot_budget_mb is None
            else int(float(snapshot_budget_mb) * 2**20)
        )
        self.tier_dir = tier_dir
        tiers_on = host_budget_mb is not None or tier_dir is not None
        disk_dir = tier_dir or (
            os.path.join(recover_dir, SPILL_DIR) if recover_dir
            else None
        )
        if tiers_on or disk_dir is not None:
            self.snapshots: SnapshotStore = TieredSnapshotStore(
                budget_bytes=budget_bytes,
                host_budget_bytes=(
                    int(float(host_budget_mb) * 2**20)
                    if host_budget_mb
                    else 0
                ),
                dir=disk_dir,
                demote_to_disk=tiers_on and disk_dir is not None,
                fingerprint=self._fingerprint,
            )
        else:
            self.snapshots = SnapshotStore(budget_bytes=budget_bytes)
        if self.trace:
            self.snapshots.trace = self.trace
        # -- request-stream CDN (round 18, docs/serving.md "Suffix
        # dedup & result cache"): a durable content-addressed RESULT
        # cache (whole .lens logs, served at submit with zero device
        # windows) + in-flight suffix dedup (identical concurrent
        # requests coalesce onto ONE lane, fanning out at the
        # streamer). Both off by default: the dormant path is the
        # round-17 server bit for bit. --
        self.result_cache_mb = result_cache_mb
        self.dedup = dedup
        self._result_cache: Optional[ResultCache] = None
        self._result_evictions_seen = 0
        # DONE tickets awaiting cache filing (appended by the stream
        # thread's completion callback, drained on the scheduler
        # thread each tick — list.append is atomic, same handoff
        # discipline as _sink_failures)
        self._cache_pending: List[Ticket] = []
        # dedup state: fingerprint -> the QUEUED ticket later
        # identical submits may attach to; leader rid -> its attached
        # follower tickets (never queued, never own a lane)
        self._dedup_leaders: Dict[str, Ticket] = {}
        self._dedup_groups: Dict[str, List[Ticket]] = {}
        if result_cache_mb is not None:
            self._result_cache = ResultCache(
                os.path.join(tier_dir or recover_dir, "results"),
                budget_bytes=int(float(result_cache_mb) * 2**20),
                fingerprint=self._fingerprint,
            )
            # the cache's kill seams fire under this server's plan
            # (the SIGKILL-mid-write durability drills)
            self._result_cache.faults = self.faults
        # counters mirrored from the store into the metrics registry
        # (delta-synced at gauge refresh: the store is scheduler-
        # thread-only, the registry is the export surface)
        self._rejected_seen = 0
        # scheduler tick sequence: the correlation coordinate every
        # span/instant and every stage breadcrumb carries (counters
        # track it too; this mirror avoids a dict build per event)
        self._ticks = 0
        # in-flight prefix coalescing: snapshot key -> fork tickets
        # waiting for the (single) internal prefix run computing it
        self._pending_prefix: Dict[Any, List[Ticket]] = {}
        # speculative warming (docs/serving.md, "Tiered snapshots &
        # speculative warming"): warm tickets wait OUTSIDE the bounded
        # client queue (scavengers must not consume client depth) and
        # admit only into lanes no admissible client ticket wants;
        # _warm_pending tracks the keys whose snapshot is being
        # computed by a warm run, so a client prefix submit that
        # coalesces onto one counts as a speculative hit
        self._warm_queue: List[Ticket] = []
        self._warm_pending: set = set()
        self.tickets: Dict[str, Ticket] = {}
        self._results: Dict[str, Any] = {}
        # per-request stream-completion events (pipelined): set once
        # the request's last sink append + close landed, so result()
        # can wait for ONE request instead of draining the whole pipe
        self._stream_done: Dict[str, threading.Event] = {}
        self._closed = False
        # -- write-ahead log + recovery (docs/serving.md, "Fault
        # tolerance & recovery") --
        self.recover_dir = recover_dir
        self._wal: Optional[ServeWal] = None
        self.recovered = 0  # unfinished WAL requests re-queued
        if recover_dir:
            os.makedirs(recover_dir, exist_ok=True)
            self._wal = ServeWal(
                os.path.join(recover_dir, WAL_NAME),
                n_shards=self.n_shards,
            )
            had_events = self._wal.replayed()
            self._wal.begin(
                self._fingerprint,
                {n: {"composite": b.cfg["composite"] or n}
                 for n, b in self.buckets.items()},
            )
            if had_events:
                with self.trace.span(
                    "recovery.replay", track=SCHED_TRACK,
                    events=len(self._wal.events),
                ):
                    self._recover()

    @classmethod
    def single_bucket(cls, composite: str, **kwargs) -> "SimServer":
        """Convenience: one bucket named after its composite. Bucket
        knobs (lanes, window, ...) ride ``kwargs``; server knobs
        (queue_depth, out_dir, sink, stream_flush, flush_every,
        pipeline, stream_queue) are split off."""
        server_keys = (
            "queue_depth", "out_dir", "sink", "stream_flush",
            "flush_every", "pipeline", "stream_queue",
            "snapshot_budget_mb", "host_budget_mb", "tier_dir",
            "check_finite", "watchdog_s",
            "sink_errors", "recover_dir", "faults", "mesh",
            "device_watchdog_s", "trace_dir", "metrics_interval_s",
            "result_cache_mb", "dedup",
        )
        server_kwargs = {
            k: kwargs.pop(k) for k in server_keys if k in kwargs
        }
        return cls({composite: kwargs}, **server_kwargs)

    # -- client surface ------------------------------------------------------

    def reserve_id(self) -> str:
        """Mint (and permanently consume) the next request id WITHOUT
        queueing anything — the front door reserves ids at HTTP accept
        time so a client holds its rid while the request still waits
        in the tenant scheduler, then submits with ``rid=``. A
        reserved id that is never submitted (cancelled at the front
        door) simply leaves a gap in the sequence."""
        return self.queue.next_id()

    def validate(
        self, request: ScenarioRequest | Mapping[str, Any]
    ) -> ScenarioRequest:
        """Run the full submit-time validation WITHOUT queueing:
        raises exactly what :meth:`submit` would raise for a malformed
        request (``ValueError``/``RequestValidationError``), returns
        the parsed request otherwise. The front door's 400-before-
        enqueue check. No side effects."""
        if isinstance(request, Mapping):
            request = ScenarioRequest.from_mapping(request)
        self._build_ticket(request, "validate-probe")
        return request

    def retry_after_hint(self) -> float:
        """The occupancy-derived backpressure hint (what a ``QueueFull``
        would quote right now) — the front door's ``Retry-After``
        source for refusals it issues itself (tenant queue full,
        drain)."""
        return self._retry_after()

    def submit(
        self,
        request: ScenarioRequest | Mapping[str, Any],
        rid: Optional[str] = None,
    ) -> str:
        """Queue a request; returns its request id.

        Raises ``ValueError`` for malformed requests — unknown bucket
        or request keys, horizon off the bucket's step/emit grid,
        override paths that are not schema variables, malformed
        ``emit``/``prefix`` blocks, out-of-range ``n_agents`` — all
        validated EAGERLY here (descriptive errors at the submit call
        site, not a FAILED ticket from deep inside the admission
        build). Raises ``QueueFull`` for backpressure (a healthy
        client retries after ``.retry_after`` seconds).

        ``rid`` admits under a PRE-RESERVED id (one previously handed
        out by :meth:`reserve_id` — the front door's deferred-submit
        path); default is to mint the next id here.
        """
        if isinstance(request, Mapping):
            request = ScenarioRequest.from_mapping(request)
        ticket = self._build_ticket(
            request, rid if rid is not None else self.queue.next_id()
        )
        # request-stream CDN (round 18): a durable cache hit serves
        # the whole result at submit — no queue, no lane, no device
        # window; an identical IN-FLIGHT request absorbs this one as a
        # follower on its lane. Both run after validation (malformed
        # requests still raise here) and neither consumes queue depth,
        # so duplicates can never be refused by backpressure.
        if (
            self._result_cache is not None
            and not request.hold_state
            and self._serve_cached(ticket)
        ):
            return ticket.request_id
        if self.dedup == "on" and self._try_coalesce(ticket):
            self.tickets[ticket.request_id] = ticket
            self._metrics.inc("submitted")
            self._metrics.tenant_inc(request.tenant, "admitted")
            if self._wal is not None:
                self._wal.append({
                    "event": SUBMIT,
                    "rid": ticket.request_id,
                    "request": _request_to_json(request),
                })
                # audit fact, not recovery state: replayed SUBMITs
                # re-coalesce through the same deterministic logic
                self._wal.append({
                    "event": COALESCE,
                    "rid": ticket.request_id,
                    "leader": ticket.leader,
                })
                self.faults.kill("submit.walled")
            return ticket.request_id
        try:
            self.queue.push(ticket, retry_after=self._retry_after())
        except QueueFull:
            self._metrics.inc("rejected")
            self._metrics.tenant_inc(request.tenant, "rejected")
            self._metrics.queue_depth = len(self.queue)
            raise
        self._metrics.tenant_inc(request.tenant, "admitted")
        self._register(ticket)
        if self._wal is not None:
            # durable intent: the WAL knows the request before the
            # client holds its id (flushed to the OS now; fsynced by
            # the next tick's group commit)
            self._wal.append({
                "event": SUBMIT,
                "rid": ticket.request_id,
                "request": _request_to_json(request),
            })
            self.faults.kill("submit.walled")
        return ticket.request_id

    def _build_ticket(self, request: ScenarioRequest, rid: str) -> Ticket:
        """Validate a request and build its (unqueued) ticket — shared
        by ``submit`` and WAL recovery's re-queue (which preserves the
        original request id)."""
        bucket = self.buckets.get(request.composite)
        if bucket is None:
            raise RequestValidationError(
                f"no bucket serves composite {request.composite!r}; "
                f"configured: {sorted(self.buckets)}",
                path="composite",
            )
        if not bucket.active_shards():
            raise ValueError(
                f"every device serving composite "
                f"{request.composite!r} is quarantined "
                f"({sorted(self._quarantined)}); the server has no "
                f"schedulable capacity for this bucket"
            )
        steps = self._horizon_steps(bucket, request.horizon)
        self._validate_request(bucket, request)
        prefix_steps, prefix_key = self._validate_prefix(
            bucket, request, steps
        )
        return Ticket(
            request_id=rid,
            request=request,
            horizon_steps=steps,
            # a fork's prefix counts as already-done work: only the
            # suffix arms, and its emit grid continues the prefix's so
            # the suffix rows land exactly where a solo full run's
            # would (times AND every-k subsample phase)
            steps_done=prefix_steps,
            steps_base=prefix_steps,
            emit_count=prefix_steps // bucket.pool.emit_every,
            prefix_key=prefix_key,
            # the content address is only read when the final state is
            # retained (hold_state retirement, resubmit advancement) —
            # hashing override bytes for every throwaway trial would
            # tax the admission hot path for nothing
            content_key=(
                self._content_key(bucket, request, steps)
                if request.hold_state
                else None
            ),
            # the result/dedup content address, computed only when a
            # CDN knob is armed (both off: no hashing on the submit
            # path, the round-17 cost profile exactly)
            fingerprint=(
                request_fingerprint(_request_to_json(request))
                if self._result_cache is not None
                or self.dedup == "on"
                else None
            ),
        )

    def _register(self, ticket: Ticket) -> None:
        """Post-push bookkeeping shared by ``submit`` and recovery."""
        self._metrics.inc("submitted")
        ticket.mark_stage("queued", self._ticks)
        self.tickets[ticket.request_id] = ticket
        if ticket.prefix_key is not None:
            self._resolve_prefix(
                ticket, self.buckets[ticket.request.composite]
            )
        if (
            self.dedup == "on"
            and ticket.fingerprint is not None
            and not ticket.internal
        ):
            # this queued ticket is now the lane later identical
            # submits coalesce onto (latest queued wins; attachment
            # is refused once it stops being QUEUED, and the entry is
            # dropped at retirement)
            self._dedup_leaders[ticket.fingerprint] = ticket
        self._metrics.queue_depth = len(self.queue)

    def _validate_request(
        self, bucket: _Bucket, request: ScenarioRequest
    ) -> None:
        """Eager submit-time validation of the per-request data blocks
        (the checks that need no compiled state): the emit spec's
        shape, override PATHS against the bucket's schema, and
        n_agents against its capacities. Value shapes still validate
        at admission (they need the built state) and still fail only
        the one request."""
        validate_emit_block(request.emit)
        validate_prefix_block(request.prefix)
        if request.priority not in PRIORITIES:
            raise RequestValidationError(
                f"unknown priority {request.priority!r}; known: "
                f"{', '.join(PRIORITIES)}",
                path="priority",
            )
        pool = bucket.pool
        try:
            pool.validate_overrides(request.overrides, what="override")
        except RequestValidationError:
            raise
        except ValueError as e:
            raise RequestValidationError(str(e), path="overrides")
        if request.prefix is not None:
            try:
                pool.validate_overrides(
                    dict(request.prefix).get("overrides"),
                    what="prefix override",
                )
            except RequestValidationError:
                raise
            except ValueError as e:
                raise RequestValidationError(
                    str(e), path="prefix.overrides"
                )
        try:
            pool.validate_agents(self._request_agents(bucket, request))
        except RequestValidationError:
            raise
        except ValueError as e:
            raise RequestValidationError(str(e), path="n_agents")

    def _validate_prefix(
        self, bucket: _Bucket, request: ScenarioRequest, steps: int
    ):
        """Validate a request's ``prefix`` block; returns
        ``(prefix_steps, snapshot_key)`` (``(0, None)`` without one)."""
        if request.prefix is None:
            return 0, None
        prefix = dict(request.prefix)
        unknown = set(prefix) - {"horizon", "overrides"}
        if unknown:
            raise ValueError(
                f"unknown prefix keys {sorted(unknown)}; known: "
                f"horizon, overrides"
            )
        if "horizon" not in prefix:
            raise ValueError("prefix needs a 'horizon'")
        prefix_steps = self._horizon_steps(bucket, prefix["horizon"])
        if prefix_steps >= steps:
            raise RequestValidationError(
                f"prefix horizon ({prefix['horizon']}) must be shorter "
                f"than the request horizon ({request.horizon}) — the "
                f"suffix needs at least one step",
                path="prefix.horizon",
            )
        key = snapshot_key(
            request.composite,
            int(request.seed),
            self._request_agents(bucket, request),
            prefix.get("overrides") or {},
            prefix_steps,
        )
        return prefix_steps, key

    def _content_key(
        self, bucket: _Bucket, request: ScenarioRequest, steps: int
    ):
        """The request's OWN content address, when its final state is a
        pure function of (seed, initial overrides, n_agents, horizon):
        plain requests always are; forks are only when their divergent
        overrides are empty (then the whole run equals a solo run under
        the prefix's overrides). Impure forks hold state under a
        per-request key instead (resubmit still works; the entry just
        cannot serve content-addressed prefix hits)."""
        if request.prefix is None:
            eff = request.overrides or {}
        elif not request.overrides:
            eff = dict(request.prefix).get("overrides") or {}
        else:
            return None
        return snapshot_key(
            request.composite,
            int(request.seed),
            self._request_agents(bucket, request),
            eff,
            steps,
        )

    def _request_agents(self, bucket: _Bucket, request: ScenarioRequest):
        """The normalized n_agents a request admits with (shared by
        admission and the snapshot content address)."""
        return bucket.pool.default_agents(
            request.n_agents
            if request.n_agents is not None
            else bucket.cfg["n_agents"]
        )

    # -- request-stream CDN (round 18) ---------------------------------------

    def _serve_cached(self, t: Ticket) -> bool:
        """Serve one submit whole from the durable result cache: the
        cached log's bytes are replayed as the new rid's own
        ``<rid>.lens`` (header re-minted, every other frame verbatim —
        byte-equal to what this request's own cold run would write),
        and the ticket is born terminal — no queue, no lane, zero
        device windows. Any replay failure degrades to a miss and the
        caller falls through to the normal path. ``hold_state``
        requests never take this path (their product includes a
        pinned device snapshot only a real lane can capture)."""
        # results the streamer completed since the last tick file NOW
        # (the tick's own sweep may not have run yet — an idle server's
        # final completions land between ticks, and a submit must see
        # them; submit and tick are serialized by the caller contract)
        self._sweep_result_cache()
        fp = t.fingerprint
        if fp not in self._result_cache \
                and not self._result_cache.refresh(fp):
            # refresh: under a cluster, a PEER worker (or the router)
            # may have filed this fingerprint into the shared results
            # dir since our scan
            self._metrics.inc("result_misses")
            return False
        rid = t.request_id
        path = os.path.join(self.out_dir, f"{rid}.lens")
        t0 = time.perf_counter()
        if not self._result_cache.serve(
            fp, rid, log_config(t.request), path
        ):
            # the entry vanished under a peer's eviction or its donor
            # was torn: an honest miss, already forgotten by the cache
            self._metrics.inc("result_misses")
            return False
        now = time.perf_counter()
        pool = self.buckets[t.request.composite].pool
        t.result_path = path
        t.status = DONE
        t.steps_done = t.horizon_steps
        t.emit_count = t.horizon_steps // pool.emit_every
        # the replay IS the stream: finished and streamed the moment
        # the rename landed (admitted_at stays None — the timing row
        # and front-door status are None-tolerant for tickets that
        # never touched a lane)
        t.finished_at = now
        t.streamed_at = now
        t.mark_stage("served from result cache", self._ticks)
        self.tickets[rid] = t
        self._metrics.inc("submitted")
        self._metrics.inc("result_hits")
        self._metrics.inc(
            "device_seconds_saved",
            -(-(t.horizon_steps - t.steps_base) // pool.window_steps)
            * self._metrics.avg_window_seconds(),
        )
        self._metrics.tenant_inc(t.request.tenant, "admitted")
        self._metrics.observe_request(0.0, now - t.submitted_at)
        self.trace.emit_span(
            "result.replay", t0, now, track=REQUEST_TRACK,
            rid=rid, tick=self._ticks,
        )
        if self._wal is not None:
            # the full terminal fact set, so recovery materializes the
            # hit over its on-disk log instead of re-running it (the
            # spliced file landed — rename protocol — before any of
            # these events could)
            self._wal.append({
                "event": SUBMIT,
                "rid": rid,
                "request": _request_to_json(t.request),
            })
            self._wal.append({
                "event": RETIRE,
                "rid": rid,
                "status": DONE,
                "error": None,
                "steps": t.steps_done,
            })
            self._wal.append({"event": STREAMED, "rid": rid})
            self.faults.kill("submit.walled")
        return True

    def _try_coalesce(self, t: Ticket) -> bool:
        """Attach one submit as a FOLLOWER of an identical QUEUED
        request, if there is one: the follower never queues and never
        owns a lane — it rides the leader's per-lane stream with its
        own sink (round-18 suffix dedup). Attachment closes at the
        leader's admission (its tick also dispatches the first
        window); later duplicates run solo — or hit the durable cache
        once the leader's result lands. ``hold_state`` submits always
        run their own lane (their retirement pins a device
        snapshot)."""
        if t.request.hold_state or t.internal:
            return False
        leader = (
            self._dedup_leaders.get(t.fingerprint)
            if t.fingerprint is not None
            else None
        )
        if (
            leader is None
            or leader is t
            or leader.status != QUEUED
            or leader.cancel_requested
        ):
            return False
        self._attach_follower(t, leader)
        return True

    def _attach_follower(self, t: Ticket, leader: Ticket) -> None:
        t.leader = leader.request_id
        t.status = QUEUED
        self._dedup_groups.setdefault(
            leader.request_id, []
        ).append(t)
        t.mark_stage(
            f"coalesced onto {leader.request_id}", self._ticks
        )
        self._metrics.inc("suffix_coalesced")
        self.trace.instant(
            "dedup.coalesced", rid=t.request_id,
            leader=leader.request_id, tick=self._ticks,
        )

    def _resolve_group(
        self, leader: Ticket, followers: List[Ticket], status: str
    ) -> None:
        """Propagate a leader's terminal fact to its attached
        followers. DONE retires every follower DONE (their streams
        already carry the same records). FAILED — divergence, sink
        failure, admission error — fails them with the cause: their
        records rode the same poisoned lane. CANCELLED/TIMEOUT are the
        LEADER'S facts only (deadlines are excluded from the
        fingerprint, so followers may outlive their leader): each
        follower detaches and re-queues as an independent request —
        sink restarted, counters reset — re-coalescing among
        themselves so the group still costs one lane."""
        if status == DONE:
            pool = self.buckets[leader.request.composite].pool
            for f in followers:
                self._metrics.inc(
                    "device_seconds_saved",
                    -(-(f.horizon_steps - f.steps_base)
                      // pool.window_steps)
                    * self._metrics.avg_window_seconds(),
                )
                self._finish(f, DONE)
                self._metrics.inc("retired")
            return
        if status in (FAILED, MIGRATED):
            # MIGRATED is unreachable (withdraw refuses leaders with
            # followers) but fail-closed beats silently parking them
            cause = leader.error or f"leader {status}"
            for f in followers:
                f.error = (
                    f"coalesced leader {leader.request_id} "
                    f"{status}: {cause}"
                )
                self._finish(f, FAILED)
                self._metrics.inc("failed")
            return
        bucket = self.buckets[leader.request.composite]
        for f in followers:
            self._reset_follower(f, bucket)
            f.leader = None
            if f.cancel_requested:
                self._finish(f, CANCELLED)
                self._metrics.inc("cancelled")
                continue
            f.mark_stage(
                f"detached from {status} leader "
                f"{leader.request_id}", self._ticks,
            )
            self.trace.instant(
                "dedup.detached", rid=f.request_id,
                tick=self._ticks, leader=leader.request_id,
            )
            if self.dedup == "on" and self._try_coalesce(f):
                continue
            # force: these requests were already accepted once; the
            # client backpressure bound must not drop them now
            self.queue.push(f, retry_after=0.0, force=True)
            if self.dedup == "on" and f.fingerprint is not None:
                self._dedup_leaders[f.fingerprint] = f
            if f.prefix_key is not None:
                self._resolve_prefix(f, bucket)
        self._metrics.queue_depth = len(self.queue)

    def _reset_follower(self, f: Ticket, bucket: _Bucket) -> None:
        """Void a follower's progress so a re-run regenerates its
        complete stream (the displaced-ticket reset, minus the lane
        bookkeeping followers never had): restart the sink, rewind the
        step/emit counters, clear the stream marks and any parked sink
        failure of the dead incarnation."""
        sink = self._results.pop(f.request_id, None)
        if sink is not None:
            try:
                sink.close()
            except Exception:
                pass  # a torn sink must not abort the detach
        self._stream_done.pop(f.request_id, None)
        f.status = QUEUED
        f.shard = None
        f.admitted_at = None
        f.steps_done = f.steps_base
        f.emit_count = f.steps_base // bucket.pool.emit_every
        f.first_window_at = None
        f.streamed_at = None
        f.requeued_at = time.perf_counter()
        f.requeues += 1
        with self._sink_fail_lock:
            f.sink_closed = False
            self._sink_failures.pop(f.request_id, None)

    def _sweep_result_cache(self) -> None:
        """File completed results into the durable cache (scheduler
        thread; the stream thread only parks DONE tickets on the
        pending list). Runs AFTER the tick's quarantine sweep so a
        divergence detected with the usual one-window lag flips the
        ticket before it can be filed. Honest limit (docs/serving.md):
        a divergence only detectable after ``close()`` — the final
        window's flags with no further tick — can still file a
        poisoned entry; ``check_finite="window"`` servers that care
        should tick once past the last retirement."""
        if self._result_cache is None or not self._cache_pending:
            return
        pending, self._cache_pending = self._cache_pending, []
        for t in pending:
            if (
                t.status != DONE
                or t.diverged
                or t.sink_closed
                or t.internal
                or t.warm
                or t.fingerprint is None
                or t.result_path is None
                or t.fingerprint in self._result_cache
            ):
                continue
            t0 = time.perf_counter()
            if self._result_cache.put(
                t.fingerprint, t.result_path,
                request=_request_to_json(t.request),
            ):
                self.trace.emit_span(
                    "result.store", t0, time.perf_counter(),
                    track=SCHED_TRACK, rid=t.request_id,
                    tick=self._ticks,
                )
                self.faults.kill("result.cached")

    def _resolve_prefix(self, t: Ticket, bucket: _Bucket) -> None:
        """Route a prefix-declaring ticket through the snapshot store:
        hit -> pin the entry and fork at admission; miss with the same
        prefix already in flight -> attach as a coalesced waiter; cold
        miss -> launch ONE internal prefix ticket all later submitters
        coalesce onto. Runs after the ticket is queued (a QueueFull
        submit leaves no store/pending side effects)."""
        key = t.prefix_key
        if key in self.snapshots:
            self.snapshots.acquire(key)
            t.carry_key = key
            self._metrics.inc("prefix_hits")
            if self.snapshots.is_warmed(key):
                # the snapshot exists (or is device-resident) because
                # warming put it there ahead of this submit
                self._metrics.inc("warm_hits")
            self.trace.instant(
                "prefix.hit", rid=t.request_id, tick=self._ticks,
                tier=self.snapshots.tier_of(key),
            )
            return
        waiters = self._pending_prefix.get(key)
        if waiters is not None:
            waiters.append(t)
            t.waiting = True
            self._metrics.inc("prefix_coalesced")
            if key in self._warm_pending:
                # coalesced onto an in-flight WARM run: the prefix
                # compute this submit would have launched was already
                # speculatively in progress — and it is CLIENT work
                # from this moment. A still-queued warm ticket must
                # stop waiting for leftover lanes (under sustained
                # load there are none, and the fork would starve
                # behind later-submitted requests): promote it into
                # the client queue, where a plain miss's internal run
                # would have gone.
                self._metrics.inc("warm_hits")
                self._promote_warm_run(key, t.request.priority)
            self.trace.instant(
                "prefix.coalesced", rid=t.request_id, tick=self._ticks
            )
            return
        self._metrics.inc("prefix_misses")
        self.trace.instant(
            "prefix.miss", rid=t.request_id, tick=self._ticks
        )
        t.waiting = True
        req = t.request
        warm = ScenarioRequest(
            composite=req.composite,
            seed=int(req.seed),
            horizon=t.steps_done * bucket.pool.timestep,
            overrides=dict(req.prefix).get("overrides") or {},
            n_agents=req.n_agents,
            # an interactive fork's prefix run is on its latency path:
            # the internal ticket rides the fork's admission class
            # (tenant deliberately unset — internal work is unbilled)
            priority=req.priority,
        )
        warm_ticket = Ticket(
            request_id=self.queue.next_id(),
            request=warm,
            horizon_steps=t.steps_done,
            content_key=key,
            internal=True,
        )
        # force: a rejected prefix run would deadlock the fork already
        # queued behind it; internal tickets are bounded by the
        # distinct prefixes of admitted client tickets, not by clients
        self.queue.push(warm_ticket, retry_after=0.0, force=True)
        self.tickets[warm_ticket.request_id] = warm_ticket
        self._pending_prefix[key] = [t]

    def _resolve_waiters(self, key, state, shard: int = 0) -> None:
        """A prefix run landed: hand its state to every still-queued
        coalesced fork (they scatter the same device tree — admission
        copies it into each lane, the source is never donated).
        ``shard`` records where the tree lives so each fork prefers
        the owning device at admission."""
        for w in self._pending_prefix.pop(key, []):
            if w.status == QUEUED:
                w.carry_state = state
                w.carry_shard = shard
                w.waiting = False

    @staticmethod
    def _horizon_steps(bucket: _Bucket, horizon: float) -> int:
        """Validate a horizon against the bucket's step/emit grid and
        return it in steps (shared by ``submit`` and ``resubmit``)."""
        pool = bucket.pool
        steps = int(round(float(horizon) / pool.timestep))
        if steps < 1 or abs(
            steps * pool.timestep - float(horizon)
        ) > 1e-6 * max(abs(float(horizon)), 1.0):
            raise RequestValidationError(
                f"horizon={horizon} is not a positive multiple "
                f"of the bucket timestep {pool.timestep}",
                path="horizon",
            )
        if steps % pool.emit_every != 0:
            raise RequestValidationError(
                f"horizon steps ({steps}) must be a multiple of the "
                f"bucket emit_every ({pool.emit_every})",
                path="horizon",
            )
        return steps

    def resubmit(self, request_id: str, extra_horizon: float) -> str:
        """EXTEND a DONE ``hold_state`` request by ``extra_horizon`` sim
        seconds: queue a continuation ticket that is admitted from the
        parent's held final state instead of a fresh seed-built one.

        The continuation's emitted rows carry times following straight
        on from the parent's, and the combined trajectory is bitwise
        identical to one original request with the longer horizon (the
        held state is the lane's exact bits; ``tests/test_serve.py``
        pins it). Returns the continuation's request id — a NEW id:
        the parent stays DONE with its own streamed records, so result
        consumers stitch segments by ``parent`` linkage (the sweep
        driver does).

        The held state lives in the server's refcounted snapshot store
        and is NOT consumed: a parent can be extended/forked any number
        of times (N branching continuations from one hold), until the
        client drops the hold with ``release_state``. A rejected
        (``QueueFull``) resubmit leaves the hold untouched and the
        parent re-extendable.

        Raises ``ValueError`` if the parent is not DONE, was not
        submitted with ``hold_state=True``, or its hold was already
        dropped by ``release_state``; ``QueueFull`` for backpressure,
        like ``submit``.
        """
        parent = self._ticket(request_id)
        if parent.status != DONE:
            raise ValueError(
                f"request {request_id} is {parent.status}; only DONE "
                f"requests can be extended"
            )
        if parent.held_key is None:
            raise ValueError(
                f"request {request_id} holds no final state (submit "
                f"with hold_state=True; release_state drops the hold)"
            )
        bucket = self.buckets[parent.request.composite]
        extra_steps = self._horizon_steps(bucket, extra_horizon)
        request = dc_replace(
            parent.request,
            horizon=float(parent.request.horizon) + float(extra_horizon),
        )
        total_steps = parent.horizon_steps + extra_steps
        ticket = Ticket(
            request_id=self.queue.next_id(),
            request=request,
            horizon_steps=total_steps,
            steps_done=parent.steps_done,
            steps_base=parent.steps_done,
            emit_count=parent.emit_count,
            # a pure parent's continuation is pure at the longer
            # horizon: same address, step coordinate advanced
            content_key=(
                parent.content_key[:-1] + (total_steps,)
                if parent.content_key is not None
                else None
            ),
            parent=parent.request_id,
        )
        try:
            self.queue.push(ticket, retry_after=self._retry_after())
        except QueueFull:
            self._metrics.inc("rejected")
            self._metrics.tenant_inc(request.tenant, "rejected")
            self._metrics.queue_depth = len(self.queue)
            raise
        self._metrics.tenant_inc(request.tenant, "admitted")
        # pin the held snapshot for the continuation only once the push
        # can no longer fail — QueueFull must leave no dangling ref
        ticket.carry_key = parent.held_key
        self.snapshots.acquire(parent.held_key)
        self._metrics.inc("resubmitted")
        self._metrics.queue_depth = len(self.queue)
        ticket.mark_stage("queued", self._ticks)
        self.tickets[ticket.request_id] = ticket
        if self._wal is not None:
            self._wal.append({
                "event": RESUBMIT,
                "rid": ticket.request_id,
                "parent": parent.request_id,
                "extra_horizon": float(extra_horizon),
            })
            self.faults.kill("resubmit.walled")
        return ticket.request_id

    def release_state(self, request_id: str) -> None:
        """Drop a DONE request's hold on its final state (a halving
        loser that will never be extended): further ``resubmit`` calls
        are refused. A content-addressed hold becomes ordinary
        evictable cache content (it can still serve prefix hits) —
        memory is reclaimed by the store's budget/LRU (or at close). A
        per-request hold (impure parent) is unreachable by any future
        lookup, so it is dropped — and its memory freed — immediately.
        In-flight continuations keep their own pins."""
        t = self._ticket(request_id)
        if t.held_key is None:
            return
        key, t.held_key = t.held_key, None
        self._metrics.inc(
            "snapshot_evictions", self.snapshots.release(key)
        )
        if (
            len(key) == 2  # ("held", rid): never content-addressable
            and key in self.snapshots
            and self.snapshots.refs(key) == 0
        ):
            self.snapshots.drop(key)
        if self._wal is not None:
            # the spill directory is deliberately KEPT: an in-flight
            # continuation admitted before this release may still need
            # rehydration after a crash; stale spills are bounded by
            # held requests and reclaimed with the recover_dir
            self._wal.append({"event": RELEASE, "rid": request_id})

    def prewarm(
        self,
        spec: Optional[Mapping[str, Any]] = None,
        **kw: Any,
    ) -> Optional[str]:
        """Speculatively warm one scenario prefix (docs/serving.md,
        "Tiered snapshots & speculative warming"): callers that know
        future traffic — the sweep driver's warmup block, the front
        door's repeated request shapes, a CLI request list — hand the
        prefix here as ``{composite, seed, horizon, overrides,
        n_agents}`` (mapping or kwargs), where ``horizon`` is the
        PREFIX length, and the server makes it device-resident ahead
        of demand without ever delaying admitted work:

        - already device-resident: no-op (returns None);
        - resident on a lower tier: promoted now and tagged warmed —
          the prefetch half of warming;
        - absent: an internal WARM ticket is queued OUTSIDE the
          bounded client queue, admitted only into lanes no admissible
          client ticket wants, and PREEMPTED (exact progress captured
          on-device, resumed later) the moment clients outnumber free
          lanes. Client prefix submits that arrive meanwhile coalesce
          onto the warm run exactly like any in-flight prefix.
          Returns the warm ticket's id.

        Warming changes WORK PLACEMENT only, never bits: a warmed
        snapshot is the same content-addressed entry a client miss
        would have computed (co-batching is bitwise-invariant, pinned
        in tests/test_tiers.py). Scheduler-thread discipline applies —
        call from the thread that drives ``tick()``.

        Raises ``ValueError`` for an unknown composite or a malformed
        prefix spec (off-grid horizon, bad override paths), exactly
        like ``submit`` would for the equivalent ``prefix`` block.
        """
        merged = {**(dict(spec) if spec else {}), **kw}
        unknown = set(merged) - {
            "composite", "seed", "horizon", "overrides", "n_agents",
        }
        if unknown:
            raise ValueError(
                f"unknown prewarm keys {sorted(unknown)}; known: "
                f"composite, seed, horizon, overrides, n_agents"
            )
        missing = {"composite", "horizon"} - set(merged)
        if missing:
            raise ValueError(
                f"prewarm needs {sorted(missing)} (got "
                f"{sorted(merged)})"
            )
        req = ScenarioRequest(
            composite=merged["composite"],
            seed=int(merged.get("seed", 0)),
            horizon=float(merged["horizon"]),
            overrides=merged.get("overrides") or {},
            n_agents=merged.get("n_agents"),
        )
        bucket = self.buckets.get(req.composite)
        if bucket is None:
            raise RequestValidationError(
                f"no bucket serves composite {req.composite!r}; "
                f"configured: {sorted(self.buckets)}",
                path="composite",
            )
        if not bucket.active_shards():
            return None  # advisory: a dead bucket just skips warming
        steps = self._horizon_steps(bucket, req.horizon)
        bucket.pool.validate_overrides(req.overrides, what="override")
        agents = self._request_agents(bucket, req)
        bucket.pool.validate_agents(agents)
        key = snapshot_key(
            req.composite, int(req.seed), agents, req.overrides, steps
        )
        if key in self.snapshots:
            if self.snapshots.tier_of(key) != DEVICE:
                # the prefetch half: promote the demoted entry back to
                # the device tier during idle, onto the emptiest shard
                shard = bucket.place()
                try:
                    self.snapshots.fetch(
                        key, shard=shard.index, device=shard.device
                    )
                except OSError:
                    # warming is ADVISORY: a torn/missing spill under
                    # a long-lived tier dir must not take the caller
                    # down (the front door's scheduler thread, the
                    # serve CLI startup). Forget the unpromotable
                    # entry when nothing pins it, so later submits
                    # MISS and recompute instead of tripping on it.
                    if self.snapshots.refs(key) == 0:
                        self.snapshots.drop(key)
                    self.trace.instant(
                        "warm.prefetch_failed", tick=self._ticks,
                    )
                    return None
                self.snapshots.mark_warmed(key)
                self.trace.instant(
                    "warm.promoted", tick=self._ticks,
                    shard=shard.index,
                )
            return None
        if key in self._pending_prefix:
            return None  # already being computed (warm or client run)
        ticket = Ticket(
            request_id=self.queue.next_id(),
            request=req,
            horizon_steps=steps,
            content_key=key,
            internal=True,
            warm=True,
        )
        self.tickets[ticket.request_id] = ticket
        self._warm_queue.append(ticket)
        self._pending_prefix[key] = []
        self._warm_pending.add(key)
        self._metrics.inc("warm_submitted")
        self.trace.instant(
            "warm.launch", rid=ticket.request_id, tick=self._ticks,
        )
        return ticket.request_id

    def status(self, request_id: str) -> Dict[str, Any]:
        t = self._ticket(request_id)
        return {
            "request_id": t.request_id,
            "status": t.status,
            "steps_done": t.steps_done,
            "horizon_steps": t.horizon_steps,
            "error": t.error,
            "result_path": t.result_path,
            "parent": t.parent,
            "server": self._gauges(),
        }

    def metrics(self) -> Dict[str, Any]:
        """A LIVE metrics snapshot: counters plus gauges recomputed at
        call time (queue depth, busy lanes, retraces), so any caller —
        the sweep driver pacing its submissions, an operator poking a
        resident server — reads current health without waiting for the
        next tick or for ``server_meta.json`` at close."""
        self._refresh_gauges()
        return self._metrics.snapshot()

    def prometheus_metrics(self) -> str:
        """The Prometheus text exposition format for this server's
        live instruments — the pull-style scrape surface
        (docs/observability.md): gauges recompute at call exactly like
        :meth:`metrics`, counters are the monotonic lifetime values,
        histograms export summary quantiles. No HTTP server is bundled
        — an operator embeds this behind whatever endpoint their
        deployment already has (the front door of ROADMAP item 5)."""
        self._refresh_gauges()
        return self._metrics.prometheus_text()

    def _gauges(self) -> Dict[str, Any]:
        """The small live-health dict embedded in ``status()``."""
        self._refresh_gauges()
        c = self._metrics.counters
        return {
            "occupancy": self._metrics.occupancy(),
            "queue_depth": self._metrics.queue_depth,
            "lanes_busy": self._metrics.lanes_busy,
            "lanes_total": self._metrics.lanes_total,
            "retraces": self._metrics.retraces,
            "quarantined_devices": self._metrics.quarantined_devices,
            "shards": [dict(s) for s in self._metrics.shards],
            "snapshots": {
                "resident": self._metrics.snapshots_resident,
                "resident_bytes": self._metrics.snapshot_bytes,
                "hits": c["prefix_hits"],
                "misses": c["prefix_misses"],
                "coalesced": c["prefix_coalesced"],
                "forks": c["prefix_forks"],
                "evictions": c["snapshot_evictions"],
                "rejected": c["snapshot_rejected"],
                "tiers": {
                    t: dict(row)
                    for t, row in self._metrics.snapshot_tiers.items()
                },
                "warm": {
                    "submitted": c["warm_submitted"],
                    "completed": c["warm_completed"],
                    "hits": c["warm_hits"],
                    "preempted": c["warm_preempted"],
                },
            },
            "tenants": self._metrics.tenants,
            **(
                {
                    "results": {
                        "entries": self._metrics.result_entries,
                        "bytes": self._metrics.result_bytes,
                        "hits": c["result_hits"],
                        "misses": c["result_misses"],
                        "coalesced": c["suffix_coalesced"],
                        "evictions": c["result_evictions"],
                        "device_seconds_saved": (
                            c["device_seconds_saved"]
                        ),
                    }
                }
                if self._result_cache is not None
                or self.dedup == "on"
                else {}
            ),
        }

    def reset_samples(self) -> None:
        """Drop accumulated latency/wait/window samples (counters stay).
        Benchmark hygiene: called after a warmup round so compile-time
        outliers never dilute the measured percentiles. The buffers
        clear atomically (each under its lock — see
        ``ServerMetrics.reset_samples``), so a stream-thread
        observation racing this call can never be read half-cleared."""
        if self._streamer is not None:
            self._streamer.drain()  # in-flight windows would re-sample
        self._metrics.reset_samples()

    def _refresh_gauges(self) -> None:
        self._metrics.queue_depth = len(self.queue)
        self._metrics.lanes_busy = sum(
            b.busy() for b in self.buckets.values()
        )
        self._metrics.lanes_total = sum(
            b.lanes_total() for b in self.buckets.values()
        )
        self._metrics.retraces = sum(
            s.pool.retraces()
            for b in self.buckets.values()
            for s in b.shards
        )
        self._metrics.snapshots_resident = len(self.snapshots)
        self._metrics.snapshot_bytes = self.snapshots.resident_bytes()
        stats = self.snapshots.tier_stats()
        if getattr(self.snapshots, "tiers_armed", False):
            # tier rows only when paging is in play: a flat-store (or
            # plain-recover_dir) server must not grow zero-valued
            # host/disk gauges in every scrape and time-series point
            self._metrics.snapshot_tiers = stats["tiers"]
        if stats["rejected"] > self._rejected_seen:
            # delta-sync the store's rejection count into the
            # monotonic registry counter (the store is scheduler-
            # thread-only; the registry is the export surface)
            self._metrics.inc(
                "snapshot_rejected",
                stats["rejected"] - self._rejected_seen,
            )
            self._rejected_seen = stats["rejected"]
        self._metrics.quarantined_devices = len(self._quarantined)
        if self._result_cache is not None:
            self._metrics.result_entries = len(self._result_cache)
            self._metrics.result_bytes = (
                self._result_cache.total_bytes()
            )
            evicted = self._result_cache.evictions
            if evicted > self._result_evictions_seen:
                # delta-sync like snapshot_rejected above: the cache
                # object counts, the registry counter exports
                self._metrics.inc(
                    "result_evictions",
                    evicted - self._result_evictions_seen,
                )
                self._result_evictions_seen = evicted
        self._metrics.shards = self._shard_gauges()

    def _shard_gauges(self) -> List[Dict[str, Any]]:
        """One gauge dict per device shard (summed across buckets) —
        the mesh observability surface in ``metrics()``/``status()``/
        ``server_meta.json`` and the ``bench_serve --mesh`` columns."""
        out: List[Dict[str, Any]] = []
        for k, dev in enumerate(self.devices):
            shards = [b.shards[k] for b in self.buckets.values()]
            busy_acc = sum(s.lane_windows_busy for s in shards)
            total_acc = sum(s.lane_windows_total for s in shards)
            out.append({
                "shard": k,
                "device": "default" if dev is None else str(dev),
                "quarantined": k in self._quarantined,
                "lanes_busy": sum(
                    len(s.assignments) for s in shards
                ),
                "lanes_total": sum(s.pool.n_lanes for s in shards),
                "occupancy": (
                    busy_acc / total_acc if total_acc else None
                ),
                "windows": sum(s.windows for s in shards),
                "diverged": sum(s.diverged for s in shards),
                "snapshots_resident": len(
                    self.snapshots.keys_on_shard(k)
                ),
                "snapshot_bytes": self.snapshots.resident_bytes(
                    shard=k
                ),
            })
        return out

    def result(self, request_id: str):
        """The request's streamed trajectory: a stacked timeseries tree
        (ram sink) or the path of its ``.lens`` log (log sink). Partial
        for TIMEOUT/CANCELLED requests — whatever was streamed before
        retirement.

        With the pipeline on, a terminal status can precede the last
        window's sink appends (bookkeeping runs ahead of streaming), so
        this waits for THIS request's stream completion first (its
        per-request event, set by the stream thread after the final
        append + close) — other requests' windows keep pipelining,
        which matters to the sweep driver polling results mid-flight.
        A non-terminal (running) request falls back to a full drain
        barrier before returning its partial records.
        """
        self._sweep_sink_failures()
        t = self._ticket(request_id)
        if t.diverged:
            # quarantined physics: never hand back the (post-divergence
            # garbage) records as if they were a completed trajectory
            raise SimulationDiverged(t.error)
        sink = self._results.get(request_id)
        if sink is None:
            if t.result_path is not None and t.status in (
                DONE, TIMEOUT, CANCELLED
            ):
                # a WAL-recovered terminal request: its records live in
                # the result log the previous incarnation wrote (the
                # log-sink result form is the path either way)
                return t.result_path
            cause = f": {t.error}" if t.error else ""
            raise ValueError(
                f"request {request_id} ({t.status}) has no result — it "
                f"was never admitted to a lane{cause}"
            )
        if self._streamer is not None:
            event = self._stream_done.get(request_id)
            if event is not None and t.status in (
                DONE, TIMEOUT, CANCELLED, FAILED
            ):
                waited = 0.0
                token = self._streamer.progress_token()
                while not event.wait(0.05):
                    # surface a parked stream error instead of
                    # waiting forever on an event it will never set
                    self._streamer.check()
                    waited += 0.05
                    if (
                        self.watchdog_s is not None
                        and waited > self.watchdog_s
                    ):
                        # no-progress semantics, like Streamer.drain:
                        # a slow-but-moving pipe resets the clock, a
                        # stuck one raises
                        now_token = self._streamer.progress_token()
                        if now_token == token:
                            raise WatchdogTimeout(
                                f"result({request_id}) made no "
                                f"stream progress for "
                                f"{self.watchdog_s}s waiting for its "
                                f"completion; {t.stage_note()}"
                            )
                        token = now_token
                        waited = 0.0
            else:
                self._streamer.drain()
        return sink.timeseries()

    def cancel(self, request_id: str) -> str:
        """Cancel a request: queued -> dropped now; running -> its lane
        is reclaimed at the next tick (already-streamed records are
        kept). Returns the resulting status."""
        t = self._ticket(request_id)
        if t.leader is not None and t.status in (QUEUED, RUNNING):
            # a coalesced follower never owns a queue slot or a lane —
            # the scheduler's follower sweep detaches its sink from
            # the leader's stream without touching the shared lane
            t.cancel_requested = True
            return t.status
        if t.status == QUEUED and self.queue.drop(t):
            self._finish(t, CANCELLED)
            self._metrics.inc("cancelled")
            self._metrics.queue_depth = len(self.queue)
        elif t.status == RUNNING:
            t.cancel_requested = True
        return t.status

    def _ticket(self, request_id: str) -> Ticket:
        t = self.tickets.get(request_id)
        if t is None:
            raise KeyError(f"unknown request id {request_id!r}")
        return t

    def withdraw(self, request_id: str) -> Dict[str, Any]:
        """Remove a QUEUED request from this server and hand back its
        exact submit-time mapping — the work-stealing egress
        (docs/serving.md, "Cluster serving": the router migrates
        queued work from a backed-up host's FIFO to an idle one).

        Only plain queued client requests are eligible. Running or
        terminal work, internal prefix/warm runs, coalesced forks
        still waiting on an in-flight prefix, forks already seeded
        with a device-resident tree, and resubmit continuations
        (their held snapshot lives here) refuse with a descriptive
        ``ValueError`` — the router skips them and steals the next
        candidate. A fork that merely PINNED a cached snapshot at
        submit migrates fine: its pin is released here and the prefix
        re-resolves wherever it lands (recompute, or a shared-tier
        disk hit).

        The withdrawn request retires ``MIGRATED`` locally and the
        retirement is WAL'd, so this host's own recovery (and a
        whole-host failover over this host's WAL) never re-runs it —
        it lives on under its original id wherever the router
        resubmits it.
        """
        t = self._ticket(request_id)
        if t.internal or t.warm:
            raise ValueError(
                f"request {request_id} is a server-internal run; "
                f"internal work is never stolen"
            )
        if t.status != QUEUED:
            raise ValueError(
                f"request {request_id} is {t.status}, not queued; "
                f"only queued requests migrate"
            )
        if t.waiting:
            raise ValueError(
                f"request {request_id} is coalesced onto an in-flight "
                f"prefix run here; it migrates only before or after "
                f"the prefix resolves"
            )
        if t.leader is not None:
            raise ValueError(
                f"request {request_id} rides leader {t.leader}'s lane "
                f"on this host (suffix dedup); followers do not "
                f"migrate"
            )
        if self._dedup_groups.get(request_id):
            raise ValueError(
                f"request {request_id} leads a coalesced group here; "
                f"its followers' streams fan out from this host"
            )
        if t.carry_state is not None:
            raise ValueError(
                f"request {request_id} already holds a device-resident "
                f"seed on this host; not stealable"
            )
        if t.parent is not None:
            raise ValueError(
                f"request {request_id} continues {t.parent}, whose "
                f"held snapshot lives on this host; continuations "
                f"do not migrate"
            )
        if not self.queue.drop(t):
            raise ValueError(
                f"request {request_id} left the queue mid-steal"
            )
        payload = _request_to_json(t.request)
        self._finish(t, MIGRATED)
        self._metrics.inc("stolen")
        self._metrics.queue_depth = len(self.queue)
        self.trace.instant(
            "cluster.withdrawn", rid=request_id, tick=self._ticks
        )
        return payload

    def adopt_displaced(
        self,
        events: List[Mapping[str, Any]],
        rids: List[str],
    ) -> List[str]:
        """Adopt requests DISPLACED from another host: re-queue each
        rid in ``rids`` under its original id, reconstructed from the
        dead host's merged WAL ``events`` — whole-host failover's
        ingress (docs/serving.md, "Cluster serving"), the per-host
        generalization of device-quarantine requeues. Continuations
        re-arm from their parent's spilled snapshot, which both hosts
        reach through the shared tier directory.

        The adopted rids' event closure (submit/resubmit chain, hold
        spills, the parents' terminal facts) is COPIED into this
        host's own WAL first, so a later crash here recovers them like
        native work; the determinism contract makes the re-run a
        bitwise resume either way. Returns the adopted rids."""
        order, recs, retired, streamed, holds, released = (
            classify_events(events)
        )
        adopted: List[str] = []
        walled: set = set()
        for rid in order:
            if rid not in rids:
                continue
            if rid in self.tickets:
                raise ValueError(
                    f"request {rid} already lives on this host; "
                    f"refusing a duplicate adoption"
                )
            # the rid's ancestry, oldest first: a continuation's
            # parent chain must be on this WAL before the resubmit
            # event that references it
            chain: List[str] = []
            walk: Optional[str] = rid
            while walk is not None:
                if walk not in recs:
                    raise ValueError(
                        f"request {rid}: the displaced WAL has no "
                        f"submit record for ancestor {walk!r}; "
                        f"cannot reconstruct the request"
                    )
                chain.append(walk)
                walk = recs[walk].get("parent")
            fin = retired.get(rid)
            finished = fin is not None and not (
                fin.get("status") == DONE and rid not in streamed
            )
            if self._wal is not None:
                for member in reversed(chain):
                    if member in walled:
                        continue
                    walled.add(member)
                    self._wal.append(_strip_seq(recs[member]))
                    # terminal facts ride along for ancestors always,
                    # and for the rid itself when the WAL attests it
                    # finished (then we materialize, not re-run)
                    if (member != rid or finished) \
                            and member in retired:
                        self._wal.append(_strip_seq(retired[member]))
                        if member in streamed:
                            self._wal.append(
                                {"event": STREAMED, "rid": member}
                            )
                    if member in holds:
                        self._wal.append(_strip_seq(holds[member]))
                    if member in released:
                        self._wal.append(
                            {"event": RELEASE, "rid": member}
                        )
            if finished:
                # a finished request adopts as a TERMINAL ticket over
                # its existing (shared-filesystem) result log; a live
                # hold re-pins from its spill in the shared tier, so
                # resubmit chains survive their host's death without
                # re-running the parent
                self._materialize(rid, recs, fin, holds, released)
            else:
                self._requeue(rid, recs, holds)
            self._metrics.inc("adopted")
            adopted.append(rid)
            self.trace.instant(
                "cluster.adopted", rid=rid, tick=self._ticks,
                finished=finished,
            )
        missing = [r for r in rids if r not in adopted]
        if missing:
            raise ValueError(
                f"displaced WAL has no submit records for {missing}"
            )
        return adopted

    # -- scheduling ----------------------------------------------------------

    def tick(self) -> bool:
        """One scheduler iteration: expire/cancel, admit, run one window
        per occupied bucket, stream, retire. Returns False when the
        server is fully idle (nothing queued, no lane busy).

        With the pipeline on, "stream" means HAND OFF: the tick
        dispatches the window, does retire/admit bookkeeping from host
        mirrors, and enqueues the (already async-copying) trajectory on
        the background streamer — so the next tick dispatches window
        k+1 while window k's host work runs off-thread. A stream-thread
        failure from an earlier tick is raised here, at the top.
        """
        if self._streamer is not None:
            self._streamer.check()
        # sink_errors="request": retire requests whose sink failed
        # since the last tick (one-window lag, like the finite check)
        self._sweep_sink_failures()
        if self._wal is not None:
            # group commit: every WAL append since the last tick is
            # durable before the scheduler acts on any of it (one
            # fsync per tick, not per event — appends were already
            # flushed to the OS, so a SIGKILL loses nothing either way)
            if self.trace:
                with self.trace.span("wal.sync", tick=self._ticks):
                    self._wal.sync()
            else:
                self._wal.sync()
        now = time.perf_counter()
        self._metrics.inc("ticks")
        self._ticks += 1
        did_work = False

        # wall-clock metrics sampling (metrics_interval_s): one
        # time-series point into the metrics.jsonl ring. Sampled at
        # tick granularity — an idle server stops sampling too, which
        # is the honest shape (nothing changed).
        if self._metrics_ring is not None and now >= self._next_sample:
            self._next_sample = now + (self.metrics_interval_s or 0.0)
            self._refresh_gauges()
            self._metrics_ring.append(self._metrics.sample_point())

        # 0a. device watchdog: a shard whose dispatched window never
        #     completed within device_watchdog_s is declared dead and
        #     quarantined BEFORE this tick schedules anything onto it
        if self.device_watchdog_s is not None:
            self._check_device_watchdog(now)

        # 0b. lane quarantine sweep (check_finite="window"): consume
        #     the previous window's per-lane finite flags BEFORE
        #     admission, so a poisoned lane is reclaimed (and
        #     reusable) this tick and never dispatches another window
        if self.check_finite == "window":
            for bucket in self.buckets.values():
                for shard in bucket.shards:
                    self._sweep_quarantine(bucket, shard)

        # 1. queued-side expiry (cancel of queued tickets is immediate
        #    in cancel(); only deadlines need the sweep)
        for t in self.queue.expire(now):
            self._finish(t, TIMEOUT)
            self._metrics.inc("timeouts")

        # 1b. a bucket whose every device is quarantined can never
        #     admit again — fail its queued work with the cause now
        #     instead of parking it forever (run_until_idle would
        #     otherwise spin on a queue nothing can drain)
        dead = {
            name for name, b in self.buckets.items()
            if not b.active_shards()
        }
        if dead:
            for t in list(self.queue):
                if t.request.composite in dead and self.queue.drop(t):
                    t.error = (
                        f"every device serving bucket "
                        f"{t.request.composite!r} is quarantined"
                    )
                    self._finish(t, FAILED)
                    self._metrics.inc("failed")

        # 2. running-side cancel/expiry: reclaim lanes BEFORE admission
        #    so freed lanes are reusable this very tick
        for bucket in self.buckets.values():
            for shard in bucket.shards:
                for lane, t in list(shard.assignments.items()):
                    if t.cancel_requested or t.expired(now):
                        shard.pool.release(lane)
                        del shard.assignments[lane]
                        if t.cancel_requested:
                            self._finish(t, CANCELLED)
                            self._metrics.inc("cancelled")
                        else:
                            self._finish(t, TIMEOUT)
                            self._metrics.inc("timeouts")
                        did_work = True

        # 2b. warm preemption: a lane running a SPECULATIVE prefix
        #     must never make an admissible client ticket wait — if
        #     clients outnumber free lanes, preempt warm lanes (exact
        #     progress captured on-device, the run resumes later in an
        #     idle lane) before admission runs. Warm runs that real
        #     forks have coalesced onto are client work now and are
        #     never preempted. Gated on _warm_pending (a warm run in a
        #     lane always has its key there), so a server that never
        #     warms pays one empty-set check per tick, not a lane scan.
        if self._warm_pending:
            did_work |= self._preempt_warm_lanes()

        # 2c. coalesced followers: a follower's cancel/deadline
        #     DETACHES it from its group — its own sink closes (in
        #     stream order, keeping partial records), the leader's
        #     lane runs on untouched. Followers live outside the
        #     queue and the lane map, so neither sweep above sees
        #     them.
        if self._dedup_groups:
            for leader_rid, group in list(self._dedup_groups.items()):
                for f in list(group):
                    if not (f.cancel_requested or f.expired(now)):
                        continue
                    group.remove(f)
                    status = (
                        CANCELLED if f.cancel_requested else TIMEOUT
                    )
                    self._finish(f, status)
                    self._metrics.inc(
                        "cancelled" if status == CANCELLED
                        else "timeouts"
                    )
                    self.trace.instant(
                        "dedup.detached", rid=f.request_id,
                        tick=self._ticks, leader=leader_rid,
                        status=status,
                    )
                    did_work = True
                if not group:
                    self._dedup_groups.pop(leader_rid, None)

        # 2d. file freshly-completed results into the durable cache
        #     (after the 0b quarantine sweep above, so a divergence
        #     caught with its one-window lag flips the ticket first)
        self._sweep_result_cache()

        # 3. admission: FIFO over the queue, per-bucket free lanes;
        #    forks waiting on an in-flight prefix are skipped in place
        free = {
            name: b.free_lanes() for name, b in self.buckets.items()
        }
        for t in self.queue.take(
            lambda t: t.request.composite, free,
            ready=lambda t: not t.waiting,
        ):
            did_work = True
            self._admit(t, now)
        self._metrics.queue_depth = len(self.queue)

        # 3b. speculative warming scavenges what is left: warm tickets
        #     admit only into lanes the client admission pass above
        #     left free (a free lane here means no admissible client
        #     ticket wanted it this tick)
        if self._warm_queue:
            for t in list(self._warm_queue):
                bucket = self.buckets[t.request.composite]
                if bucket.free_lanes() > 0:
                    self._warm_queue.remove(t)
                    self._admit(t, now)
                    did_work = True

        # 4. one window per (bucket, shard) with any occupied lane —
        #    each shard is its own device program, so the dispatches
        #    queue independently per device and run concurrently.
        #    The FaultPlan's device_down seam fires per dispatch
        #    attempt: a declared-dead device is quarantined INSTEAD of
        #    dispatching, its work failing over to the survivors.
        for bucket in self.buckets.values():
            for shard in bucket.shards:
                if shard.quarantined or not shard.assignments:
                    continue
                if self.faults and self.faults.device_down(shard.index):
                    self.quarantine_device(
                        shard.index,
                        reason="FaultPlan device_down declaration",
                    )
                    did_work = True
                    continue
                did_work = True
                self._run_shard_window(bucket, shard)

        self._metrics.lanes_busy = sum(
            b.busy() for b in self.buckets.values()
        )
        self._metrics.retraces = sum(
            s.pool.retraces()
            for b in self.buckets.values()
            for s in b.shards
        )
        # completed results parked for the durable cache count as
        # work-in-flight: the stream thread can land one during this
        # tick's drain, and reporting idle before the 2d sweep files
        # it would let run_until_idle return with publication pending
        # (a repeat submit right after idle would then race a miss)
        return did_work or bool(self._cache_pending)

    def run_until_idle(self, max_ticks: Optional[int] = None) -> int:
        """Drive ``tick`` until nothing is queued or running (the
        in-process serving loop for tests/bench/CLI). Returns ticks
        run. ``max_ticks`` guards against a scheduling bug looping
        forever — exceeding it raises."""
        ticks = 0
        while True:
            busy = self.tick()
            ticks += 1
            if not busy and not len(self.queue):
                # idle = every result fully streamed, not just every
                # window dispatched: drain the pipeline before
                # reporting idle (also surfaces stream errors here)
                if self._streamer is not None:
                    self._streamer.drain()
                if self._sink_failures or self._cache_pending:
                    # a scoped sink failure or a cache-bound result
                    # landed during the final drain: tick once more
                    # so it retires FAILED / files into the result
                    # cache before this reports idle
                    continue
                return ticks
            if max_ticks is not None and ticks >= max_ticks:
                raise RuntimeError(
                    f"server not idle after {ticks} ticks "
                    f"(queue={len(self.queue)}, "
                    f"busy={self._metrics.lanes_busy})"
                )

    # -- internals -----------------------------------------------------------

    def _retry_after(self) -> float:
        """Backpressure hint: an HONEST estimate of when a retried
        submit could land, derived from the actual occupancy — (a)
        windows until the EARLIEST busy lane frees (the host-mirrored
        remaining counters, zero if any lane is free now) plus (b) the
        queued backlog's own remaining windows spread across every
        lane — quoted at the measured window rate. Still a pacing
        signal, not a promise (retirement order depends on horizons
        admitted later), but it scales with the real backlog instead
        of just the queue LENGTH: ten queued 4000-step requests now
        hint a proportionally longer wait than ten 37-step ones.

        Mesh honesty: the math counts only NON-QUARANTINED shards'
        lanes — a half-dead mesh must not advertise capacity it
        cannot schedule (the hint would undershoot forever)."""
        total_lanes = sum(
            b.lanes_total() for b in self.buckets.values()
        )
        to_free = 0.0
        if not any(b.free_lanes() > 0 for b in self.buckets.values()):
            to_free = min(
                (
                    -(-int(s.pool.remaining_host[lane])
                      // s.pool.window_steps)
                    for b in self.buckets.values()
                    for s in b.active_shards()
                    for lane in s.assignments
                ),
                default=0.0,
            )
        queued_windows = sum(
            -(-(t.horizon_steps - t.steps_done)
              // self.buckets[t.request.composite].pool.window_steps)
            for t in self.queue
        )
        backlog = to_free + queued_windows / max(total_lanes, 1)
        return max(backlog, 1.0) * self._metrics.avg_window_seconds()

    def _admit(self, t: Ticket, now: float) -> None:
        bucket = self.buckets[t.request.composite]
        # placement: a ticket scattering a cached snapshot prefers the
        # shard whose device already holds it (the scatter stays
        # device-local); everything else balances onto the emptiest
        # active shard
        prefer = None
        if t.carry_key is not None:
            prefer = self.snapshots.shard_of(t.carry_key)
        elif t.carry_state is not None:
            prefer = t.carry_shard
        shard = bucket.place(prefer)
        lane = shard.next_free_lane()
        # a continuation/fork ticket arms only its REMAINING steps (its
        # steps_done already counts the parent's run / the shared
        # prefix); fresh tickets have steps_done == 0 so this is their
        # full horizon
        arm_steps = t.horizon_steps - t.steps_done
        # a fork applies its divergent overrides AT the fork point (a
        # resubmit continuation does not: its request overrides were
        # the chain root's t=0 initial conditions, long since evolved)
        fork_overrides = (
            (t.request.overrides or None)
            if t.prefix_key is not None
            else None
        )
        if self.trace:
            # the request's queue wait as an async span (they overlap
            # freely across requests), closing the moment a lane is
            # chosen; the scatter itself is timed below. A re-admission
            # after device failover waits from its REQUEUE (the time
            # before that was spent running on the dead device) and
            # gets its own async id, so the attempts render as
            # separate bars instead of bogus nesting.
            wait_t0 = (
                t.requeued_at if t.requeued_at is not None
                else t.submitted_at
            )
            aid = (
                t.request_id if not t.requeues
                else f"{t.request_id}#r{t.requeues}"
            )
            self.trace.emit_span(
                "queue.wait", wait_t0, now,
                track=REQUEST_TRACK, aid=aid,
                rid=t.request_id, tick=self._ticks,
                internal=t.internal,
            )
            admit_t0 = time.perf_counter()
        try:
            if t.carry_key is not None:
                # fetch, not state: a host/disk-resident snapshot
                # promotes onto THIS shard's device here — the paging
                # moment (device_put / restore_tree), counted per-tier
                # by the store
                shard.pool.admit_state(
                    lane,
                    self.snapshots.fetch(
                        t.carry_key, shard=shard.index,
                        device=shard.device,
                    ),
                    arm_steps,
                    overrides=fork_overrides,
                )
                self._metrics.inc(
                    "snapshot_evictions",
                    self.snapshots.release(t.carry_key),
                )
                t.carry_key = None
            elif t.carry_state is not None:
                shard.pool.admit_state(
                    lane, t.carry_state, arm_steps,
                    overrides=fork_overrides,
                )
                t.carry_state = None  # scattered; drop the shared ref
                t.carry_shard = None
            else:
                shard.pool.admit(
                    lane,
                    seed=int(t.request.seed),
                    horizon_steps=arm_steps,
                    n_agents=self._request_agents(bucket, t.request),
                    overrides=t.request.overrides or None,
                )
        except Exception as e:  # bad overrides/counts: fail the REQUEST
            carry = t.carry_key
            t.error = f"{type(e).__name__}: {e}"
            self._finish(t, FAILED)  # releases the carry pin
            self._metrics.inc("failed")
            if (
                isinstance(e, OSError)
                and carry is not None
                and carry in self.snapshots
                and self.snapshots.refs(carry) == 0
            ):
                # a torn disk spill must fail at most the requests
                # already pinned to it, never every future fork of
                # the prefix: forget the unpromotable entry so later
                # submits MISS and recompute (prewarm's prefetch path
                # applies the same repair)
                self.snapshots.drop(carry)
            return
        if self.trace:
            self.trace.emit_span(
                "admit", admit_t0, time.perf_counter(),
                track=SCHED_TRACK, rid=t.request_id,
                tick=self._ticks, shard=shard.index, lane=lane,
                fork=t.prefix_key is not None,
                continuation=t.parent is not None
                and t.prefix_key is None,
            )
        if t.prefix_key is not None:
            self._metrics.inc("prefix_forks")
        t.status = RUNNING
        t.lane = lane
        t.shard = shard.index
        t.admitted_at = now
        t.mark_stage("admitted", self._ticks)
        shard.assignments[lane] = t
        if not t.internal:
            self._results[t.request_id] = self._make_sink(t)
            if self._streamer is not None:
                self._stream_done[t.request_id] = threading.Event()
        # attached followers come alive with their leader's lane: each
        # gets its OWN sink (and stream event) here, fed by fan-out
        # slices at every window — but no lane, and no place in the
        # admitted counter (they scatter nothing)
        for f in self._dedup_groups.get(t.request_id, ()):
            f.status = RUNNING
            f.shard = shard.index
            f.admitted_at = now
            f.mark_stage("admitted (coalesced)", self._ticks)
            self._results[f.request_id] = self._make_sink(f)
            if self._streamer is not None:
                self._stream_done[f.request_id] = threading.Event()
        self._metrics.inc("admitted")
        self.faults.kill("admitted")

    def _promote_warm_run(self, key, priority: str) -> None:
        """A client fork now depends on a speculative run. If its warm
        ticket is still waiting for scraps on the warm queue, move it
        into the CLIENT queue (force-pushed, exactly where a plain
        miss's internal prefix run goes) under the fork's admission
        class — the run is on a real request's latency path now.
        RUNNING warm tickets need nothing: the waiter check in
        ``_preempt_warm_lanes`` already shields them."""
        for w in self._warm_queue:
            if w.content_key == key:
                self._warm_queue.remove(w)
                w.request = dc_replace(w.request, priority=priority)
                self.queue.push(w, retry_after=0.0, force=True)
                return

    def _preempt_warm_lanes(self) -> bool:
        """Free lanes running waiter-less warm tickets for the
        admissible client tickets queued this tick. The preempted
        run's exact progress is captured on-device (one jitted lane
        slice, the hold_state mechanism) and carried back onto the
        warm queue, so resuming later costs nothing but the scatter —
        and the resumed run is bitwise the run that was interrupted."""
        preempted = False
        for name, bucket in self.buckets.items():
            shortfall = sum(
                1 for t in self.queue
                if t.request.composite == name and not t.waiting
            ) - bucket.free_lanes()
            if shortfall <= 0:
                continue
            for shard in bucket.active_shards():
                if shortfall <= 0:
                    break
                for lane, t in list(shard.assignments.items()):
                    if shortfall <= 0:
                        break
                    if not t.warm:
                        continue
                    if self._pending_prefix.get(t.content_key):
                        continue  # real forks wait on it: client work
                    t.carry_state = shard.pool.lane_state_device(lane)
                    t.carry_shard = shard.index
                    shard.pool.release(lane)
                    del shard.assignments[lane]
                    t.status = QUEUED
                    t.lane = None
                    t.shard = None
                    self._warm_queue.append(t)
                    self._metrics.inc("warm_preempted")
                    self.trace.instant(
                        "warm.preempted", rid=t.request_id,
                        tick=self._ticks, shard=shard.index,
                        lane=lane, steps=t.steps_done,
                    )
                    shortfall -= 1
                    preempted = True
        return preempted

    def _make_sink(self, t: Ticket):
        if self.sink == "ram":
            return _RamResult()
        path = os.path.join(self.out_dir, f"{t.request_id}.lens")
        t.result_path = path
        req = t.request
        return _LogResult(
            path,
            t.request_id,
            config={
                "composite": req.composite,
                "seed": int(req.seed),
                "horizon": float(req.horizon),
                "n_agents": req.n_agents,
                "overrides": {
                    SEP.join(map(str, p)): np.asarray(v).tolist()
                    for p, v in flatten_paths(req.overrides or {})
                },
                "emit": dict(req.emit or {}),
                # a forked run's rows are SUFFIX-only with divergent
                # overrides applied at the fork point — without the
                # prefix declaration the file would misdescribe itself
                # as a full t=0 run
                "prefix": (
                    {
                        "horizon": float(req.prefix["horizon"]),
                        "overrides": {
                            SEP.join(map(str, p)): np.asarray(v).tolist()
                            for p, v in flatten_paths(
                                req.prefix.get("overrides") or {}
                            )
                        },
                    }
                    if req.prefix
                    else None
                ),
            },
            flush_every=self.flush_every if self.stream_flush else None,
        )

    def _sweep_quarantine(self, bucket: _Bucket, shard: _Shard) -> None:
        """Consume a shard's pending finite flags (dispatched with the
        previous window, host-copied alongside its trajectory) and
        quarantine any occupied-at-dispatch lane that went non-finite.
        Reading the flags waits only for the PREVIOUS window's compute
        — work the device had to finish before the next dispatch
        anyway — so the check adds a tiny transfer, not a sync point
        the pipeline didn't already have."""
        if shard.pending_check is None:
            return
        flags_dev, watched = shard.pending_check
        shard.pending_check = None
        flags = np.asarray(jax.device_get(flags_dev))
        for lane, (t, step_after) in watched.items():
            if bool(flags[lane]):
                continue
            self._quarantine(bucket, shard, lane, t, step_after)

    def _quarantine(
        self,
        bucket: _Bucket,
        shard: _Shard,
        lane: int,
        t: Ticket,
        step_after: int,
    ) -> None:
        """Fail ONE diverged request: reclaim its lane (running) or
        flip its just-retired DONE to FAILED (the one-window detection
        lag can land after retirement). Co-resident lanes are bitwise
        untouched — the serve path has no cross-lane coupling, so
        quarantine is pure bookkeeping. The poisoned state stays
        frozen in the lane until the next admission overwrites every
        leaf of it."""
        dt = shard.pool.timestep
        t.diverged = True
        t.error = (
            f"SimulationDiverged: non-finite state (NaN/Inf) in lane "
            f"{lane} (shard {shard.index}) of bucket {bucket.name!r} "
            f"within the window "
            f"ending at step {step_after} (t={step_after * dt:g}); "
            f"the request failed and its lane was reclaimed — "
            f"co-batched requests are unaffected; {t.stage_note()}, "
            f"detected at tick {self._ticks}"
        )
        self._metrics.inc("diverged")
        self.trace.instant(
            "lane.quarantined", rid=t.request_id, tick=self._ticks,
            shard=shard.index, lane=lane, step=step_after,
        )
        shard.diverged += 1
        if t.status == RUNNING and shard.assignments.get(lane) is t:
            shard.pool.release(lane)
            del shard.assignments[lane]
            self._finish(t, FAILED)
            self._metrics.inc("failed")
        elif t.status == DONE:
            # retired the same tick its poisoned window was dispatched
            # (divergence in the final window): flip post-hoc — the
            # streamed records end in garbage, and result() must raise
            # rather than hand them back as a completed trajectory
            t.status = FAILED
            self._metrics.inc("failed")
            if t.held_key is not None:
                # never extend a poisoned snapshot
                key, t.held_key = t.held_key, None
                self._metrics.inc(
                    "snapshot_evictions", self.snapshots.release(key)
                )
                if (
                    key in self.snapshots
                    and self.snapshots.refs(key) == 0
                ):
                    self.snapshots.drop(key)
            if t.internal:
                # a diverged PREFIX run that already published its
                # snapshot and seeded waiters: drop the poisoned cache
                # entry; already-seeded forks will diverge and be
                # quarantined individually at their own windows
                if (
                    t.content_key in self.snapshots
                    and self.snapshots.refs(t.content_key) == 0
                ):
                    self.snapshots.drop(t.content_key)
            if self._wal is not None and not t.internal:
                self._wal.append({
                    "event": RETIRE,
                    "rid": t.request_id,
                    "status": FAILED,
                    "error": t.error,
                    "steps": t.steps_done,
                }, shard=shard.index)
        # already terminal non-DONE (cancelled/expired raced the
        # check): keep the terminal status, the diverged flag and
        # error still mark the records as suspect

    # -- whole-device failover (docs/serving.md, "Mesh serving &
    # device failover") ------------------------------------------------------

    def quarantine_device(
        self, shard: int, reason: str = "operator request"
    ) -> int:
        """Quarantine one device shard: drain it from scheduling and
        fail its work over to the surviving devices. Returns how many
        running requests were displaced.

        Every bucket's pool on that device stops dispatching; its
        running requests RE-QUEUE under their original ids — a
        continuation re-arms from its parent's held snapshot (bitwise
        resume where the snapshot survives, via a rehydrated spill or
        a surviving shard), everything else re-runs deterministically
        from its exact inputs — and each re-queued request's sink
        restarts, so the final streamed bytes equal a never-faulted
        run's. Snapshots whose buffers lived in the dead device's
        memory rehydrate from their spills onto a survivor
        (``recover_dir``); without a spill they are lost, and whatever
        depended on the exact bits (queued continuations, future
        ``resubmit`` of a held parent) fails with a descriptive error
        rather than silently recomputing different state.

        Reached three ways: a ``FaultPlan`` ``device_down``
        declaration at the shard's window seam, the device watchdog
        (``device_watchdog_s``), or an operator calling this directly.
        Idempotent per device. There is deliberately no
        un-quarantine: a revived device needs a fresh server (the WAL
        makes that cheap)."""
        if not 0 <= shard < self.n_shards:
            raise IndexError(
                f"shard {shard} not in [0, {self.n_shards})"
            )
        if shard in self._quarantined:
            return 0
        # settle the stream pipe before touching any sink: windows
        # already handed off (including this shard's) finish
        # appending, so the sinks we are about to reset are quiescent.
        # If the pipe is stuck on the DEAD device's own transfer (a
        # truly hung chip under the pipeline), a watchdog-bounded
        # drain times out — proceed with the failover anyway:
        # displaced sinks restart from scratch, and the per-handoff
        # watchdog_s keeps every later stream handoff bounded. (With
        # watchdog_s unset a hung transfer blocks here indefinitely —
        # arm BOTH watchdogs for full hang coverage; docs/serving.md.)
        if self._streamer is not None:
            try:
                self._streamer.drain()
            except WatchdogTimeout:
                pass
        self._quarantined.add(shard)
        self.trace.instant(
            "device.quarantined", shard=shard, tick=self._ticks,
            reason=reason,
        )
        displaced: List[Ticket] = []
        for bucket in self.buckets.values():
            s = bucket.shards[shard]
            s.quarantined = True
            s.pending_check = None
            s.watch = None
            displaced.extend(s.assignments.values())
            s.assignments.clear()
        self._failover_snapshots(shard)
        # any QUEUED ticket may hold a device tree captured on the
        # dead device — a preempted warm ticket's progress capture
        # (warm queue, or client queue after _promote_warm_run) or a
        # coalesced fork's seeded carry_state — and scattering dead
        # buffers fails on real hardware. Void the capture: warm runs
        # restart from scratch, forks re-resolve their prefix against
        # the (just failed-over) store, exactly like the carry_KEY
        # repair in _repair_lost_refs.
        for w in list(self._warm_queue) + list(self.queue):
            if w.carry_shard == shard and w.carry_state is not None:
                w.carry_state = None
                w.carry_shard = None
                w.steps_done = w.steps_base
                if w.prefix_key is not None and w.status == QUEUED:
                    self._resolve_prefix(
                        w, self.buckets[w.request.composite]
                    )
        # re-queue in submission order — failover preserves the FIFO
        # fairness the queue had before the device died
        for t in sorted(displaced, key=lambda t: t.request_id):
            self._requeue_displaced(t, shard, reason)
        self._metrics.quarantined_devices = len(self._quarantined)
        self._metrics.lanes_total = sum(
            b.lanes_total() for b in self.buckets.values()
        )
        self._metrics.queue_depth = len(self.queue)
        if self._wal is not None:
            # observability, not recovery state: a restarted server
            # starts with every device healthy (replay ignores this)
            self._wal.append(
                {"event": QUARANTINE, "shard": shard, "reason": reason}
            )
        return len(displaced)

    def _check_device_watchdog(self, now: float) -> None:
        """Quarantine any device whose oldest dispatched window has
        not completed within ``device_watchdog_s`` — fail-stop
        detection for a chip that silently stopped making progress
        (the per-handoff ``watchdog_s`` catches hung HOST seams; this
        one catches the device itself)."""
        for k in range(self.n_shards):
            if k in self._quarantined:
                continue
            stalled = False
            for bucket in self.buckets.values():
                s = bucket.shards[k]
                if s.watch is None:
                    continue
                if self._window_ready(s):
                    s.watch = None
                elif now - s.watch[0] > self.device_watchdog_s:
                    stalled = True
            if stalled:
                self.quarantine_device(
                    k,
                    reason=(
                        f"device watchdog: a dispatched window made "
                        f"no progress for {self.device_watchdog_s}s"
                    ),
                )

    @staticmethod
    def _window_ready(shard: _Shard) -> bool:
        """Non-blocking completion poll of the WATCHED window's own
        output handle — not the pool's current (newest) one, which a
        busy shard overwrites every tick (jax arrays expose
        ``is_ready``). Anything unpollable reads as ready — the
        watchdog degrades to off rather than false-positive on an
        exotic array type."""
        probe = getattr(shard.watch[1], "is_ready", None)
        if probe is None:
            return True
        try:
            return bool(probe())
        except Exception:
            return True

    def _failover_snapshots(self, dead: int) -> None:
        """Re-home every snapshot whose buffers lived in the dead
        device's memory. With the tiered store, an entry with a
        durable disk copy simply DEMOTES to the disk tier (same key,
        same refs — the admission that next needs it restores onto a
        surviving device, lazily); only entries with no copy anywhere
        else are lost, and the tickets that depended on their exact
        bits are repaired with descriptive failures."""
        for key, orphaned in self.snapshots.device_lost(dead):
            self._metrics.inc("snapshot_evictions")
            if orphaned:
                self._repair_lost_refs(key)

    def _repair_lost_refs(self, key) -> None:
        """A pinned snapshot died with its device (no spill): every
        ticket holding a ref must stop pointing at it — holds are
        dropped (a later ``resubmit`` refuses descriptively), queued
        forks re-resolve their prefix (a fresh run on a survivor),
        queued continuations fail (the parent's exact bits are
        unrecoverable)."""
        for t in list(self.tickets.values()):
            if t.held_key == key:
                t.held_key = None
            if t.carry_key == key and t.status == QUEUED:
                t.carry_key = None
                bucket = self.buckets[t.request.composite]
                if t.prefix_key == key:
                    self._resolve_prefix(t, bucket)
                elif self.queue.drop(t):
                    t.error = (
                        "the held snapshot this continuation extends "
                        "died with its quarantined device and had no "
                        "durable spill (serve with recover_dir to "
                        "make holds survive device loss)"
                    )
                    self._finish(t, FAILED)
                    self._metrics.inc("failed")

    def _requeue_displaced(
        self, t: Ticket, dead: int, reason: str
    ) -> None:
        """Re-queue one request displaced from a quarantined device,
        under its ORIGINAL id. The sink restarts (partial records from
        the dead device are discarded) and the step/emit counters
        reset to the ticket's base, so the re-run regenerates the
        complete stream — bitwise what a never-faulted run would have
        streamed, by the serving determinism contract. A continuation
        re-pins its parent's held snapshot (rehydrated by
        :meth:`_failover_snapshots` when the parent ran on the dead
        device); a fork re-resolves its prefix against the store."""
        bucket = self.buckets[t.request.composite]
        sink = self._results.pop(t.request_id, None)
        if sink is not None:
            try:
                sink.close()
            except Exception:
                pass  # a torn sink must not abort the failover
        self._stream_done.pop(t.request_id, None)
        t.status = QUEUED
        t.lane = None
        t.shard = None
        t.diverged = False
        t.error = None
        t.steps_done = t.steps_base
        t.emit_count = t.steps_base // bucket.pool.emit_every
        # the timing table reports the run that produced the surviving
        # records — the dead device's window/stream stamps are void —
        # and the re-admission's queue.wait span starts here, not at
        # the original submit
        t.first_window_at = None
        t.streamed_at = None
        t.requeued_at = time.perf_counter()
        t.requeues += 1
        t.carry_state = None
        t.carry_shard = None
        t.waiting = False
        # a sink failure parked for the OLD incarnation is void — the
        # re-run gets a fresh sink, and the first-failure-wins guard
        # must not swallow a genuine failure of the new one
        with self._sink_fail_lock:
            t.sink_closed = False
            self._sink_failures.pop(t.request_id, None)
        if t.cancel_requested:
            self._finish(t, CANCELLED)
            self._metrics.inc("cancelled")
            return
        failure = None
        parent = (
            self.tickets.get(t.parent)
            if t.parent is not None and t.prefix_key is None
            else None
        )
        if not bucket.active_shards():
            failure = (
                f"device {dead} quarantined ({reason}) and no "
                f"surviving device serves bucket {bucket.name!r}"
            )
        elif (
            not t.internal
            and t.parent is not None
            and t.prefix_key is None
            and (parent is None or parent.held_key is None)
        ):
            failure = (
                f"device {dead} quarantined ({reason}); the parent "
                f"request's held state died with it and had no "
                f"durable spill, so this continuation cannot re-arm "
                f"(serve with recover_dir to make holds survive "
                f"device loss)"
            )
        if failure is not None:
            t.error = failure
            self._finish(t, FAILED)
            self._metrics.inc("failed")
            return
        # displaced leader: its followers' sinks also carry partial
        # records from the dead device — restart them alongside the
        # leader so every fanned-out stream regenerates complete
        for f in self._dedup_groups.get(t.request_id, ()):
            self._reset_follower(f, bucket)
            f.mark_stage(
                f"requeued off quarantined device {dead} "
                f"(coalesced)", self._ticks,
            )
        if self.dedup == "on" and t.fingerprint is not None:
            # back in the queue, the leader can pick up NEW followers
            self._dedup_leaders[t.fingerprint] = t
        # force: failover re-queues already-admitted work; bouncing it
        # off the client backpressure bound would drop accepted
        # requests
        self.queue.push(t, retry_after=0.0, force=True)
        t.mark_stage(
            f"requeued off quarantined device {dead}", self._ticks
        )
        if not t.internal:
            self._metrics.inc("requeued")
            self.trace.instant(
                "request.requeued", rid=t.request_id,
                tick=self._ticks, shard=dead,
            )
        if parent is not None and not t.internal:
            t.carry_key = parent.held_key
            self.snapshots.acquire(parent.held_key)
        if t.prefix_key is not None:
            self._resolve_prefix(t, bucket)

    def _run_shard_window(self, bucket: _Bucket, shard: _Shard) -> None:
        """Dispatch one window on ONE device shard and route its host
        work (each shard's window is an independent device program —
        dispatches across shards queue per-device and overlap).

        Pipelined (default): start the trajectory's device->host copy,
        do ALL retire/admit bookkeeping from the host-mirrored
        counters (no device readback), enqueue the window on the
        background streamer, and return — the next tick dispatches
        window k+1 while the streamer slices/appends window k. A
        retiring hold_state lane is snapshotted ON-DEVICE here (before
        any reassignment) with the host fetch deferred.

        Synchronous (``pipeline="off"``): the r08 path — one blocking
        ``device_get``, then inline slicing/appends via the same
        ``process_window`` the streamer runs, so both modes produce
        byte-identical sink contents.
        """
        pool = shard.pool
        pipelined = self._streamer is not None
        if self.faults:
            # fault seam "lane.state": poison a matched request's lane
            # BEFORE the dispatch, so the NaN propagates through this
            # window and the finite check sees it at the next tick
            for lane, t in shard.assignments.items():
                if self.faults.poison(t.request_id, t.steps_done):
                    pool.poison_lane(lane)
        t0 = time.perf_counter()
        remaining_before, traj = pool.run_window()
        shard.windows += 1
        if self.trace:
            # the dispatch itself (enqueue + host bookkeeping window;
            # first call of a bucket includes its trace/compile) —
            # device compute is timed separately from the async-copy
            # completion (window.device)
            self.trace.emit_span(
                "window.dispatch", t0, time.perf_counter(),
                track=SCHED_TRACK, tick=self._ticks,
                shard=shard.index, bucket=bucket.name,
                lanes_busy=len(shard.assignments),
            )
        for t in shard.assignments.values():
            if t.first_window_at is None:
                t.first_window_at = t0
            # raw fields only — stage_note() formats lazily, so the
            # per-lane-per-window cost is one tuple, not an f-string
            t.mark_stage(
                "window dispatched", self._ticks,
                (min(t.steps_done + pool.window_steps,
                     t.horizon_steps),
                 t.horizon_steps, shard.index),
            )
        if self.device_watchdog_s is not None and shard.watch is None:
            # device watchdog arm: time THIS window against its own
            # output handle (a [L] int32 — negligible to keep alive);
            # the next window is timed only after this one completes.
            # Clock starts NOW, not at t0: run_window() returns after
            # trace/compile, and a first-dispatch compile can dwarf
            # any sane deadline — the watchdog must time device
            # progress only
            shard.watch = (time.perf_counter(), pool.remaining)
        self.faults.kill("window.dispatched")
        self._metrics.inc("windows")
        self._metrics.inc("lane_windows_busy", len(shard.assignments))
        self._metrics.inc("lane_windows_total", pool.n_lanes)
        shard.lane_windows_busy += len(shard.assignments)
        shard.lane_windows_total += pool.n_lanes

        if self.check_finite == "window":
            # per-lane finite flags over the post-window states, read
            # at the NEXT tick's sweep; the map freezes lane->ticket at
            # dispatch (lanes retire/reassign underneath the lag)
            flags = pool.finite_flags()
            shard.pending_check = (
                flags,
                {
                    lane: (
                        t,
                        t.steps_done + min(
                            int(remaining_before[lane]),
                            pool.window_steps,
                        ),
                    )
                    for lane, t in shard.assignments.items()
                },
            )
            if pipelined:
                copy_tree_to_host_async(flags)

        if pipelined:
            copy_tree_to_host_async(traj)
            host = ready = None
        else:
            # ONE device->host transfer for the whole window, shared by
            # every lane's slicing below (same policy as the run path's
            # per-segment transfer).
            host = jax.device_get(traj)
            ready = time.perf_counter()
            shard.watch = None  # blocked through it: observed complete

        slices: List[LaneSlice] = []
        retiring = []
        for lane, t in list(shard.assignments.items()):
            before = int(remaining_before[lane])
            retire = before <= pool.window_steps  # horizon elapsed
            if t.internal:
                # a prefix run emits nothing (its product is the
                # snapshot, captured at retirement below) — advance
                # the step counter and skip all sink routing
                t.steps_done += min(before, pool.window_steps)
                if retire:
                    retiring.append((lane, t))
                continue
            data = self._lane_slice(pool, t, lane, before)
            job = data
            if job is not None:
                slices.append(job)
            elif retire and pipelined:
                # no rows kept this window, but the sink must still
                # close AFTER any appends already queued for it
                job = LaneSlice(
                    t.request_id, self._results[t.request_id],
                    on_error=(
                        self._sink_error_cb(t)
                        if self.sink_errors == "request"
                        else None
                    ),
                )
                slices.append(job)
            if retire:
                if pipelined:
                    # close + completion bookkeeping ride the slice so
                    # they happen when the records are actually down,
                    # keeping latency_seconds comparable with the
                    # synchronous path (status flips DONE now; the
                    # sample lands at stream completion)
                    job.close_after = True
                    job.on_close = self._completion_cb(t)
                retiring.append((lane, t))
            # suffix-dedup fan-out: every attached follower mirrors
            # this window into its OWN sink — the leader's row
            # selection (idx/times/paths) verbatim, so each follower's
            # log is byte-equal to its solo run; error scope stays
            # per-follower (one torn follower sink never touches the
            # leader or its siblings)
            for f in self._dedup_groups.get(t.request_id, ()):
                f.steps_done = t.steps_done
                f.emit_count = t.emit_count
                if f.first_window_at is None:
                    f.first_window_at = t0
                f.mark_stage(
                    "window dispatched", self._ticks, t.stage_info
                )
                fjob = None
                if data is not None:
                    fjob = LaneSlice(
                        f.request_id,
                        self._results[f.request_id],
                        lane=lane,
                        idx=data.idx,
                        times=data.times,
                        paths=data.paths,
                        on_error=(
                            self._sink_error_cb(f)
                            if self.sink_errors == "request"
                            else None
                        ),
                    )
                elif retire and pipelined:
                    fjob = LaneSlice(
                        f.request_id, self._results[f.request_id],
                        on_error=(
                            self._sink_error_cb(f)
                            if self.sink_errors == "request"
                            else None
                        ),
                    )
                if fjob is not None:
                    slices.append(fjob)
                    if retire and pipelined:
                        fjob.close_after = True
                        fjob.on_close = self._completion_cb(f)

        if not pipelined:
            # append BEFORE retiring: _finish closes sinks inline in
            # sync mode, and a request's final rows precede its close
            process_window(host, slices, faults=self.faults)
            done = time.perf_counter()
            self._metrics.observe_window(done - t0)
            self._metrics.observe_stream(t0, ready, done)
            if self.trace:
                # same two spans the streamer emits pipelined, so a
                # sync trace renders on the same tracks (serialized)
                self.trace.emit_span(
                    "window.device", t0, ready,
                    track=device_track(shard.index),
                    shard=shard.index, tick=self._ticks,
                )
                self.trace.emit_span(
                    "window.stream", ready, done, track=STREAM_TRACK,
                    shard=shard.index, tick=self._ticks,
                    requests=len(slices),
                )

        for lane, t in retiring:
            if t.internal or t.request.hold_state:
                # capture the lane's exact final bits BEFORE the lane
                # can be reassigned, so a later fork/resubmit continues
                # the scenario bitwise; the capture stays on-device (a
                # jitted lane slice, no sync) — admit_state scatters
                # the device tree as-is, host bytes only if a client
                # inspects them
                snap = pool.lane_state_device(lane)
                if t.internal:
                    # a finished prefix run: publish the snapshot
                    # (unpinned cache content, owned by this shard's
                    # device) and release every coalesced fork
                    # waiting on it
                    self._metrics.inc(
                        "snapshot_evictions",
                        self.snapshots.put(
                            t.content_key, snap, shard=shard.index
                        ),
                    )
                    if t.warm:
                        # a speculative run's product: tag it so later
                        # hits count as warming successes
                        self.snapshots.mark_warmed(t.content_key)
                        self._warm_pending.discard(t.content_key)
                        self._metrics.inc("warm_completed")
                    self._resolve_waiters(
                        t.content_key, snap, shard=shard.index
                    )
                else:
                    # hold_state: pin the snapshot for resubmit —
                    # content-addressed when the run is pure (so it
                    # doubles as a prefix-cache entry), per-request
                    # otherwise
                    held = (
                        t.content_key
                        if t.content_key is not None
                        else ("held", t.request_id)
                    )
                    self._metrics.inc(
                        "snapshot_evictions",
                        self.snapshots.put(
                            held, snap, pin=True, shard=shard.index
                        ),
                    )
                    t.held_key = held
                    if self._wal is not None:
                        self._spill_hold(t, held)
            del shard.assignments[lane]
            self._finish(t, DONE)
            self._metrics.inc("retired")

        if pipelined:
            stall = self._streamer.submit(
                WindowItem(
                    traj, slices, dispatched_at=t0,
                    shard=shard.index, tick=self._ticks,
                )
            )
            self._metrics.observe_stall(stall)
            # window wall (dispatch -> trajectory host-side) is
            # observed by the streamer; the dispatch itself is ~free

    def _lane_slice(
        self, pool: LanePool, t: Ticket, lane: int, before: int
    ) -> Optional[LaneSlice]:
        """Bookkeep one lane's window and build its stream slice (rows
        kept after the request's ``every`` subsample + path filter), or
        None if nothing is kept. Host arithmetic only — the scheduler
        never reads the device. Advances ``t.emit_count`` and
        ``t.steps_done``."""
        n_valid = pool.valid_emits(before)
        ran = min(before, pool.window_steps)
        idx = None
        if n_valid:
            every = int((t.request.emit or {}).get("every", 1))
            # global (request-local) emit indices of this window's rows
            idx = subsample_rows(t.emit_count, n_valid, every)
            t.emit_count += n_valid
        if idx is None or not idx.size:
            t.steps_done += ran
            return None
        times = (
            t.steps_done + (idx + 1) * pool.emit_every
        ) * pool.timestep
        t.steps_done += ran
        paths = (t.request.emit or {}).get("paths")
        return LaneSlice(
            t.request_id,
            self._results[t.request_id],
            lane=lane,
            idx=idx,
            times=times,
            paths=[str(p) for p in paths] if paths else None,
            on_error=(
                self._sink_error_cb(t)
                if self.sink_errors == "request"
                else None
            ),
        )

    def _spill_hold(self, t: Ticket, key) -> None:
        """Durably spill a held snapshot and WAL the hold, so a killed
        server's ``resubmit`` chain can rehydrate the exact bits. The
        spill IS a disk-tier object now (round 16): the store's
        ``persist`` writes the same tmp+rename directory a budget
        demotion would, plus the content sidecar — one on-disk format,
        whether the bytes got there by durability or by paging (the
        round-12 double-spill is gone). Runs on the scheduler thread
        at retirement, paid only by ``hold_state`` requests under a
        ``recover_dir``; lands BEFORE the retire event (file order =
        replay order), so a resubmit event in the WAL always implies a
        complete spill."""
        t0 = time.perf_counter()
        name = self.snapshots.persist(key)
        if self.trace:
            self.trace.emit_span(
                "hold.spill", t0, time.perf_counter(),
                track=SCHED_TRACK, rid=t.request_id,
                tick=self._ticks, shard=t.shard or 0,
            )
        self._wal.append({
            "event": HOLD,
            "rid": t.request_id,
            "key": key_to_json(key),
            "name": name,
        }, shard=t.shard or 0)
        self.faults.kill("hold.spilled")

    def _mark_streamed(self, t: Ticket) -> None:
        """WAL the moment a request's records are durably down (sink
        closed + flushed): the event that lets recovery trust a DONE
        request's log instead of re-running it. Called from the stream
        thread (pipelined) or the scheduler (sync) — the WAL is
        thread-safe."""
        t.streamed_at = time.perf_counter()
        t.mark_stage("streamed", self._ticks)
        if self._wal is not None and not t.internal:
            self._wal.append(
                {"event": STREAMED, "rid": t.request_id},
                shard=t.shard or 0,
            )
            self.faults.kill("streamed.walled")

    def _sink_error_cb(self, t: Ticket):
        """The per-request sink-failure handler handed to each stream
        slice under ``sink_errors="request"``: runs on the stream
        thread (or inline on the sync path), closes the broken sink,
        parks the failure for the scheduler's sweep, and releases any
        ``result()`` waiter. First failure wins (later windows of the
        same dead sink re-raise into the same handler)."""

        def failed(e: BaseException) -> None:
            with self._sink_fail_lock:
                if t.sink_closed:
                    # later windows of the already-dead sink raise
                    # again; the FIRST failure is the cause on record
                    return
                t.sink_closed = True
                self._sink_failures[t.request_id] = e
            try:
                self._results[t.request_id].close()
            except Exception:
                pass  # the sink is already broken
            # the torn stream is FINAL: whatever landed before the
            # failure is all there will ever be — stamp the stream
            # completion so result() and front-door streams stop
            # waiting for appends that can never come (no WAL
            # `streamed` event: that attestation is reserved for
            # complete DONE streams)
            t.streamed_at = time.perf_counter()
            ev = self._stream_done.get(t.request_id)
            if ev is not None:
                ev.set()

        return failed

    def _sweep_sink_failures(self) -> None:
        """Consume failures the stream path scoped to single requests
        (``sink_errors="request"``) and retire them FAILED: a RUNNING
        request's lane is reclaimed, a just-retired DONE flips FAILED
        post-hoc (the same one-window lag discipline as the finite
        check) — co-batched requests are untouched either way."""
        if not self._sink_failures:
            return
        with self._sink_fail_lock:
            failures, self._sink_failures = self._sink_failures, {}
        for rid, e in failures.items():
            t = self.tickets.get(rid)
            if t is None:
                continue
            if t.status == QUEUED:
                # a device quarantine re-queued the ticket between the
                # failure and this sweep: the failed sink belonged to
                # the dead incarnation and the re-run streams afresh —
                # the stale failure is void
                continue
            t.error = (
                f"sink failure: {type(e).__name__}: {e} — the "
                f"request's result stream is torn and the request "
                f"failed; co-batched requests are unaffected; "
                f"{t.stage_note()}"
            )
            self._metrics.inc("sink_failed")
            self.trace.instant(
                "sink.failed", rid=rid, tick=self._ticks,
            )
            if t.status == RUNNING:
                shard = (
                    self.buckets[t.request.composite].shards[t.shard]
                    if t.shard is not None
                    else None
                )
                if (
                    shard is not None
                    and t.lane is not None
                    and shard.assignments.get(t.lane) is t
                ):
                    shard.pool.release(t.lane)
                    del shard.assignments[t.lane]
                self._finish(t, FAILED)
                self._metrics.inc("failed")
            elif t.status == DONE:
                # retired before its final window's append landed:
                # flip post-hoc, drop any held snapshot of a request
                # whose stream the client can never trust
                t.status = FAILED
                self._metrics.inc("failed")
                if t.held_key is not None:
                    key, t.held_key = t.held_key, None
                    self._metrics.inc(
                        "snapshot_evictions",
                        self.snapshots.release(key),
                    )
                    if (
                        key in self.snapshots
                        and self.snapshots.refs(key) == 0
                    ):
                        self.snapshots.drop(key)
                if self._wal is not None and not t.internal:
                    self._wal.append({
                        "event": RETIRE,
                        "rid": t.request_id,
                        "status": FAILED,
                        "error": t.error,
                        "steps": t.steps_done,
                    }, shard=t.shard or 0)
            # other terminal states (cancelled/expired raced the
            # failure): keep the terminal status; the error string
            # still marks the records as torn

    def _completion_cb(self, t: Ticket):
        """Completion bookkeeping for a pipelined DONE request, run by
        the stream thread after the final append + sink close: stamps
        the data-available finish time and records the latency sample
        there, so pipelined percentiles measure when ``result()`` could
        actually return, not when bookkeeping ran ahead."""

        def done() -> None:
            t.finished_at = time.perf_counter()
            if t.admitted_at is not None:
                self._metrics.observe_request(
                    t.admitted_at - t.submitted_at,
                    t.finished_at - t.submitted_at,
                )
            self._mark_streamed(t)
            if self._result_cache is not None and not t.internal:
                # the log is complete and closed — hand it to the
                # scheduler's next cache sweep (list.append is atomic;
                # the sweep runs on the scheduler thread)
                self._cache_pending.append(t)
            ev = self._stream_done.get(t.request_id)
            if ev is not None:
                ev.set()

        return done

    def _finish(self, t: Ticket, status: str) -> None:
        t.status = status
        t.finished_at = time.perf_counter()
        t.mark_stage(f"retired {status}", self._ticks)
        if not t.internal:
            self.trace.instant(
                "retire", rid=t.request_id, tick=self._ticks,
                status=status, shard=t.shard,
                steps=t.steps_done,
            )
        if self._wal is not None and not t.internal:
            # terminal fact first (a kill right after must see the
            # status); DONE completeness is attested separately by the
            # streamed event once the records are durably down
            self._wal.append({
                "event": RETIRE,
                "rid": t.request_id,
                "status": status,
                "error": t.error,
                "steps": t.steps_done,
            }, shard=t.shard or 0)
            self.faults.kill("retired.walled")
        if t.carry_key is not None:
            # terminal before the scatter consumed it (failed
            # admission, cancelled/expired while queued): drop the
            # ticket's pin so the snapshot is evictable again
            self._metrics.inc(
                "snapshot_evictions",
                self.snapshots.release(t.carry_key),
            )
            t.carry_key = None
        # a coalesced waiter's unscattered seed is device memory the
        # store never accounted for — a terminal ticket must not keep
        # the tree alive for the server's lifetime
        t.carry_state = None
        if t.internal and status != DONE:
            # a failed/killed prefix run: every coalesced fork waiting
            # on it can never be seeded — fail them with the cause
            # rather than leaving them queued forever
            self._warm_pending.discard(t.content_key)
            for w in self._pending_prefix.pop(t.content_key, []):
                if w.status == QUEUED and self.queue.drop(w):
                    w.error = t.error or f"prefix run {status}"
                    self._finish(w, FAILED)
                    self._metrics.inc("failed")
        if t.fingerprint is not None \
                and self._dedup_leaders.get(t.fingerprint) is t:
            # a terminal leader must stop accepting attachments
            del self._dedup_leaders[t.fingerprint]
        if t.leader is not None:
            # a follower retiring on its own (sink failure, shutdown)
            # must leave its leader's group, or the leader's terminal
            # propagation would re-finish it over this status
            group = self._dedup_groups.get(t.leader)
            if group is not None and t in group:
                group.remove(t)
        followers = self._dedup_groups.pop(t.request_id, None)
        if followers:
            self._resolve_group(t, followers, status)
        sink = self._results.get(t.request_id)
        pipelined_done = self._streamer is not None and status == DONE
        if sink is not None and not t.sink_closed:
            if self._streamer is None:
                sink.close()
                self._mark_streamed(t)
                if status == DONE and self._result_cache is not None \
                        and not t.internal:
                    self._cache_pending.append(t)
            elif status != DONE:
                # cancel/timeout of a RUNNING request: its last window
                # may still be queued on the streamer — close in FIFO
                # order so partial records land before the close
                ev = self._stream_done.get(t.request_id)

                def closed(t=t, ev=ev) -> None:
                    self._mark_streamed(t)
                    if ev is not None:
                        ev.set()

                self._streamer.submit_close(
                    sink,
                    on_close=closed,
                    on_error=(
                        self._sink_error_cb(t)
                        if self.sink_errors == "request"
                        else None
                    ),
                )
            # pipelined DONE: the retiring window's LaneSlice carries
            # close_after, keeping append->close order per request
        if t.admitted_at is not None and not pipelined_done \
                and not t.internal:
            # pipelined DONE latency is observed by _completion_cb at
            # stream completion instead; internal prefix runs are not
            # client requests and never enter the latency percentiles
            self._metrics.observe_request(
                t.admitted_at - t.submitted_at,
                t.finished_at - t.submitted_at,
            )

    # -- WAL recovery --------------------------------------------------------

    def _recover(self) -> None:
        """Replay the WAL into live server state (constructor-time,
        before any client call). Finished requests (a terminal
        ``retire``; DONE additionally needs ``streamed`` — under the
        pipeline, status runs ahead of the sink, and recovery must not
        trust a DONE whose records never fully landed) materialize as
        terminal tickets over their on-disk result logs, with held
        snapshots re-pinned from their spills. Everything else is
        RE-QUEUED under its original id and re-runs from its exact
        inputs — the determinism contract turns that into a bitwise
        resume (its partial result log is truncated at re-admission).
        Continuations re-queue from their parent's spilled snapshot,
        whether or not the parent itself finished."""
        order, recs, retired, streamed, holds, released = (
            classify_events(self._wal.events)
        )
        if not order:
            return
        self.queue.skip_ids(
            1 + max(int(r.rsplit("-", 1)[1]) for r in order)
        )
        for rid in order:
            fin = retired.get(rid)
            finished = fin is not None and not (
                fin.get("status") == DONE and rid not in streamed
            )
            if finished:
                self._materialize(rid, recs, fin, holds, released)
            else:
                self._requeue(rid, recs, holds)
                self.recovered += 1
                self._metrics.inc("recovered")

    def _effective_request(
        self, rid: str, recs: Mapping[str, Mapping[str, Any]]
    ) -> ScenarioRequest:
        """The full-horizon request a WAL record denotes: a submit
        record's request as-is; a resubmit record resolves its parent
        chain and extends the horizon — a continuation is, bitwise,
        one long request."""
        rec = recs[rid]
        if rec.get("event") == SUBMIT:
            return ScenarioRequest.from_mapping(rec["request"])
        parent = self._effective_request(rec["parent"], recs)
        return dc_replace(
            parent,
            horizon=float(parent.horizon) + float(rec["extra_horizon"]),
        )

    def _rehydrate(self, hold: Mapping[str, Any], pin: bool):
        """Re-pin one spilled snapshot INTO the disk tier (round 16:
        ``adopt`` registers the existing spill without restoring it —
        the held state is promoted lazily, at the admission that
        actually scatters it, so recovery memory is bounded by what
        runs instead of by everything ever held); returns its key.
        Idempotent across multiple continuations of one parent."""
        key = key_from_json(hold["key"])
        try:
            self.snapshots.adopt(key, str(hold["name"]), pin=pin)
        except FileNotFoundError as e:
            raise FileNotFoundError(
                f"{e} — the WAL records this hold for request "
                f"{hold.get('rid')!r}; recovery cannot rebuild the "
                f"held state"
            ) from None
        return key

    def _materialize(self, rid, recs, fin, holds, released) -> None:
        """A finished request becomes a terminal ticket: status, error,
        result path, and (for an unreleased hold) the re-pinned held
        snapshot — so ``status``/``result``/``resubmit`` keep working
        across the restart."""
        request = self._effective_request(rid, recs)
        bucket = self.buckets[request.composite]
        steps = self._horizon_steps(bucket, request.horizon)
        status = str(fin.get("status"))
        t = Ticket(
            request_id=rid,
            request=request,
            status=status,
            error=fin.get("error"),
            horizon_steps=steps,
            steps_done=int(fin.get("steps", steps)),
            emit_count=steps // bucket.pool.emit_every,
            parent=recs[rid].get("parent"),
            content_key=(
                self._content_key(bucket, request, steps)
                if request.hold_state
                else None
            ),
        )
        if "SimulationDiverged" in str(fin.get("error") or ""):
            t.diverged = True
        path = os.path.join(self.out_dir, f"{rid}.lens")
        if os.path.exists(path):
            t.result_path = path
        if (
            status == DONE
            and rid in holds
            and rid not in released
            and request.hold_state
        ):
            t.held_key = self._rehydrate(holds[rid], pin=True)
        self.tickets[rid] = t

    def _requeue(self, rid, recs, holds) -> None:
        """Re-admit one unfinished request under its original id."""
        rec = recs[rid]
        request = self._effective_request(rid, recs)
        if rec.get("event") == SUBMIT:
            ticket = self._build_ticket(request, rid)
            if self.dedup == "on" and self._try_coalesce(ticket):
                # the group re-forms deterministically from replayed
                # SUBMITs in submission order (the leader re-queued
                # first and re-registered) — no duplicate WAL events
                self.tickets[rid] = ticket
                self._metrics.inc("submitted")
                return
        else:
            # a continuation: re-arm only the extension, seeded from
            # the parent's spilled snapshot (present by WAL ordering:
            # resubmit implies the parent retired DONE, which implies
            # its hold was spilled first) — independent of whether the
            # parent itself is being re-run for its records
            parent_rid = rec["parent"]
            parent_req = self._effective_request(parent_rid, recs)
            bucket = self.buckets[request.composite]
            total_steps = self._horizon_steps(bucket, request.horizon)
            parent_steps = self._horizon_steps(
                bucket, parent_req.horizon
            )
            ticket = Ticket(
                request_id=rid,
                request=request,
                horizon_steps=total_steps,
                steps_done=parent_steps,
                steps_base=parent_steps,
                emit_count=parent_steps // bucket.pool.emit_every,
                content_key=(
                    self._content_key(bucket, request, total_steps)
                    if request.hold_state
                    else None
                ),
                parent=parent_rid,
            )
            ticket.carry_key = self._rehydrate(
                holds[parent_rid], pin=False
            )
            self.snapshots.acquire(ticket.carry_key)
        # force: the bounded queue is client backpressure; refusing
        # our own recovery backlog would drop admitted work
        self.queue.push(ticket, retry_after=0.0, force=True)
        self._register(ticket)

    def _request_table(self) -> List[Dict[str, Any]]:
        """The ``server_meta.json`` per-request timing table: one row
        per client request (internal prefix runs excluded) with its
        lifecycle wall times — queued, admitted, first window on a
        device, last streamed, retired — derived from the span marks
        the scheduler stamps on each ticket. Rows are in request-id
        order (= submission order)."""
        return [
            request_timing_row(t, self._metrics._t0)
            for rid, t in sorted(self.tickets.items())
            if not t.internal
        ]

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Drain and join the streamer thread, close every sink, and
        write ``server_meta.json`` — in that order, each step running
        even if an earlier one fails, so a crashed driver can never
        leak open log handles or lose the metrics sidecar. Idempotent.
        The first error (a parked stream failure, a sink close) is
        re-raised AFTER cleanup completes."""
        if self._closed:
            return
        self._closed = True
        first_error: Optional[BaseException] = None
        try:
            # scoped sink failures still parked flip their requests
            # FAILED before the meta/timing table is written
            self._sweep_sink_failures()
        except BaseException as e:
            first_error = e
        # fail coalesced-prefix waiters FIRST, with the cause: their
        # shared prefix run will never land now, and a queued fork
        # left QUEUED forever would read as "still pending" to any
        # client holding its id (no sink exists yet, so this touches
        # no streamer state)
        try:
            for key, waiters in list(self._pending_prefix.items()):
                for w in waiters:
                    if w.status == QUEUED:
                        self.queue.drop(w)
                        w.error = (
                            "server closed while the shared prefix "
                            "this fork was waiting on was still in "
                            "flight"
                        )
                        self._finish(w, FAILED)
                        self._metrics.inc("failed")
            self._pending_prefix.clear()
        except BaseException as e:
            first_error = e
        # coalesced followers still riding an unfinished leader fail
        # the same way: their shared lane will never retire now, and a
        # follower parked QUEUED forever would read as still pending
        try:
            for leader_rid, followers in list(
                self._dedup_groups.items()
            ):
                for f in followers:
                    f.error = (
                        f"server closed while coalesced onto "
                        f"in-flight leader {leader_rid}"
                    )
                    f.leader = None  # detach before _finish re-walks
                    self._finish(f, FAILED)
                    self._metrics.inc("failed")
            self._dedup_groups.clear()
        except BaseException as e:
            first_error = first_error or e
        if self._streamer is not None:
            try:
                self._streamer.close()
            except BaseException as e:
                first_error = e
        for sink in self._results.values():
            try:
                sink.close()
            except BaseException as e:
                first_error = first_error or e
        try:
            # results completed by the streamer's final drain still
            # file into the durable cache before the handle is lost
            self._sweep_result_cache()
        except BaseException as e:
            first_error = first_error or e
        # drop every ticket's snapshot pin (held states, unscattered
        # carries) — every acquire pairs with a release even on the
        # close path, so a refcount imbalance surfaces HERE as an
        # error instead of leaking silently
        try:
            for t in self.tickets.values():
                if t.carry_key is not None:
                    self._metrics.inc(
                        "snapshot_evictions",
                        self.snapshots.release(t.carry_key),
                    )
                    t.carry_key = None
                if t.held_key is not None:
                    self._metrics.inc(
                        "snapshot_evictions",
                        self.snapshots.release(t.held_key),
                    )
                    t.held_key = None
        except BaseException as e:
            first_error = first_error or e
        if self.meta_dir:
            try:
                # failures parked during the streamer's final drain
                # must flip their tickets before the table is written
                self._sweep_sink_failures()
                self._refresh_gauges()
                write_server_meta(
                    self.meta_dir,
                    {name: b.cfg for name, b in self.buckets.items()},
                    self._metrics,
                    requests=self._request_table(),
                )
            except BaseException as e:
                # never let a failed meta write mask the root cause
                first_error = first_error or e
        if self._metrics_ring is not None:
            try:
                # one terminal sample so the ring always ends with the
                # final counters, then release the file handle
                self._refresh_gauges()
                self._metrics_ring.append(self._metrics.sample_point())
                self._metrics_ring.close()
            except BaseException as e:
                first_error = first_error or e
        try:
            self.trace.close()
        except BaseException as e:
            first_error = first_error or e
        if self._wal is not None:
            try:
                self._wal.close()
            except BaseException as e:
                first_error = first_error or e
        self.snapshots.clear()  # free the resident device trees
        if first_error is not None:
            raise first_error

    def __enter__(self) -> "SimServer":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        try:
            self.close()
        except BaseException:
            # cleanup errors must not mask the exception already
            # unwinding through the with-block; surface them only on
            # the clean-exit path
            if exc_type is None:
                raise
