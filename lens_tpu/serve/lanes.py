"""Fixed lane pools: L logical scenarios resident in ONE jitted program.

The serving primitive. A ``LanePool`` wraps any colony-form sim
(:class:`~lens_tpu.colony.colony.Colony`, ``SpatialColony``,
``MultiSpeciesColony``) in an :class:`~lens_tpu.colony.ensemble.Ensemble`
of ``n_lanes`` replicates and keeps a small fixed set of device
programs hot for the server's whole lifetime:

- ``_build_solo``: jitted solo-state construction, one compile per
  (n_agents, override structure) — seed and override values are traced
  data, so every sweep trial / plain request reuses one program
  (eager per-admission builds were the admission bottleneck);
- ``_admit``: scatter one freshly-built solo state into lane ``i`` and
  arm its remaining-steps counter (``i`` and the counter are traced
  scalars, so every admission reuses one compile);
- ``_window``: advance every lane by ``window_steps`` steps, freezing
  lanes whose per-lane ``remaining`` counter hits zero mid-window
  (``Ensemble.step_where`` — the replicate-axis version of the colony's
  dead-row alive mask), collecting the emit slice every ``emit_every``
  steps. One trace at construction shapes; retraces are a bug the
  metrics surface.

Heterogeneous horizons ride the ``remaining`` vector: a request needing
37 more steps and one needing 4,000 share the same window dispatch, and
a finished lane costs (masked) FLOPs but never a recompile. Determinism
contract: a lane's trajectory depends only on its own admitted state —
``step_where``'s select is elementwise along the lane axis and the serve
path contains no cross-lane reduction — so a request's bits are
identical served solo or co-batched (pinned in tests/test_serve.py).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.colony.colony import Colony
from lens_tpu.colony.ensemble import Ensemble
from lens_tpu.emit.log import SEP
from lens_tpu.utils.dicts import flatten_paths, set_path


def _solo_initial_state(
    sim: Any,
    n_agents: Any,
    key: jax.Array,
    overrides: Mapping | None = None,
):
    """``initial_state`` across the three colony forms' signatures.

    The solo construction is the determinism anchor: the state scattered
    into a lane is built exactly as a one-shot run would build it (same
    seed -> same bits), so "served" vs "ran alone" can only differ if
    the window program itself coupled lanes.
    """
    if isinstance(sim, Colony):
        return sim.initial_state(
            int(n_agents), overrides=overrides or None, key=key
        )
    # SpatialColony and MultiSpeciesColony share (n, key, overrides=...);
    # the multi form takes a per-species count mapping.
    if isinstance(n_agents, Mapping):
        n_agents = {k: int(v) for k, v in n_agents.items()}
    else:
        n_agents = int(n_agents)
    return sim.initial_state(n_agents, key, overrides=overrides or None)


def _override_leaves(overrides: Mapping | None):
    """Canonical (path-sorted) override leaves plus the hashable
    STRUCTURE key — ``(path, shape, dtype)`` per leaf — that addresses
    one compiled program. Shared by the solo-builder and fork-admit
    caches so override canonicalization can never diverge between them.
    """
    leaves = sorted(
        (path, jnp.asarray(value))
        for path, value in flatten_paths(overrides or {})
    )
    structure = tuple(
        (path, v.shape, str(v.dtype)) for path, v in leaves
    )
    return leaves, structure


class LanePool:
    """``n_lanes`` independent scenario slots over one resident program.

    Parameters
    ----------
    sim:
        The bucket's steppable (one per composite/shape bucket — every
        request served by this pool shares the compiled shapes).
    n_lanes:
        Lane count L. Throughput scales with occupied lanes; idle lanes
        cost masked compute, so L is a capacity/latency knob, not free.
    window_steps:
        Steps per scheduler tick. Larger windows amortize dispatch and
        host round-trips (better throughput ceiling) but coarsen the
        admission/retire granularity (worse queueing latency).
    timestep:
        Sim seconds per step (must match the sim's own dt constraints,
        e.g. a lattice's diffusion dt).
    emit_every:
        Steps between emitted slices inside the window;
        ``window_steps`` must be a positive multiple.
    device:
        Pin this pool's resident state (and therefore every program
        that consumes it — jit follows committed inputs) to ONE
        device: the mesh-serving placement primitive, one pool per
        shard. Everything entering the pool from elsewhere — a freshly
        built solo state, a snapshot captured on another shard — is
        ``device_put`` onto it at admission, so cross-device failover
        is a transfer, never a tracing difference. ``None`` (default)
        leaves placement to jax: the single-device behavior, bit for
        bit.
    """

    def __init__(
        self,
        sim: Any,
        n_lanes: int,
        window_steps: int = 32,
        timestep: float = 1.0,
        emit_every: int = 1,
        device: Any = None,
    ):
        if n_lanes < 1:
            raise ValueError(f"n_lanes={n_lanes} must be >= 1")
        if window_steps < 1 or emit_every < 1 \
                or window_steps % emit_every != 0:
            raise ValueError(
                f"window_steps ({window_steps}) must be a positive "
                f"multiple of emit_every ({emit_every})"
            )
        self.sim = sim
        self.ensemble = Ensemble(sim, n_lanes)
        # a span tracer (lens_tpu.obs) the owning server installs:
        # first-call compiles of the per-structure admission builders
        # are the serve path's only legitimate mid-flight stalls, and
        # the timeline should show them as compiles, not mystery gaps.
        # None / NullTracer = no emission, zero extra work.
        self.trace: Any = None
        self.n_lanes = int(n_lanes)
        self.window_steps = int(window_steps)
        self.timestep = float(timestep)
        self.emit_every = int(emit_every)
        self.device = device
        self.emits_per_window = self.window_steps // self.emit_every

        # Idle-lane filler: an empty (0 alive) solo state broadcast to
        # every lane. Its contents are never observed — admission
        # overwrites the whole lane, step_where freezes it — it only
        # pins shapes/dtypes for the resident program.
        template = _solo_initial_state(
            sim, self._zero_agents(), jax.random.PRNGKey(0)
        )
        self.states = jax.tree.map(
            lambda x: jnp.broadcast_to(
                x, (self.n_lanes,) + jnp.shape(x)
            ).copy(),
            template,
        )
        self.remaining = jnp.zeros(self.n_lanes, jnp.int32)
        if device is not None:
            # committed inputs route every jitted program below to this
            # device; uncommitted scalars (lane index, step counts)
            # follow the committed operands
            self.states = jax.device_put(self.states, device)
            self.remaining = jax.device_put(self.remaining, device)
        # Host mirror of ``remaining``: admission/retire arithmetic is
        # fully host-predictable (arm H, subtract min(window, left) per
        # window), so the scheduler never reads the device counter —
        # reading it would force a device sync per window, which
        # measurably caps served/ceiling throughput (bench_serve.py).
        # The device array stays authoritative for the in-window mask.
        self.remaining_host = np.zeros(self.n_lanes, np.int64)

        ens, dt = self.ensemble, self.timestep
        emit_every, n_emits = self.emit_every, self.emits_per_window

        def window(states, remaining):
            def emit_block(carry, _):
                st, rem = carry

                def one_step(c, _):
                    st2, rem2 = c
                    active = rem2 > 0
                    st2 = ens.step_where(st2, active, dt)
                    return (st2, rem2 - active.astype(rem2.dtype)), None

                (st, rem), _ = jax.lax.scan(
                    one_step, (st, rem), None, length=emit_every
                )
                return (st, rem), ens.emit_state(st)

            (states, remaining), traj = jax.lax.scan(
                emit_block, (states, remaining), None, length=n_emits
            )
            return states, remaining, traj

        # Donate the lane states on accelerators: the old buffer is dead
        # after the window returns, and the pool is the largest resident
        # allocation. CPU skips donation (XLA:CPU ignores it and warns —
        # same policy as SpatialColony's cached window program).
        donate = jax.default_backend() != "cpu"
        self._window = jax.jit(
            window, donate_argnums=(0,) if donate else ()
        )

        def admit(states, remaining, lane, solo, steps):
            states = jax.tree.map(
                lambda pool, s: pool.at[lane].set(s), states, solo
            )
            return states, remaining.at[lane].set(steps)

        # lane/steps are traced scalars: one compile serves every
        # admission into every lane
        self._admit = jax.jit(
            admit, donate_argnums=(0, 1) if donate else ()
        )
        self._release = jax.jit(
            lambda remaining, lane: remaining.at[lane].set(0),
            donate_argnums=(0,) if donate else (),
        )
        # Device-side lane snapshot (hold_state capture): lane is a
        # traced scalar, so one compile serves every retirement. NOT
        # donated — it reads the same pool the next window consumes.
        self._lane_slice = jax.jit(
            lambda states, lane: jax.tree.map(lambda x: x[lane], states)
        )
        # Jitted solo-state builders, one per (n_agents, override
        # STRUCTURE) — admission's third resident program. The eager
        # op-by-op build cost ~0.8 ms per admission on this box's CPU
        # (dozens of tiny dispatches), which capped sweep throughput:
        # it exceeded the whole 1-lane window wall. Requests sharing an
        # override structure — every trial of a sweep, every plain
        # request — reuse ONE compile; seed and override VALUES ride as
        # traced data, so the built bits are the eager build's bits.
        self._solo_builders: Dict[Any, Any] = {}
        # Jitted fork-admit programs, one per divergent-override
        # STRUCTURE: apply each fork's overrides to a cached prefix
        # snapshot and scatter it into a lane in ONE dispatch (values
        # ride as traced data — every fork of a sweep reuses one
        # compile). See admit_state(overrides=...).
        self._fork_admits: Dict[Any, Any] = {}

        # Per-lane finite check (the check_finite="window" quarantine):
        # AND of isfinite over every inexact leaf's non-lane axes — a
        # [L] bool the server reads one window late off the same
        # device->host path the trajectory already rides, so the check
        # never adds a sync of its own. Compiled lazily (jit) — a
        # server with the check off never traces it.
        def finite(states):
            flags = jnp.ones((self.n_lanes,), bool)
            for leaf in jax.tree.leaves(states):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    flags &= jnp.isfinite(leaf).reshape(
                        self.n_lanes, -1
                    ).all(axis=1)
            return flags

        self._finite = jax.jit(finite)

        # Divergence injector (FaultPlan "nan" faults + tests): set the
        # FIRST inexact leaf's whole slice of one lane to NaN. Lane is
        # a traced scalar — one compile serves every injection. Not
        # donated: used only under fault injection, clarity wins.
        def poison(states, lane):
            leaves, treedef = jax.tree.flatten(states)
            for i, leaf in enumerate(leaves):
                if jnp.issubdtype(leaf.dtype, jnp.inexact):
                    leaves[i] = leaf.at[lane].set(jnp.nan)
                    break
            else:
                raise ValueError(
                    "no inexact state leaf to poison in this sim form"
                )
            return jax.tree.unflatten(treedef, leaves)

        self._poison = jax.jit(poison)

    def _build_solo(self, n_agents, seed: int, overrides: Mapping | None):
        leaves, structure = _override_leaves(overrides)
        na_key = (
            tuple(sorted(n_agents.items()))
            if isinstance(n_agents, Mapping)
            else int(n_agents)
        )
        key = (na_key, structure)
        builder = self._solo_builders.get(key)
        fresh = builder is None
        if fresh:
            paths = [path for path, _ in leaves]

            def build(prng, values):
                tree: Dict = {}
                for path, value in zip(paths, values):
                    tree = set_path(tree, path, value)
                return _solo_initial_state(
                    self.sim, n_agents, prng, overrides=tree or None
                )

            builder = jax.jit(build)
            self._solo_builders[key] = builder
        args = (jax.random.PRNGKey(int(seed)), [v for _, v in leaves])
        if fresh and self.trace:
            # the first call traces + compiles this override
            # structure's builder — a one-off stall worth naming
            with self.trace.span(
                "compile.admit_builder", override_paths=len(leaves)
            ):
                return builder(*args)
        return builder(*args)

    def _zero_agents(self):
        """The 'no live rows' n_agents for this sim form."""
        from lens_tpu.environment.multispecies import MultiSpeciesColony

        if isinstance(self.sim, MultiSpeciesColony):
            return {name: 0 for name in self.sim.species}
        return 0

    # -- eager request validation (submit-time, pre-compile) -----------------

    def _colonies(self) -> Dict[str, Any]:
        """``{species_or_'': Colony}`` — the schema owners of this sim
        form (the multi-species form routes overrides by species key;
        the other two take bare paths)."""
        from lens_tpu.environment.multispecies import MultiSpeciesColony

        if isinstance(self.sim, MultiSpeciesColony):
            return {
                name: sp.colony for name, sp in self.sim.species.items()
            }
        if isinstance(self.sim, Colony):
            return {"": self.sim}
        return {"": self.sim.colony}

    def validate_overrides(
        self, overrides: Mapping | None, what: str = "overrides"
    ) -> None:
        """Submit-time path validation: every override path must name a
        schema variable of this bucket's compartment (per species on
        multi-species buckets). Catches the classic client typo — an
        unknown path — at ``submit`` with a descriptive error instead
        of deep inside the admission build. Value SHAPES are still
        validated at admission (they need the built state)."""
        if not overrides:
            return
        colonies = self._colonies()
        multi = "" not in colonies
        if multi:
            unknown = set(overrides) - set(colonies)
            if unknown:
                raise ValueError(
                    f"{what} name unknown species {sorted(unknown)}; "
                    f"this bucket serves {sorted(colonies)}"
                )
            items = [
                (f"{name}{SEP}", colonies[name], ovr)
                for name, ovr in overrides.items()
            ]
        else:
            items = [("", colonies[""], overrides)]
        for prefix, colony, ovr in items:
            known = colony.compartment.updaters
            for path, value in flatten_paths(ovr or {}):
                if path not in known:
                    raise ValueError(
                        f"{what} path "
                        f"{prefix}{SEP.join(map(str, path))!r} is not "
                        f"a schema variable of this bucket; known "
                        f"paths include "
                        f"{sorted(SEP.join(map(str, p)) for p in known)[:8]}"
                    )
                try:
                    np.asarray(value)
                except Exception as e:
                    raise ValueError(
                        f"{what} value at "
                        f"{prefix}{SEP.join(map(str, path))} is not "
                        f"array-like: {e}"
                    )

    def validate_agents(self, n_agents: Any) -> None:
        """Submit-time n_agents validation against the bucket's
        capacities (``n_agents`` already normalized by
        :meth:`default_agents`)."""
        colonies = self._colonies()
        if "" in colonies:
            cap = colonies[""].capacity
            n = int(n_agents)
            if not 0 <= n <= cap:
                raise ValueError(
                    f"n_agents={n} not in [0, {cap}] (bucket capacity)"
                )
            return
        unknown = set(n_agents) - set(colonies)
        if unknown:
            raise ValueError(
                f"n_agents names unknown species {sorted(unknown)}; "
                f"this bucket serves {sorted(colonies)}"
            )
        for name, colony in colonies.items():
            n = int(n_agents.get(name, 0))
            if not 0 <= n <= colony.capacity:
                raise ValueError(
                    f"n_agents[{name!r}]={n} not in "
                    f"[0, {colony.capacity}] (bucket capacity)"
                )

    def default_agents(self, n: Any = None):
        """Normalize an n_agents default to this sim form: ints fan out
        to every species of a multi-species sim (a bare int would crash
        its per-species ``initial_state``); ``None`` means one agent
        (per species)."""
        zero = self._zero_agents()
        if n is None:
            n = 1
        if isinstance(zero, dict) and not isinstance(n, Mapping):
            return {name: int(n) for name in zero}
        return n

    # -- admission -----------------------------------------------------------

    def admit(
        self,
        lane: int,
        seed: int,
        horizon_steps: int,
        n_agents: Any = None,
        overrides: Mapping | None = None,
    ) -> None:
        """Build a solo initial state (request seed, request overrides)
        and scatter it into ``lane``, arming ``horizon_steps``.

        Raises whatever the sim's own override/count validation raises —
        the scheduler maps that to a FAILED request instead of letting
        one bad request poison the pool.
        """
        if not 0 <= lane < self.n_lanes:
            raise IndexError(f"lane {lane} not in [0, {self.n_lanes})")
        if horizon_steps < 1:
            raise ValueError(
                f"horizon_steps={horizon_steps} must be >= 1"
            )
        n_agents = self.default_agents(n_agents)
        solo = self._build_solo(n_agents, seed, overrides)
        if self.device is not None:
            # the jitted solo build lands uncommitted (default device);
            # a committed pool must not mix devices inside one program
            solo = jax.device_put(solo, self.device)
        self.states, self.remaining = self._admit(
            self.states,
            self.remaining,
            jnp.int32(lane),
            solo,
            jnp.int32(horizon_steps),
        )
        self.remaining_host[lane] = int(horizon_steps)

    def admit_state(
        self, lane: int, state, steps: int, overrides: Mapping | None = None
    ) -> None:
        """Scatter an EXPLICIT solo state into ``lane`` and arm ``steps``.

        The continuation path (``SimServer.resubmit``) and the fork
        path (prefix caching): ``state`` is a lane slice previously
        captured by :meth:`lane_state` / :meth:`lane_state_device` or a
        ``SnapshotStore`` entry, so re-scattering it and stepping
        ``steps`` more is bitwise what a longer original horizon would
        have produced (``step_where`` froze nothing but time in
        between). Reuses the one compiled admit program — the state
        rides as data, same shapes.

        ``overrides`` is the fork point's divergence: schema-variable
        values applied to the snapshot (``sim.apply_overrides`` — same
        validation/broadcast as initial-state overrides) before the
        scatter, fused with it in one jitted program cached per
        override structure. The snapshot argument is never donated —
        the same cached prefix seeds many forks.
        """
        if not 0 <= lane < self.n_lanes:
            raise IndexError(f"lane {lane} not in [0, {self.n_lanes})")
        if steps < 1:
            raise ValueError(f"steps={steps} must be >= 1")
        if self.device is not None:
            # a snapshot may live on another shard's device (prefix
            # forks after failover, rehydrated spills): migrating it is
            # one transfer, and the bits are the bits — device_put is
            # a byte copy, so the determinism contract rides along
            state = jax.device_put(state, self.device)
        if overrides:
            self._fork_admit(lane, state, steps, overrides)
            return
        self.states, self.remaining = self._admit(
            self.states,
            self.remaining,
            jnp.int32(lane),
            state,
            jnp.int32(steps),
        )
        self.remaining_host[lane] = int(steps)

    def _fork_admit(
        self, lane: int, state, steps: int, overrides: Mapping
    ) -> None:
        """Apply divergent overrides to a snapshot and scatter it, one
        cached compile per override structure (values are traced)."""
        leaves, key = _override_leaves(overrides)
        program = self._fork_admits.get(key)
        fresh = program is None
        if fresh:
            paths = [path for path, _ in leaves]
            donate = jax.default_backend() != "cpu"

            def fork(states, remaining, lane, solo, steps, values):
                tree: Dict = {}
                for path, value in zip(paths, values):
                    tree = set_path(tree, path, value)
                solo = self.sim.apply_overrides(solo, tree)
                states = jax.tree.map(
                    lambda pool, s: pool.at[lane].set(s), states, solo
                )
                return states, remaining.at[lane].set(steps)

            program = jax.jit(
                fork, donate_argnums=(0, 1) if donate else ()
            )
            self._fork_admits[key] = program
        args = (
            self.states,
            self.remaining,
            jnp.int32(lane),
            state,
            jnp.int32(steps),
            [v for _, v in leaves],
        )
        if fresh and self.trace:
            with self.trace.span(
                "compile.fork_admit", override_paths=len(leaves)
            ):
                self.states, self.remaining = program(*args)
        else:
            self.states, self.remaining = program(*args)
        self.remaining_host[lane] = int(steps)

    def lane_state_device(self, lane: int):
        """DEVICE-side snapshot of one lane's current state (a
        solo-shaped pytree of device arrays) — no host sync.

        The pipelined hold_state capture: the slice program is
        dispatched before the lane can be reassigned (XLA sequences it
        ahead of the next admit/window on the same buffers), so the
        snapshot holds the lane's exact final bits while the scheduler
        runs ahead. ``admit_state`` accepts the device tree directly,
        so a later ``resubmit`` continues the scenario bitwise without
        the state ever visiting the host; anything that does want host
        bytes (``lane_state``, a client inspecting results) pays the
        transfer then — deferred, off the window critical path.
        """
        if not 0 <= lane < self.n_lanes:
            raise IndexError(f"lane {lane} not in [0, {self.n_lanes})")
        return self._lane_slice(self.states, jnp.int32(lane))

    def lane_state(self, lane: int):
        """Host copy of one lane's current state (a solo-shaped pytree).

        One small transfer (the lane slice, not the pool); the bits are
        exactly what the resident program holds, so
        ``admit_state(lane', lane_state(lane), ...)`` continues the
        scenario bitwise.
        """
        return jax.device_get(self.lane_state_device(lane))

    def release(self, lane: int) -> None:
        """Free a lane before its horizon elapsed (cancel/deadline): zero
        the remaining counter so the next window freezes it. The stale
        state stays in place — frozen, unobserved, overwritten by the
        next admission."""
        if not 0 <= lane < self.n_lanes:
            raise IndexError(f"lane {lane} not in [0, {self.n_lanes})")
        self.remaining = self._release(self.remaining, jnp.int32(lane))
        self.remaining_host[lane] = 0

    # -- stepping ------------------------------------------------------------

    def run_window(self) -> Tuple[np.ndarray, Any]:
        """One resident-program dispatch: every lane advances up to
        ``window_steps`` of ITS OWN remaining steps.

        Returns ``(remaining_before, trajectory)`` where
        ``remaining_before`` is the host MIRROR of the pre-window
        counters (what the scheduler needs to slice each lane's VALID
        emit rows — no device read: the mirror is exact by arithmetic)
        and ``trajectory`` is the device emit stack, leaves
        ``[emits_per_window, n_lanes, ...]``.
        """
        remaining_before = self.remaining_host.copy()
        self.states, self.remaining, traj = self._window(
            self.states, self.remaining
        )
        self.remaining_host = np.maximum(
            remaining_before - self.window_steps, 0
        )
        return remaining_before, traj

    def finite_flags(self) -> Any:
        """DEVICE [n_lanes] bool: lane state is all-finite (every
        inexact leaf). Dispatched by the server right after a window
        when ``check_finite="window"`` is on; the flags ride the same
        async device->host copy as the trajectory, and the scheduler
        reads them at the NEXT tick — one-window detection lag, zero
        added syncs. Free/frozen lanes may legitimately be flagged
        (stale state is never scrubbed) — the server consults flags
        only for lanes occupied at dispatch time."""
        return self._finite(self.states)

    def poison_lane(self, lane: int) -> None:
        """Inject NaN into one lane's state (the first inexact leaf,
        whole lane slice) — the deterministic divergence injector
        behind ``FaultPlan`` ``nan`` faults and the quarantine tests.
        Co-resident lanes are untouched (elementwise lane update), so
        the quarantine pin can require their bits unchanged."""
        if not 0 <= lane < self.n_lanes:
            raise IndexError(f"lane {lane} not in [0, {self.n_lanes})")
        self.states = self._poison(self.states, jnp.int32(lane))

    def retraces(self) -> int:
        """Compiles of the window program beyond the expected one — the
        serving-layer regression the metrics export watches."""
        size = getattr(self._window, "_cache_size", None)
        if size is None:
            return 0
        return max(int(size()) - 1, 0)

    def valid_emits(self, remaining_before: int) -> int:
        """How many of this window's emit rows a lane with
        ``remaining_before`` steps left actually produced (rows past its
        horizon are frozen state — dropped host-side)."""
        steps_run = min(int(remaining_before), self.window_steps)
        return steps_run // self.emit_every
