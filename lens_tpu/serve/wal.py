"""The serve write-ahead log: a SIGKILL'd server loses no admitted work.

Before round 12 the scenario server's only durable output was the
per-request ``.lens`` result logs — a killed server forgot every
admitted-but-unfinished request, every held snapshot, and every
resubmit chain. This module is the sweep ledger's discipline
(append-only framed JSON events, replay at open — the same
:class:`~lens_tpu.emit.log.JsonFrameLog` framing) applied to serving:

- every client ``submit``/``resubmit`` is one event, written (and
  flushed to the OS) before the request id is returned;
- every terminal status is a ``retire`` event; a ``streamed`` event
  marks the moment the request's records are DURABLY down (sink closed
  and flushed) — the distinction that makes recovery honest under the
  pipeline, where status flips DONE while sink appends are still in
  flight;
- a ``hold_state`` retirement spills the pinned snapshot via the
  checkpoint rename protocol (:func:`lens_tpu.checkpoint.save_tree`)
  and records a ``hold`` event, so a recovered server can re-pin the
  exact bits and serve ``resubmit`` continuations from them.

Recovery (``SimServer(recover_dir=...)``) is replay: finished requests
(retire + streamed for DONE) materialize as terminal tickets pointing
at their existing result logs; everything else is RE-RUN FROM ITS
EXACT INPUTS — the serving determinism contract (a request's bits are
a pure function of its request) turns "re-run" into "bitwise resume",
so a recovered run's outputs equal an uninterrupted run's byte for
byte (pinned in tests/test_recovery.py, SIGKILL at every named
kill-point).

Durability policy: appends flush to the OS immediately (a SIGKILL'd
process loses nothing appended), while fsync is GROUP COMMIT — the
scheduler syncs once per tick before acting on the queue, and appends
are sequential so every sync makes a clean prefix durable. The framing
tolerates a torn tail frame exactly like the sweep ledger.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence

from lens_tpu.emit.log import JsonFrameLog

WAL_NAME = "serve.wal"
SPILL_DIR = "snapshots"

_SHARD_WAL_RE = re.compile(r"^serve-shard(\d+)\.wal$")

#: Event vocabulary (replay ignores unknown events, so old readers
#: tolerate newer WALs — the ledger's forward-compat posture).
BEGIN = "server_begin"   # {fingerprint, buckets}
SUBMIT = "submit"        # {rid, request}
RESUBMIT = "resubmit"    # {rid, parent, extra_horizon}
RETIRE = "retire"        # {rid, status, error, steps}
STREAMED = "streamed"    # {rid} records durably on disk
HOLD = "hold"            # {rid, key, name} held snapshot spilled
RELEASE = "release"      # {rid} hold dropped
QUARANTINE = "device_quarantined"  # {shard, reason} observability only
#: {rid, leader}: the request coalesced onto an identical in-flight
#: leader's lane (round-18 suffix dedup). Observability/audit only —
#: recovery does NOT replay attachments from it: re-running the
#: recovered SUBMITs through the same deterministic coalescing logic
#: re-forms (or re-runs) each group from the requests themselves, so
#: the event can never disagree with what recovery actually does.
COALESCE = "coalesced"


def classify_events(events: Sequence[Mapping[str, Any]]):
    """Fold a merged WAL event stream into the per-request facts
    recovery acts on: ``(order, recs, retired, streamed, holds,
    released)`` where ``order`` is submission order, ``recs`` maps rid
    -> its submit/resubmit event, ``retired`` maps rid -> its LAST
    retire event (quarantine may flip DONE post hoc), ``streamed`` is
    the set of rids whose records are attested durably on disk, and
    ``holds``/``released`` track spilled snapshots. Shared by
    ``SimServer`` construction-time recovery, cluster whole-host
    failover (a SURVIVOR adopting a dead host's WAL — docs/serving.md,
    "Cluster serving"), and the ``python -m lens_tpu wal`` dump.
    Unknown events are ignored (forward compat)."""
    recs: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    retired: Dict[str, Dict[str, Any]] = {}
    streamed: set = set()
    holds: Dict[str, Dict[str, Any]] = {}
    released: set = set()
    for ev in events:
        kind = ev.get("event")
        rid = ev.get("rid")
        if kind in (SUBMIT, RESUBMIT):
            if rid not in recs:
                order.append(rid)
            recs[rid] = dict(ev)
        elif kind == RETIRE:
            retired[rid] = dict(ev)
        elif kind == STREAMED:
            streamed.add(rid)
        elif kind == HOLD:
            holds[rid] = dict(ev)
        elif kind == RELEASE:
            released.add(rid)
    return order, recs, retired, streamed, holds, released


def unfinished(
    order: Sequence[str],
    retired: Mapping[str, Mapping[str, Any]],
    streamed,
) -> List[str]:
    """The rids a recovery/failover must RE-RUN: no terminal retire, or
    a DONE retire whose records were never attested durable (under the
    pipeline, status runs ahead of the sink)."""
    out = []
    for rid in order:
        fin = retired.get(rid)
        if fin is None or (
            fin.get("status") == "done" and rid not in streamed
        ):
            out.append(rid)
    return out


def read_events(path: str) -> List[Dict[str, Any]]:
    """The merged event stream of a WAL directory (or its head
    ``serve.wal`` file) WITHOUT arming it for appends — the read-only
    entry point for cluster failover and the ``wal`` dump CLI. The
    directory's per-shard files are merged on the global ``seq`` stamp
    exactly like :attr:`ServeWal.events`."""
    if os.path.isdir(path):
        path = os.path.join(path, WAL_NAME)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no WAL at {path}")
    wal = ServeWal(path)
    try:
        return wal.events
    finally:
        wal.close()


def shard_wal_name(shard: int) -> str:
    """Per-shard WAL file name. Shard 0 keeps the historical
    ``serve.wal`` name, so every pre-mesh recover_dir is a valid
    1-shard mesh WAL and vice versa."""
    return WAL_NAME if shard == 0 else f"serve-shard{shard:02d}.wal"


def buckets_fingerprint(buckets: Mapping[str, Mapping[str, Any]]) -> str:
    """sha256 over the BITS-RELEVANT bucket configuration. Scheduling
    knobs (lanes, window, queue depth) are deliberately absent — the
    co-batching determinism contract makes results independent of
    them, so a recovered server may legally resize its pool. Anything
    that changes what a request computes (composite, config, capacity,
    agent defaults, timestep, emit cadence) is in."""
    canon = {
        name: {
            "composite": cfg.get("composite") or name,
            "config": cfg.get("config") or {},
            "capacity": cfg.get("capacity"),
            "n_agents": cfg.get("n_agents"),
            "division": cfg.get("division", True),
            "timestep": float(cfg.get("timestep", 1.0)),
            "emit_every": int(cfg.get("emit_every", 1)),
        }
        for name, cfg in buckets.items()
    }
    blob = json.dumps(canon, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def key_to_json(key: Any) -> Any:
    """A SnapshotStore key (nested tuples of str/int) as JSON."""
    if isinstance(key, tuple):
        return [key_to_json(k) for k in key]
    return key


def key_from_json(key: Any) -> Any:
    """Inverse of :func:`key_to_json` (lists back to tuples, exactly —
    the store addresses by tuple equality)."""
    if isinstance(key, list):
        return tuple(key_from_json(k) for k in key)
    return key


def spill_name(key: Any) -> str:
    """Deterministic spill-directory name for a snapshot key — stable
    across a re-run of the same request, so a crash between spill and
    WAL append is healed by the next spill simply overwriting it."""
    digest = hashlib.sha256(repr(key).encode()).hexdigest()[:16]
    return f"snap_{digest}"


class ServeWal:
    """One server's write-ahead log, ONE framed-JSON file PER SHARD
    (thread-safe: ``streamed`` events land from the stream thread
    while the scheduler appends).

    Mesh discipline (round 13): a multi-device server's durability
    must not funnel every shard's retire/streamed/hold traffic through
    one file — per-shard logs keep the write path independent per
    failure domain (on a real multi-host mesh each host fsyncs its
    own log), and a torn tail on ONE shard's file loses only that
    shard's last event. What makes the split safe is the **merge
    protocol**: every append is stamped with a global monotonically
    increasing ``seq`` drawn under one lock, so ``events`` — and
    therefore recovery — is the TOTAL ORDER the scheduler actually
    produced, reconstructed by merging all shard files on ``seq``.
    Replaying the merged stream is byte-equal to replaying a single
    WAL holding the same appends (pinned in tests/test_mesh_serve.py).
    Legacy single-file WALs (pre-seq events) sort before all stamped
    events in file order, so old recover_dirs replay unchanged.

    ``events`` is the merged replayed history; :meth:`begin` pins (or,
    on replayed files, verifies) the bucket fingerprint per shard file
    — recovering with buckets that would compute different bits is
    refused instead of silently serving a different simulation under
    old request ids. A server may legally reopen with a different
    shard count (scheduling knobs are outside the fingerprint): extra
    existing shard files are still read and merged; appends for
    shards this server does not have route to shard 0.
    """

    def __init__(self, path: str, n_shards: int = 1):
        if n_shards < 1:
            raise ValueError(f"n_shards={n_shards} must be >= 1")
        self.path = path
        self.n_shards = int(n_shards)
        self._dir = os.path.dirname(path) or "."
        self._lock = threading.Lock()
        self._dirty: set = set()
        # every shard this server writes, plus any shard file a
        # previous (wider) incarnation left behind — recovery must
        # merge ALL of them or silently forget that shard's retires
        shards = set(range(self.n_shards))
        for p in glob.glob(os.path.join(self._dir, "serve-shard*.wal")):
            m = _SHARD_WAL_RE.match(os.path.basename(p))
            if m:
                shards.add(int(m.group(1)))
        self._logs: Dict[int, JsonFrameLog] = {
            k: JsonFrameLog(
                os.path.join(self._dir, shard_wal_name(k))
                if k else path,
                fsync_every=False,
            )
            for k in sorted(shards)
        }
        self._seq = 1 + max(
            (
                int(e["seq"])
                for log in self._logs.values()
                for e in log.events
                if "seq" in e
            ),
            default=-1,
        )

    @property
    def events(self) -> List[Dict[str, Any]]:
        """All shards' events merged into the total append order:
        sorted by the global ``seq`` stamp; pre-seq (legacy) events
        keep their file order ahead of every stamped one."""
        merged = []
        for shard, log in sorted(self._logs.items()):
            for pos, e in enumerate(log.events):
                merged.append((int(e.get("seq", -1)), shard, pos, e))
        merged.sort(key=lambda t: t[:3])
        return [e for *_, e in merged]

    def replayed(self) -> bool:
        """True when any shard file held events before this open — the
        server must run recovery before serving."""
        return any(
            e.get("event") != BEGIN
            for log in self._logs.values()
            for e in log.events
        )

    def begin(
        self, fingerprint: str, buckets: Mapping[str, Any]
    ) -> None:
        with self._lock:
            for shard, log in self._logs.items():
                had = False
                for e in log.events:
                    if e.get("event") == BEGIN:
                        had = True
                        if e.get("fingerprint") != fingerprint:
                            raise ValueError(
                                f"{log.path} belongs to a server with "
                                f"bucket fingerprint "
                                f"{e.get('fingerprint')!r}, not "
                                f"{fingerprint!r} — the bucket "
                                f"configuration changed in a "
                                f"bits-relevant way; recovery under "
                                f"old request ids would serve a "
                                f"different simulation. Use a fresh "
                                f"recover_dir (or restore the "
                                f"original buckets)."
                            )
                if not had:
                    self._append_locked(
                        {
                            "event": BEGIN,
                            "fingerprint": fingerprint,
                            "shard": shard,
                            "buckets": {
                                k: dict(v) for k, v in buckets.items()
                            },
                        },
                        shard,
                    )

    def _append_locked(
        self, event: Mapping[str, Any], shard: int
    ) -> None:
        # dict.get + explicit None test: JsonFrameLog has __len__, so
        # an EMPTY shard log is falsy — an `or` fallback would
        # silently misroute its first event to shard 0
        log = self._logs.get(int(shard))
        if log is None:
            log = self._logs[0]
        stamped = dict(event)
        stamped["seq"] = self._seq
        self._seq += 1
        log.append(stamped)
        self._dirty.add(id(log))

    def append(
        self, event: Mapping[str, Any], shard: int = 0
    ) -> None:
        """Append one event to ``shard``'s log (events about a request
        land on the shard that ran it; submit-side events land on
        shard 0): seq-stamped under the lock, framed + flushed to the
        OS (SIGKILL-safe) now, fsynced at the next :meth:`sync` (group
        commit)."""
        with self._lock:
            self._append_locked(event, shard)

    def sync(self) -> None:
        """Group commit: fsync every shard file with appends since the
        last sync (the scheduler calls this once per tick, before
        acting on the queue; untouched shards skip the syscall)."""
        with self._lock:
            for log in self._logs.values():
                if id(log) in self._dirty:
                    log.sync()
            self._dirty.clear()

    def close(self) -> None:
        with self._lock:
            for log in self._logs.values():
                log.sync()
                log.close()
            self._dirty.clear()
