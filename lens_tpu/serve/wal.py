"""The serve write-ahead log: a SIGKILL'd server loses no admitted work.

Before round 12 the scenario server's only durable output was the
per-request ``.lens`` result logs — a killed server forgot every
admitted-but-unfinished request, every held snapshot, and every
resubmit chain. This module is the sweep ledger's discipline
(append-only framed JSON events, replay at open — the same
:class:`~lens_tpu.emit.log.JsonFrameLog` framing) applied to serving:

- every client ``submit``/``resubmit`` is one event, written (and
  flushed to the OS) before the request id is returned;
- every terminal status is a ``retire`` event; a ``streamed`` event
  marks the moment the request's records are DURABLY down (sink closed
  and flushed) — the distinction that makes recovery honest under the
  pipeline, where status flips DONE while sink appends are still in
  flight;
- a ``hold_state`` retirement spills the pinned snapshot via the
  checkpoint rename protocol (:func:`lens_tpu.checkpoint.save_tree`)
  and records a ``hold`` event, so a recovered server can re-pin the
  exact bits and serve ``resubmit`` continuations from them.

Recovery (``SimServer(recover_dir=...)``) is replay: finished requests
(retire + streamed for DONE) materialize as terminal tickets pointing
at their existing result logs; everything else is RE-RUN FROM ITS
EXACT INPUTS — the serving determinism contract (a request's bits are
a pure function of its request) turns "re-run" into "bitwise resume",
so a recovered run's outputs equal an uninterrupted run's byte for
byte (pinned in tests/test_recovery.py, SIGKILL at every named
kill-point).

Durability policy: appends flush to the OS immediately (a SIGKILL'd
process loses nothing appended), while fsync is GROUP COMMIT — the
scheduler syncs once per tick before acting on the queue, and appends
are sequential so every sync makes a clean prefix durable. The framing
tolerates a torn tail frame exactly like the sweep ledger.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, List, Mapping, Optional

from lens_tpu.emit.log import JsonFrameLog

WAL_NAME = "serve.wal"
SPILL_DIR = "snapshots"

#: Event vocabulary (replay ignores unknown events, so old readers
#: tolerate newer WALs — the ledger's forward-compat posture).
BEGIN = "server_begin"   # {fingerprint, buckets}
SUBMIT = "submit"        # {rid, request}
RESUBMIT = "resubmit"    # {rid, parent, extra_horizon}
RETIRE = "retire"        # {rid, status, error, steps}
STREAMED = "streamed"    # {rid} records durably on disk
HOLD = "hold"            # {rid, key, name} held snapshot spilled
RELEASE = "release"      # {rid} hold dropped


def buckets_fingerprint(buckets: Mapping[str, Mapping[str, Any]]) -> str:
    """sha256 over the BITS-RELEVANT bucket configuration. Scheduling
    knobs (lanes, window, queue depth) are deliberately absent — the
    co-batching determinism contract makes results independent of
    them, so a recovered server may legally resize its pool. Anything
    that changes what a request computes (composite, config, capacity,
    agent defaults, timestep, emit cadence) is in."""
    canon = {
        name: {
            "composite": cfg.get("composite") or name,
            "config": cfg.get("config") or {},
            "capacity": cfg.get("capacity"),
            "n_agents": cfg.get("n_agents"),
            "division": cfg.get("division", True),
            "timestep": float(cfg.get("timestep", 1.0)),
            "emit_every": int(cfg.get("emit_every", 1)),
        }
        for name, cfg in buckets.items()
    }
    blob = json.dumps(canon, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def key_to_json(key: Any) -> Any:
    """A SnapshotStore key (nested tuples of str/int) as JSON."""
    if isinstance(key, tuple):
        return [key_to_json(k) for k in key]
    return key


def key_from_json(key: Any) -> Any:
    """Inverse of :func:`key_to_json` (lists back to tuples, exactly —
    the store addresses by tuple equality)."""
    if isinstance(key, list):
        return tuple(key_from_json(k) for k in key)
    return key


def spill_name(key: Any) -> str:
    """Deterministic spill-directory name for a snapshot key — stable
    across a re-run of the same request, so a crash between spill and
    WAL append is healed by the next spill simply overwriting it."""
    digest = hashlib.sha256(repr(key).encode()).hexdigest()[:16]
    return f"snap_{digest}"


class ServeWal:
    """One server's write-ahead log (thread-safe: ``streamed`` events
    land from the stream thread while the scheduler appends).

    ``events`` is the replayed history; :meth:`begin` pins (or, on a
    replayed file, verifies) the bucket fingerprint — recovering with
    buckets that would compute different bits is refused instead of
    silently serving a different simulation under old request ids.
    """

    def __init__(self, path: str):
        self._log = JsonFrameLog(path, fsync_every=False)
        self._lock = threading.Lock()
        self._dirty = False
        self.path = path

    @property
    def events(self) -> List[Dict[str, Any]]:
        return self._log.events

    def replayed(self) -> bool:
        """True when the file held events before this open — the
        server must run recovery before serving."""
        return any(e.get("event") != BEGIN for e in self._log.events)

    def begin(
        self, fingerprint: str, buckets: Mapping[str, Any]
    ) -> None:
        for e in self._log.events:
            if e.get("event") == BEGIN:
                if e.get("fingerprint") != fingerprint:
                    raise ValueError(
                        f"{self.path} belongs to a server with bucket "
                        f"fingerprint {e.get('fingerprint')!r}, not "
                        f"{fingerprint!r} — the bucket configuration "
                        f"changed in a bits-relevant way; recovery "
                        f"under old request ids would serve a "
                        f"different simulation. Use a fresh "
                        f"recover_dir (or restore the original "
                        f"buckets)."
                    )
                return
        self.append({
            "event": BEGIN,
            "fingerprint": fingerprint,
            "buckets": {k: dict(v) for k, v in buckets.items()},
        })

    def append(self, event: Mapping[str, Any]) -> None:
        """Append one event: framed + flushed to the OS (SIGKILL-safe)
        now, fsynced at the next :meth:`sync` (group commit)."""
        with self._lock:
            self._log.append(event)
            self._dirty = True

    def sync(self) -> None:
        """Group commit: fsync every append so far (the scheduler
        calls this once per tick, before acting on the queue; a tick
        with nothing appended skips the syscall)."""
        with self._lock:
            if self._dirty:
                self._log.sync()
                self._dirty = False

    def close(self) -> None:
        with self._lock:
            self._log.sync()
            self._log.close()
